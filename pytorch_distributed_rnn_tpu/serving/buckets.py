"""Prompt-length bucket policy.

A jitted prefill traces once per distinct input shape, so serving pads
every prompt up to one of a FIXED set of lengths: after warm-up the jit
cache holds exactly ``len(prompt_buckets)`` prefill programs and the
decode step's single program, and no request mix can trigger another
trace (the zero-retrace contract the engine asserts and the PD104
retrace-hazard rule guards statically).

Pure Python/numpy - unit-testable without jax.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

DEFAULT_PROMPT_BUCKETS = (16, 32, 64, 128)

# the id every padded prompt position carries; any in-vocab id works
# (masked prefill never lets pad positions touch the decode state) but a
# fixed one keeps padded buffers reproducible across runs
PAD_TOKEN = 0


@dataclass(frozen=True)
class BucketSpec:
    """A sorted set of prompt-length buckets."""

    prompt_buckets: tuple[int, ...] = DEFAULT_PROMPT_BUCKETS

    def __post_init__(self):
        buckets = tuple(self.prompt_buckets)
        if not buckets:
            raise ValueError("at least one prompt bucket is required")
        if any(b < 1 for b in buckets):
            raise ValueError(f"bucket lengths must be >= 1: {buckets}")
        if sorted(set(buckets)) != list(buckets):
            raise ValueError(
                f"prompt buckets must be strictly increasing: {buckets}"
            )

    @classmethod
    def parse(cls, spec: str) -> "BucketSpec":
        """``"16,32,64"`` -> BucketSpec((16, 32, 64))."""
        try:
            buckets = tuple(
                int(part) for part in str(spec).split(",") if part.strip()
            )
        except ValueError as exc:
            raise ValueError(f"bad bucket spec {spec!r}: {exc}") from exc
        return cls(buckets)

    @property
    def max_prompt_len(self) -> int:
        return self.prompt_buckets[-1]

    def bucket_for(self, length: int) -> int:
        """The smallest bucket holding ``length`` prompt tokens; raises
        for empty prompts and prompts past the largest bucket (admission
        rejects those loudly instead of silently truncating)."""
        if length < 1:
            raise ValueError("prompts must hold at least one token")
        for bucket in self.prompt_buckets:
            if length <= bucket:
                return bucket
        raise ValueError(
            f"prompt of {length} tokens exceeds the largest bucket "
            f"{self.max_prompt_len}"
        )

    def pad(self, prompt) -> np.ndarray:
        """``prompt`` (list/array of ids) -> (1, bucket) int32 padded
        with :data:`PAD_TOKEN`."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        bucket = self.bucket_for(len(prompt))
        out = np.full((1, bucket), PAD_TOKEN, np.int32)
        out[0, : len(prompt)] = prompt
        return out
