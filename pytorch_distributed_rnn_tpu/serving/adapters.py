"""Per-family prefill / decode-step programs for the serving engine.

An adapter binds one model family to the engine's two jitted programs:

- ``prefill(model_params, prompt (1, L), length (1,))`` consumes one
  request's BUCKET-PADDED prompt and returns ``(seq_state, logits)``
  with leading dim 1 - the per-sequence decode state the engine splices
  into a batch slot;
- ``step(model_params, state, tok (B,), pos (B,))`` advances every slot
  one token and returns ``(state, logits (B, vocab))``.

Every adapter reuses the family's reference-decode math (the module
functions its ``generate`` is built from), so a request decoded inside
a continuous batch reproduces its single-request ``generate`` output
exactly - the parity contract ``tests/test_serving.py`` pins per
family.

Prompt padding never leaks into decode state: the RNN families run a
MASKED prefill scan (carries update only while ``t < length``), and the
attention family's padded KV-cache columns are ``-inf``-masked until
each is overwritten by a real decoded token.  Masking - not exact-length
tracing - is what lets one jitted prefill per bucket serve every prompt
length, the zero-retrace property the engine asserts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from pytorch_distributed_rnn_tpu.models.attention_lm import (
    AttentionLM,
    attention_decode_step,
    attention_prefill,
)
from pytorch_distributed_rnn_tpu.models.char_rnn import CharRNN
from pytorch_distributed_rnn_tpu.models.moe_lm import MoELM, moe_lm_decode_tail
from pytorch_distributed_rnn_tpu.ops.rnn import (
    head_logits,
    stacked_rnn_decode_step,
)


def masked_rnn_prefill(layers, embeds, length, cell: str):
    """Stacked-RNN prefill over a padded prompt.

    ``embeds``: (B, L, in) token embeddings, ``length``: (B,) int32 true
    prompt lengths.  Scans single-token decode steps over the padded
    extent; carries merge only while ``t < length``, and the top-layer
    hidden at ``t == length - 1`` is captured as the last-step features.
    Numerically identical to ``stacked_rnn`` over the exact-length
    prompt (the per-timestep projection slices are the same matmul
    rows), which the parity tests pin.
    Returns ``(carries, last_h (B, H))``.
    """
    batch = embeds.shape[0]
    hidden = layers[0]["w_hh"].shape[1]

    def zero_carry(_layer):
        h = jnp.zeros((batch, hidden), jnp.float32)
        return (h, h) if cell == "lstm" else h

    carries0 = [zero_carry(layer) for layer in layers]
    last_h0 = jnp.zeros((batch, hidden), jnp.float32)

    def step(carry, x_t):
        carries, last_h, t = carry
        new_carries, h_top = stacked_rnn_decode_step(
            layers, carries, x_t, cell
        )
        keep = (t < length)[:, None]  # (B, 1) broadcasts over hidden
        carries = jax.tree.map(
            lambda new, old: jnp.where(keep, new, old), new_carries, carries
        )
        last_h = jnp.where((t == length - 1)[:, None], h_top, last_h)
        return (carries, last_h, t + 1), None

    (carries, last_h, _), _ = lax.scan(
        step, (carries0, last_h0, jnp.int32(0)),
        jnp.swapaxes(embeds, 0, 1),
    )
    return carries, last_h


def _rnn_state_template(layers, batch: int, hidden: int, cell: str):
    """Blank stacked-RNN decode state.  Every leaf is a DISTINCT zeros
    array: the engine donates the state tree into its jitted step, and
    aliased leaves would be the same buffer donated twice."""

    def carry():
        if cell == "lstm":
            return (jnp.zeros((batch, hidden), jnp.float32),
                    jnp.zeros((batch, hidden), jnp.float32))
        return jnp.zeros((batch, hidden), jnp.float32)

    return {"carries": [carry() for _ in layers]}


class CharRNNAdapter:
    """CharRNN: decode state = the stacked cells' carries."""

    family = "char"

    def __init__(self, model: CharRNN):
        self.model = model
        self.vocab_size = model.vocab_size
        self.max_context = None  # recurrent state: no positional bound

    def state_template(self, model_params, batch: int):
        return _rnn_state_template(
            model_params["rnn"], batch, self.model.hidden_dim,
            self.model.cell,
        )

    def prefill(self, model_params, prompt, length):
        embeds = model_params["embed"][prompt]
        carries, last_h = masked_rnn_prefill(
            model_params["rnn"], embeds, length, self.model.cell
        )
        return {"carries": carries}, head_logits(
            model_params["head"], last_h)

    def step(self, model_params, state, tok, pos):
        carries, h_top = stacked_rnn_decode_step(
            model_params["rnn"], state["carries"],
            model_params["embed"][tok], self.model.cell,
        )
        return {"carries": carries}, head_logits(
            model_params["head"], h_top)


class MoELMAdapter:
    """MoELM: CharRNN-shaped carries, MoE-FFN + head decode tail."""

    family = "moe"

    def __init__(self, model: MoELM):
        self.model = model
        self.vocab_size = model.vocab_size
        self.max_context = None

    def state_template(self, model_params, batch: int):
        return _rnn_state_template(
            model_params["rnn"], batch, self.model.hidden_dim,
            self.model.cell,
        )

    def prefill(self, model_params, prompt, length):
        embeds = model_params["embed"][prompt]
        carries, last_h = masked_rnn_prefill(
            model_params["rnn"], embeds, length, self.model.cell
        )
        logits = moe_lm_decode_tail(
            model_params, last_h, self.model.num_selected
        )
        return {"carries": carries}, logits

    def step(self, model_params, state, tok, pos):
        carries, h_top = stacked_rnn_decode_step(
            model_params["rnn"], state["carries"],
            model_params["embed"][tok], self.model.cell,
        )
        logits = moe_lm_decode_tail(
            model_params, h_top, self.model.num_selected
        )
        return {"carries": carries}, logits


class AttentionLMAdapter:
    """AttentionLM: decode state = fixed-capacity KV caches; the model's
    ``max_len`` bounds prompt + generated tokens per request."""

    family = "attention"

    def __init__(self, model: AttentionLM):
        self.model = model
        self.vocab_size = model.vocab_size
        self.max_context = model.max_len
        self.cache_len = model.max_len

    def state_template(self, model_params, batch: int):
        depth = self.model.depth
        heads = self.model.num_heads
        hd = self.model.dim // heads
        shape = (batch, depth, heads, self.cache_len, hd)
        return {
            "k": jnp.zeros(shape, jnp.float32),
            "v": jnp.zeros(shape, jnp.float32),
        }

    def prefill(self, model_params, prompt, length):
        k_cache, v_cache, logits_all = attention_prefill(
            model_params, prompt, self.model.num_heads,
            cache_len=self.cache_len,
        )
        # the true prompt's last-step logits (padded rows are causal
        # garbage); per-row dynamic index so every bucket traces once
        logits = jax.vmap(lambda row, i: row[i])(logits_all, length - 1)
        return {"k": k_cache, "v": v_cache}, logits

    def step(self, model_params, state, tok, pos):
        k_cache, v_cache, logits = attention_decode_step(
            model_params, state["k"], state["v"], pos, tok,
            self.model.num_heads,
        )
        return {"k": k_cache, "v": v_cache}, logits


def adapter_for(model):
    """The adapter matching ``model``'s family (loud on unknowns)."""
    if isinstance(model, CharRNN):
        return CharRNNAdapter(model)
    if isinstance(model, MoELM):
        return MoELMAdapter(model)
    if isinstance(model, AttentionLM):
        return AttentionLMAdapter(model)
    raise TypeError(
        f"no serving adapter for {type(model).__name__} - servable "
        "families: CharRNN, AttentionLM, MoELM"
    )
