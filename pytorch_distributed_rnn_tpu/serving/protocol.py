"""JSON-lines wire protocol for the serving TCP endpoint.

One JSON object per ``\\n``-terminated line in both directions - the
same framing idiom as the launcher/param-server control plane, chosen
over a binary header because serving payloads are token id lists, not
flat gradient vectors.  Requests carry an ``op``; responses echo the
request ``id`` and carry an ``event``:

Client -> server::

    {"op": "generate", "id": "r1", "prompt": [7, 12, 3],
     "max_new_tokens": 16, "temperature": 0.8, "seed": 7,
     "stream": true}
    {"op": "generate", "text": "To be, or", ...}   # byte-vocab models
    {"op": "generate", "priority": "low", "deadline_ms": 2000, ...}
    {"op": "ping"}
    {"op": "stats"}

``priority`` (``high`` | ``normal`` | ``low``) and ``deadline_ms`` are
the fleet-router QoS fields (``serving/fleet/router.py``): the router
sheds low priority first past its admission budget and bounds each
request's dispatch + retries by its deadline.  A bare ``pdrnn-serve``
ignores both - single-replica requests keep their exact old behavior.

``trace`` is the OPTIONAL distributed-tracing context
(``obs/tracectx.py``)::

    {"op": "generate", "trace": {"id": "9f2c...", "span": "51ab...",
     "parent": "03de...", "qos": "high"}, ...}

``id`` names the whole request's trace, ``span`` the sender's span,
``parent`` its cause; remaining keys are QoS baggage.  Every hop that
forwards a traced request re-mints ``span`` (router dispatch attempts
each get their own), and receivers that don't trace simply ignore the
field.  Untraced requests carry NO ``trace`` key at all - the wire
bytes of an untraced request are pinned byte-identical to the
pre-tracing protocol.

Server -> client::

    {"id": "r1", "event": "token", "index": 0, "token": 42}   # stream
    {"id": "r1", "event": "done", "status": "done",
     "tokens": [...], "token_count": 16, "latency_ms": ...,
     "ttft_ms": ..., "queue_ms": ..., "seed": 7}
    {"id": "r1", "event": "error", "error": "...", "shed": true}
    {"event": "pong", "model": "char", "vocab_size": 256, ...}
    {"event": "stats", ...engine stats...}

:class:`ServingClient` is the blocking one-request-at-a-time client the
load generator and the tests build on (concurrency = many clients, the
server multiplexes slots across connections).
"""

from __future__ import annotations

import itertools
import json
import os
import socket
import time

# The serve wire contract - the PD401 registry (lint/lifecycle.py):
# every op below must name a `handles` dispatch site, every `request`
# site must pair with a `reply` site, and optional wire fields are
# declared with `field` so the registry stays the single source of
# truth for what rides the protocol.
# protocol: serve op generate
# protocol: serve op ping
# protocol: serve op stats
# protocol: serve field trace


def encode_line(obj: dict) -> bytes:
    return (json.dumps(obj) + "\n").encode()


def decode_line(line: str) -> dict:
    obj = json.loads(line)
    if not isinstance(obj, dict):
        raise ValueError(f"protocol messages are JSON objects, got {obj!r}")
    return obj


def text_to_tokens(text: str) -> list[int]:
    """UTF-8 bytes as token ids - the byte-vocab (>= 256) convention the
    char family trains with (``data/text.py``)."""
    return list(text.encode("utf-8"))


def tokens_to_text(tokens: list[int]) -> str:
    """Best-effort text rendering of byte tokens (lossless for ids
    < 256 via latin-1; serving never round-trips through this)."""
    return bytes(t & 0xFF for t in tokens).decode("latin-1")


def build_generate_request(prompt=None, *, text: str | None = None,
                           request_id: str = "0",
                           max_new_tokens: int = 16,
                           temperature: float = 0.0,
                           seed: int | None = None, stream: bool = False,
                           priority: str | None = None,
                           deadline_ms: float | None = None,
                           trace=None) -> dict:
    """The exact ``generate`` request object a client puts on the wire.

    Factored out of :meth:`ServingClient.generate` so tests can pin the
    untraced wire bytes: with ``trace=None`` the returned dict carries
    no ``trace`` key and is byte-identical to the pre-tracing protocol.
    ``trace`` is a :class:`~..obs.tracectx.TraceContext` (duck-typed:
    anything with ``to_wire()``)."""
    req: dict = {
        "op": "generate", "id": request_id,
        "max_new_tokens": int(max_new_tokens),
        "temperature": float(temperature), "stream": bool(stream),
    }
    if text is not None:
        req["text"] = text
    else:
        req["prompt"] = [int(t) for t in (prompt or [])]
    if seed is not None:
        req["seed"] = int(seed)
    if priority is not None:
        req["priority"] = str(priority)
    if deadline_ms is not None:
        req["deadline_ms"] = float(deadline_ms)
    if trace is not None:
        req["trace"] = trace.to_wire()  # protocol: serve field trace
    return req


class ProtocolError(RuntimeError):
    """The peer sent something outside the protocol."""


class ServingClient:
    """Blocking JSONL client: one in-flight request per connection.

    ``timeout_s`` bounds each individual socket read; ``connect_timeout_s``
    (default: ``timeout_s``) bounds the dial separately, so a vanished
    or wedged target fails the CONNECT in seconds instead of holding a
    whole request timeout.  Per-request wall deadlines are the
    ``deadline_s`` argument of :meth:`generate` - a per-read timeout
    alone never bounds a stream that keeps dribbling tokens."""

    def __init__(self, host: str, port: int, timeout_s: float = 60.0,
                 connect_timeout_s: float | None = None):
        self.sock = socket.create_connection(
            (host, port),
            timeout=timeout_s if connect_timeout_s is None
            else connect_timeout_s,
        )
        try:
            self.sock.settimeout(timeout_s)
            self.timeout_s = float(timeout_s)
            self._rfile = self.sock.makefile("r", encoding="utf-8")
        except Exception:
            self.sock.close()
            raise
        # per-client unique request-id minting: a random prefix keeps
        # ids from CONCURRENT clients of one server distinct, the
        # counter keeps a single client's requests distinct
        self._id_prefix = os.urandom(3).hex()
        self._id_seq = itertools.count()

    def close(self):
        try:
            self._rfile.close()
        finally:
            self.sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- plumbing ------------------------------------------------------------

    def _send(self, obj: dict):
        self.sock.sendall(encode_line(obj))

    def _recv(self) -> dict:
        line = self._rfile.readline()
        if not line:
            raise ProtocolError("server closed the connection")
        return decode_line(line)

    def request(self, obj: dict) -> dict:
        self._send(obj)
        return self._recv()

    # -- ops -----------------------------------------------------------------

    def ping(self) -> dict:
        reply = self.request({"op": "ping"})  # protocol: serve request ping
        if reply.get("event") != "pong":
            raise ProtocolError(f"expected pong, got {reply}")
        return reply

    def stats(self) -> dict:
        reply = self.request({"op": "stats"})  # protocol: serve request stats
        if reply.get("event") != "stats":
            raise ProtocolError(f"expected stats, got {reply}")
        return reply

    def generate(self, prompt=None, *, text: str | None = None,
                 max_new_tokens: int = 16, temperature: float = 0.0,
                 seed: int | None = None, stream: bool = False,
                 request_id: str | None = None, on_token=None,
                 priority: str | None = None,
                 deadline_ms: float | None = None,
                 deadline_s: float | None = None,
                 trace=None) -> dict:
        """Run one generation; returns the final ``done``/``error``
        payload.  With ``stream=True``, ``on_token(index, token)`` fires
        per streamed token before the final payload arrives.

        ``request_id`` defaults to a freshly minted per-client unique id
        (prefix + counter) - the old ``"0"`` default made every request
        from a default-argument caller the SAME request in stats and
        sidecars.  Pass an explicit id to correlate with external
        bookkeeping.

        ``priority``/``deadline_ms`` ride in the request (router QoS
        fields; plain servers ignore them).  ``trace`` attaches a
        :class:`~..obs.tracectx.TraceContext` as the ``trace`` wire
        field; ``None`` (the default) leaves the request byte-identical
        to the untraced protocol.  ``deadline_s`` is CLIENT-side: a
        wall bound across every read of this request - without it a
        stream emitting a token every few hundred ms resets the
        per-read timeout forever and a wedged server pins the caller."""
        if request_id is None:
            request_id = f"{self._id_prefix}-{next(self._id_seq)}"
        req = build_generate_request(
            prompt, text=text, request_id=request_id,
            max_new_tokens=max_new_tokens, temperature=temperature,
            seed=seed, stream=stream, priority=priority,
            deadline_ms=deadline_ms, trace=trace,
        )
        self._send(req)  # protocol: serve request generate
        expiry = (
            None if deadline_s is None
            else time.monotonic() + float(deadline_s)
        )
        while True:
            if expiry is not None:
                remaining = expiry - time.monotonic()
                if remaining <= 0:
                    raise ProtocolError(
                        f"no final reply within the {deadline_s:g}s "
                        f"request deadline"
                    )
                self.sock.settimeout(min(self.timeout_s, remaining))
            try:
                reply = self._recv()
            except OSError as exc:
                # a read armed with the residual deadline timing out IS
                # the deadline expiring - name it that, not "timed out"
                if expiry is not None and time.monotonic() >= expiry:
                    raise ProtocolError(
                        f"no final reply within the {deadline_s:g}s "
                        f"request deadline"
                    ) from exc
                raise
            event = reply.get("event")
            if event == "token":
                if on_token is not None:
                    on_token(reply.get("index"), reply.get("token"))
                continue
            if event in ("done", "error"):
                return reply
            raise ProtocolError(f"unexpected event {reply}")
