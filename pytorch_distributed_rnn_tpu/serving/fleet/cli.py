"""``pdrnn-router`` console entry point.

::

  pdrnn-router --replicas 127.0.0.1:7071,127.0.0.1:7072 --port 7070 \\
      --retries 2 --hedge-after-ms 250 --metrics router-metrics.jsonl \\
      --live 9100

The router is the fleet's observability ANCHOR: with ``--live`` it
hosts the aggregator (``/metrics`` + ``/health`` + ``/events`` +
``/fleet``) the replicas push their digests to - which is also the
router's load signal (a replica's ``serving.active + queue_depth``
rides its digest, so least-loaded dispatch needs no extra channel).

``--replica-port-files`` is the drill/spawn form: each replica writes
``host port`` once listening, the router waits for every file - no
fixed port allocation needed.  Replica ids are 1..N in listed order
(the router itself is rank 0).
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading
import time
from pathlib import Path

from pytorch_distributed_rnn_tpu.utils import leakcheck

log = logging.getLogger(__name__)


def build_router_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="pdrnn-router",
        description="fault-tolerant fleet router over pdrnn-serve "
        "replicas (same JSONL protocol as a single server)",
    )
    target = parser.add_mutually_exclusive_group(required=True)
    target.add_argument(
        "--replicas", default=None, metavar="HOST:PORT,...",
        help="static replica pool, comma-separated",
    )
    target.add_argument(
        "--replica-port-files", default=None, metavar="PATH,...",
        help="read each replica's address from a pdrnn-serve "
        "--port-file (waits for the files; the spawn-fleet form)",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument(
        "--port", default=0, type=int,
        help="TCP port (0 = ephemeral; see --port-file)",
    )
    parser.add_argument(
        "--port-file", default=None, type=Path, metavar="PATH",
        help="write 'host port' here once the pool is READY (first "
        "replica pong), so spawners block until the fleet can serve",
    )
    parser.add_argument(
        "--max-inflight", default=64, type=int,
        help="admission budget; QoS classes shed past graduated "
        "shares of it (low at 50%%, normal at 85%%, high at 100%%)",
    )
    parser.add_argument(
        "--retries", default=2, type=int,
        help="sibling re-dispatch budget per request (idempotent "
        "seeded requests only; a started stream is never replayed)",
    )
    parser.add_argument(
        "--hedge-after-ms", default=None, type=float,
        help="tail-latency hedge: dispatch a second replica when the "
        "primary is silent this long (non-stream requests only)",
    )
    parser.add_argument(
        "--deadline-ms", default=None, type=float,
        help="default per-request deadline when the client sends none",
    )
    parser.add_argument(
        "--trace-sample", default=0.0, type=float, metavar="RATE",
        help="head-sample this fraction of untraced requests into "
        "distributed traces (0 = off; requests arriving with a trace "
        "field are always traced; needs --metrics for the spans to "
        "land anywhere)",
    )
    parser.add_argument(
        "--eject-after", default=3, type=int,
        help="consecutive failures (ping or dispatch) opening a "
        "replica's circuit breaker",
    )
    parser.add_argument(
        "--cooldown-s", default=2.0, type=float,
        help="open -> half-open breaker cooldown",
    )
    parser.add_argument(
        "--half-open-probes", default=2, type=int,
        help="ping successes re-admitting a half-open replica (one "
        "successful trial dispatch also re-admits)",
    )
    parser.add_argument("--health-every-s", default=0.5, type=float)
    parser.add_argument("--connect-timeout", default=2.0, type=float,
                        metavar="S")
    parser.add_argument("--io-timeout", default=30.0, type=float,
                        metavar="S")
    parser.add_argument(
        "--ready-timeout", default=60.0, type=float, metavar="S",
        help="max wait for replica port files + the first pong",
    )
    parser.add_argument(
        "--drain-timeout", default=30.0, type=float, metavar="S",
        help="SIGTERM drain bound: in-flight dispatches get this long",
    )
    parser.add_argument("--metrics", default=None, type=Path,
                        metavar="PATH")
    parser.add_argument("--metrics-sample-every", default=None, type=int)
    parser.add_argument(
        "--live", default=None, metavar="[HOST:]PORT",
        help="live observability plane (needs --metrics): the router "
        "ANCHORS the fleet aggregator here - replicas started with "
        "the same --live spec push their digests to it; the anchor "
        "also hosts the time-series store behind GET /series and the "
        "capacity gauges on /metrics",
    )
    parser.add_argument("--live-port-file", default=None, type=Path,
                        metavar="PATH")
    parser.add_argument(
        "--slo", action="append", default=None, metavar="SPEC",
        help="per-QoS SLO objective (repeatable, one per class): "
        "'qos=high:p95_ms=250:availability=99.9'.  Arms per-class SLO "
        "breach alerts and the store's multi-window error-budget burn "
        "alerts (slo_burn fires / slo_burn_cleared on /events)",
    )
    parser.add_argument(
        "--slo-windows", default=None, metavar="FAST,SLOW",
        help="burn-rate window pair in seconds (default 300,3600 - "
        "the Google SRE fast/slow pair); drills shrink it to fit a "
        "burst",
    )
    parser.add_argument("--log", default="INFO")
    return parser


def _parse_host_port(spec: str) -> tuple[str, int]:
    host, _, port = spec.strip().rpartition(":")
    if not host:
        raise SystemExit(f"bad replica spec {spec!r} (want HOST:PORT)")
    return host, int(port)


def _await_port_files(paths: list[Path],
                      timeout_s: float) -> list[tuple[str, int]]:
    """Block until every replica wrote its ``host port`` file."""
    deadline = time.monotonic() + timeout_s
    addrs: list[tuple[str, int]] = []
    for path in paths:
        while True:
            try:
                fields = path.read_text().split()
                if len(fields) == 2:
                    addrs.append((fields[0], int(fields[1])))
                    break
            except (OSError, ValueError):
                pass
            if time.monotonic() > deadline:
                raise SystemExit(
                    f"replica port file {path} not ready after "
                    f"{timeout_s:g}s"
                )
            time.sleep(0.05)
    return addrs


def router_main(argv=None) -> int:
    args = build_router_parser().parse_args(argv)
    logging.basicConfig(level=args.log.upper())
    # before any socket/thread/file exists, so every acquisition is seen
    leakcheck.maybe_install()

    from pytorch_distributed_rnn_tpu.obs.live import LivePlane
    from pytorch_distributed_rnn_tpu.obs.recorder import MetricsRecorder
    from pytorch_distributed_rnn_tpu.serving.fleet.pool import (
        Replica,
        ReplicaPool,
    )
    from pytorch_distributed_rnn_tpu.serving.fleet.router import (
        RouterCore,
        RouterServer,
    )

    if args.replicas is not None:
        addrs = [_parse_host_port(s) for s in args.replicas.split(",")]
    else:
        paths = [Path(p.strip())
                 for p in args.replica_port_files.split(",")]
        addrs = _await_port_files(paths, args.ready_timeout)
    # ids 1..N: the router is the fleet's rank 0, replicas are ranks
    # 1..N - matching the --replica-id each pdrnn-serve was given
    replicas = [
        Replica(i + 1, host=h, port=p) for i, (h, p) in enumerate(addrs)
    ]

    recorder = MetricsRecorder.resolve(
        args, meta={"role": "router", "argv": sys.argv[1:]},
    )
    plane = LivePlane.resolve(args, recorder, rank=0, role="router")

    def load_hint(replica) -> float:
        # the live plane doubles as the load signal: a replica's digest
        # carries its serving gauges; silence costs nothing (hint 0 -
        # pings still arbitrate liveness)
        if plane is None or plane.aggregator is None:
            return 0.0
        digest = plane.aggregator.peek(f"serve-{replica.replica_id}")
        serving = (digest or {}).get("serving") or {}
        return float((serving.get("active") or 0)
                     + (serving.get("queue_depth") or 0))

    def pool_event(kind: str, **fields) -> None:
        if recorder.enabled:
            recorder.record(kind, **fields)

    pool = ReplicaPool(
        replicas, eject_after=args.eject_after,
        cooldown_s=args.cooldown_s,
        half_open_probes=args.half_open_probes,
        health_every_s=args.health_every_s,
        connect_timeout_s=args.connect_timeout,
        load_hint=load_hint, on_event=pool_event,
    )
    core = RouterCore(
        pool, max_inflight=args.max_inflight, retries=args.retries,
        hedge_after_ms=args.hedge_after_ms,
        default_deadline_ms=args.deadline_ms,
        connect_timeout_s=args.connect_timeout,
        io_timeout_s=args.io_timeout, recorder=recorder,
        trace_sample=args.trace_sample,
    )
    if plane is not None:
        plane.exporter.add_source(core.live_source)
    server = RouterServer(core, host=args.host, port=args.port,
                          recorder=recorder)

    stop = threading.Event()

    def _on_signal(signum, _frame):
        log.info(f"pdrnn-router: signal {signum}, draining")
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    server.start()
    if not server.wait_ready(timeout_s=args.ready_timeout):
        print(
            f"pdrnn-router: no replica answered a ping within "
            f"{args.ready_timeout:g}s", file=sys.stderr,
        )
        server.shutdown(drain_timeout_s=1.0)
        if plane is not None:
            plane.close()
        return 2
    # the port file lands only once the fleet can actually serve, so a
    # spawner reading it never races the first dispatch into a pool of
    # unpinged replicas
    if args.port_file is not None:
        args.port_file.parent.mkdir(parents=True, exist_ok=True)
        args.port_file.write_text(f"{server.host} {server.port}\n")
    print(f"pdrnn-router: listening on {server.host}:{server.port} "
          f"({len(replicas)} replicas)", flush=True)
    while not stop.is_set():
        stop.wait(timeout=0.5)
    server.shutdown(drain_timeout_s=args.drain_timeout)
    if plane is not None:
        plane.close()
    stats = core.stats()
    log.info(
        f"pdrnn-router: routed {stats['done']} "
        f"({stats['rerouted']} rerouted, {stats['retries']} retries, "
        f"{stats['hedges']} hedges), shed {stats['shed_total']}, "
        f"{stats['errors']} errors"
    )
    return 0


if __name__ == "__main__":
    sys.exit(router_main())
