"""Serving fleet: a fault-tolerant router over ``pdrnn-serve`` replicas.

The scale-out layer of the serving stack (ROADMAP item #3): a front-end
TCP router (``pdrnn-router``) speaking the same JSON-lines protocol as
a single ``pdrnn-serve``, dispatching over N engine replicas with

- a health-checked replica pool (``pool.py``): periodic pings plus the
  live plane's digests as the load signal, least-loaded dispatch, and a
  per-replica circuit breaker (eject after consecutive failures,
  half-open probing for re-admission);
- per-request robustness (``router.py``): deadline propagation,
  retry-budgeted re-dispatch of idempotent seeded requests to sibling
  replicas (bit-identical by construction - the seed pins the decode),
  tail-latency hedging behind ``--hedge-after-ms``, and QoS classes
  with priority shedding past the admission budget;
- degradation drills (``drill.py``): ``pdrnn-loadgen --spawn-fleet N``
  runs replicas under a
  :class:`~pytorch_distributed_rnn_tpu.launcher.supervisor.ReplicaSupervisor`,
  kills one mid-burst, and asserts rerouting + exactly-once accounting
  (done + shed + errors == submitted) + SLO recovery.

A client that speaks to ``pdrnn-serve`` speaks to ``pdrnn-router``
unchanged; the fleet is invisible until something fails.
"""

from pytorch_distributed_rnn_tpu.serving.fleet.pool import (  # noqa: F401
    Replica,
    ReplicaPool,
    TcpReplicaConnection,
)
from pytorch_distributed_rnn_tpu.serving.fleet.router import (  # noqa: F401
    QOS_ADMIT_FRAC,
    QOS_CLASSES,
    RouterCore,
    RouterServer,
)
