"""``python -m pytorch_distributed_rnn_tpu.serving.fleet ...`` - the
module form of the ``pdrnn-router`` console script (the drill spawns
the router through this form so it works from a source checkout
without an installed entry point)."""

from __future__ import annotations

import sys

from pytorch_distributed_rnn_tpu.serving.fleet.cli import router_main

if __name__ == "__main__":
    sys.exit(router_main())
