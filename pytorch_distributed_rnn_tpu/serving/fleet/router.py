"""The fleet router: QoS admission, deadline-bounded dispatch, retry,
hedging, drain.

:class:`RouterCore` is the transport-free request path (unit-testable
with fake replicas through the pool's ``dial`` factory);
:class:`RouterServer` wraps it in the same JSONL-over-TCP front end a
single ``pdrnn-serve`` presents, so clients - the load generator
included - cannot tell a fleet from a replica.

Request lifecycle::

    admit (QoS budget)  ->  dispatch (least-loaded pick)
        -> relay events  ->  final done/error back to the client
        -> on transport failure: retry a SIBLING replica
           (backoff from resilience/retry.py, trimmed to the deadline)

The robustness contracts, in order of importance:

- **exactly-once accounting**: every admitted request ends in exactly
  one of done/error; sheds and drain rejections are counted at
  admission.  ``done + shed + errors == submitted`` is the drill's
  gate and ``stats()`` exposes every term.
- **idempotent retry only**: the router assigns a seed to any generate
  that arrives without one, so EVERY dispatch is deterministic and a
  re-dispatch to a sibling replica is bit-identical (the seed pins the
  decode; replicas share the checkpoint).  A streaming request that
  already relayed tokens to the client is FAILED on transport loss,
  never replayed - replaying would re-emit prefix tokens and no
  dedupe exists client-side.
- **deadline propagation**: ``deadline_ms`` (or ``--deadline-ms``)
  bounds the whole dispatch+retry+hedge tree; the remaining budget
  arms every connect/read and trims the backoff schedule
  (``resilience/retry.backoff_delays(deadline_s=...)``).
- **priority shedding**: past graduated shares of the admission budget
  (``QOS_ADMIT_FRAC``) low sheds first, then normal, then high - an
  EXPLICIT overload error with ``shed: true``, never a silent drop.
- **hedging** (``--hedge-after-ms``): a non-streaming request whose
  primary dispatch is silent past the threshold gets a second dispatch
  to a sibling; first final reply wins, the loser is cancelled
  (connection closed, pool release neutral - a slow replica is not a
  failed one).  Stream requests never hedge: two streams cannot be
  merged token-wise.

Distributed tracing (``obs/tracectx.py``): the router is the fleet's
trace EDGE.  A request arriving with a ``trace`` wire field extends the
client's context; otherwise ``--trace-sample RATE`` head-samples fresh
roots.  A traced request gets a ``route`` span covering its whole stay,
one ``attempt`` child span per dispatch (retries and both hedge legs
each their own - sibling re-dispatches are finally distinguishable in
replica sidecars), and the re-minted per-attempt context rides the
forwarded message so the replica's queue_wait/prefill/decode spans nest
under the attempt that caused them.  Untraced requests allocate no
context and their forwarded bytes are untouched.
"""

from __future__ import annotations

import itertools
import json
import logging
import queue
import socket
import threading
import time

from pytorch_distributed_rnn_tpu.obs.live import (
    RollingWindow,
    request_latency_histogram,
)
from pytorch_distributed_rnn_tpu.obs.recorder import NULL_RECORDER
from pytorch_distributed_rnn_tpu.obs.tracectx import (
    TraceContext,
    should_sample,
)
from pytorch_distributed_rnn_tpu.resilience.retry import backoff_delays
from pytorch_distributed_rnn_tpu.serving.fleet.pool import Replica
from pytorch_distributed_rnn_tpu.serving.protocol import (
    ProtocolError,
    encode_line,
)
from pytorch_distributed_rnn_tpu.utils import leakcheck, threadcheck

log = logging.getLogger(__name__)

QOS_CLASSES = ("high", "normal", "low")

# admission shares of --max-inflight per class: low is shed first (past
# half the budget), normal next, high rides to the full budget - the
# graceful-degradation ordering under overload
QOS_ADMIT_FRAC = {"high": 1.0, "normal": 0.85, "low": 0.5}


class DispatchError(RuntimeError):
    """A dispatch failed at the transport level (dial/read/protocol):
    the replica is charged a breaker failure; the request may retry a
    sibling if its stream never started."""


class _Cancelled(Exception):
    """A hedge loser was cancelled - neutral, nobody is at fault."""


class RouterCore:
    """The request path: admission, dispatch, retry, hedge, accounting."""

    def __init__(self, pool, *, max_inflight: int = 64, retries: int = 2,
                 retry_base_delay_s: float = 0.05,
                 hedge_after_ms: float | None = None,
                 default_deadline_ms: float | None = None,
                 connect_timeout_s: float = 2.0,
                 io_timeout_s: float = 30.0,
                 recorder=None, seed: int = 0,
                 trace_sample: float = 0.0):
        self.pool = pool
        self.max_inflight = int(max_inflight)
        self.retries = int(retries)
        self.retry_base_delay_s = float(retry_base_delay_s)
        self.hedge_after_ms = (
            None if hedge_after_ms is None else float(hedge_after_ms)
        )
        self.default_deadline_ms = (
            None if default_deadline_ms is None
            else float(default_deadline_ms)
        )
        self.connect_timeout_s = float(connect_timeout_s)
        self.io_timeout_s = float(io_timeout_s)
        self.recorder = recorder if recorder is not None else NULL_RECORDER
        self.seed = int(seed)
        self.trace_sample = float(trace_sample)
        self._seed_seq = itertools.count()
        # head-sampling sequence for router-minted trace roots (only
        # consumed when sampling is on and the request arrived untraced)
        self._trace_seq = itertools.count(1)
        self._lock = threadcheck.lock(threading.Lock(), "router.stats")  # guards: _inflight, _submitted, _done, _errors, _shed, _drain_rejected, _retries, _rerouted, _hedges, _hedge_wins, _stream_aborts, _draining, _route_span_open
        self._inflight = 0
        self._submitted = 0
        self._done = 0
        self._errors = 0
        self._shed = dict.fromkeys(QOS_CLASSES, 0)
        self._drain_rejected = 0
        self._retries = 0
        self._rerouted = 0
        self._hedges = 0
        self._hedge_wins = 0
        self._stream_aborts = 0
        self._draining = False
        # one route span in flight at a time: concurrent handler threads
        # all share the router's single timeline lane, and the trace
        # validator (rightly) rejects partially-overlapping spans on one
        # lane - non-candidates just skip the span, the latency window
        # still sees every request
        self._route_span_open = False
        # thread-safe on their own: read outside the stats lock
        self._completions = RollingWindow()
        self._latency_s = RollingWindow()
        # request-latency histogram behind the aggregator's
        # pdrnn_request_latency_seconds series; traced completions stamp
        # their bucket's exemplar with their trace_id.  Constructed via
        # the SHARED spec (obs/live.request_latency_histogram) so the
        # engine's buckets and the store's quantile sketches line up.
        self._latency_hist = request_latency_histogram()
        # per-QoS latency windows behind latency_s_p95_by_qos: the
        # store and watchdog scope --slo objectives per class with them
        self._latency_by_qos = {
            q: RollingWindow() for q in QOS_CLASSES
        }

    # -- admission -----------------------------------------------------------

    def _admit(self, qos: str) -> str:
        allowed = max(1, int(self.max_inflight * QOS_ADMIT_FRAC[qos]))
        with self._lock:
            if self._draining:
                self._drain_rejected += 1
                return "draining"
            if self._inflight >= allowed:
                self._shed[qos] += 1
                return "shed"
            self._inflight += 1
            self._submitted += 1
            return "ok"

    def begin_drain(self) -> None:
        with self._lock:
            self._draining = True

    def inflight_count(self) -> int:
        with self._lock:
            return self._inflight

    # -- the request path ----------------------------------------------------

    def handle_generate(self, msg: dict, send) -> dict:
        """Route one generate request; every path sends exactly one
        final ``done``/``error`` to the client (token events are relayed
        as they arrive for streams).  Returns the final payload."""
        request_id = str(msg.get("id", ""))
        qos = str(msg.get("priority", "normal")).lower()
        if qos not in QOS_CLASSES:
            final = {
                "id": request_id, "event": "error",
                "error": f"unknown priority {qos!r} "
                         f"({'|'.join(QOS_CLASSES)})",
            }
            send(final)
            return final
        if "seed" not in msg:
            # the idempotency pin: a router-assigned seed makes every
            # dispatch deterministic, so a retry to a sibling replica
            # reproduces the decode bit-identically
            msg["seed"] = (self.seed * 1_000_003
                           + next(self._seed_seq)) & 0x7FFFFFFF
        verdict = self._admit(qos)
        if verdict != "ok":
            if verdict == "draining":
                error = "router draining - not accepting requests"
            else:
                error = (
                    f"router overloaded - {qos} priority shed past "
                    f"admission budget"
                )
            self.recorder.record("route_shed", qos=qos,
                                 request=request_id, reason=verdict)
            final = {"id": request_id, "event": "error", "error": error,
                     "shed": True, "qos": qos}
            send(final)
            return final

        # trace edge: extend the sender's context, or head-sample a
        # fresh root when --trace-sample is on.  An untraced request
        # constructs NO context (the zero-overhead pin) and is forwarded
        # byte-identical.
        route_ctx = None
        if self.recorder.enabled:
            if "trace" in msg:
                # protocol: serve field trace
                incoming = TraceContext.from_wire(msg.get("trace"))
                if incoming is not None:
                    route_ctx = incoming.child()
            elif self.trace_sample > 0.0 and should_sample(
                    next(self._trace_seq), self.trace_sample):
                route_ctx = TraceContext.mint(qos=qos)

        deadline_ms = msg.get("deadline_ms", self.default_deadline_ms)
        expiry = (
            None if deadline_ms is None
            else time.monotonic() + float(deadline_ms) / 1e3
        )
        t0 = time.perf_counter()
        span_t0 = span_dur = None
        with self._lock:
            if not self._route_span_open:
                self._route_span_open = True
                # start time taken under the lock: acquisition is
                # serialized after the previous candidate's release (and
                # its end-time measurement), so candidate spans nest
                span_t0 = time.perf_counter()
        try:
            final, meta = self._route(msg, send, expiry, route_ctx)
        finally:
            if span_t0 is not None:
                span_dur = time.perf_counter() - span_t0
            with self._lock:
                self._inflight -= 1
                if span_t0 is not None:
                    self._route_span_open = False
        elapsed = time.perf_counter() - t0
        final = {"id": request_id, **final, **meta}
        ok = final.get("event") == "done"
        with self._lock:
            if ok:
                self._done += 1
                if meta.get("attempts", 1) > 1:
                    self._rerouted += 1
            else:
                self._errors += 1
            submitted = self._submitted
        if ok:
            self._completions.observe(1.0)
            self._latency_s.observe(elapsed)
            self._latency_by_qos[qos].observe(elapsed)
            self._latency_hist.observe(
                elapsed, trace_id=None if route_ctx is None
                else route_ctx.trace_id,
            )
            if span_t0 is not None and \
                    self.recorder.is_sample_step(submitted):
                self.recorder.emit_span(
                    "route", span_t0, span_dur, cat="router",
                    replica=meta.get("replica"),
                    attempts=meta.get("attempts"), qos=qos,
                )
        if route_ctx is not None:
            # the request-level trace span: emitted for EVERY traced
            # request (unlike the sampled timeline-lane span above) so
            # the assembled tree never misses its router root, and the
            # client learns its trace_id from the final payload
            self.recorder.emit_span(
                "route", t0, elapsed, cat="trace", request=request_id,
                qos=qos, replica=meta.get("replica"),
                attempts=meta.get("attempts"),
                outcome=final.get("event"),
                **route_ctx.span_fields(),
            )
            final["trace_id"] = route_ctx.trace_id
        send(final)
        return final

    def _route(self, msg: dict, send, expiry,
               route_ctx=None) -> tuple[dict, dict]:
        """Dispatch with retry/hedge; returns (final-payload, meta).
        With a ``route_ctx`` every dispatch attempt forks a child
        context, forwards it on a COPIED message, and emits an
        ``attempt`` span - the original ``msg`` is never mutated, so
        untraced forwarding stays byte-identical."""
        stream = bool(msg.get("stream"))
        relayed = {"tokens": 0}
        relay = send if stream else None
        remaining = (
            None if expiry is None else expiry - time.monotonic()
        )
        delays = backoff_delays(
            self.retries, base_delay=self.retry_base_delay_s,
            seed=int(msg["seed"]), deadline_s=remaining,
        )
        hedge_first = self.hedge_after_ms is not None and not stream
        tried: list[int] = []
        attempts = 0
        hedged = False
        last_error = "no healthy replica available"
        for attempt in range(self.retries + 1):
            if expiry is not None and time.monotonic() >= expiry:
                with self._lock:
                    self._retries += max(0, attempts - 1)
                return ({
                    "event": "error",
                    "error": f"deadline exceeded after {attempts} "
                             f"attempt(s): {last_error}",
                }, {"attempts": attempts})
            replica = self.pool.pick(exclude=tried)
            if replica is None:
                break
            tried.append(replica.replica_id)
            attempts += 1
            hedge_now = hedge_first and attempt == 0
            att_ctx = att_msg = att_t0 = None
            if route_ctx is not None and not hedge_now:
                att_ctx = route_ctx.child()
                # protocol: serve field trace
                att_msg = {**msg, "trace": att_ctx.to_wire()}
                att_t0 = time.perf_counter()
            try:
                if hedge_now:
                    reply, hedge_replica, hedged = self._dispatch_hedged(
                        replica, msg, expiry, tried,
                        route_ctx=route_ctx, attempt_index=attempts,
                    )
                    replica = hedge_replica
                else:
                    reply = self._dispatch(
                        replica, msg if att_msg is None else att_msg,
                        relay, relayed, expiry,
                    )
                    if att_ctx is not None:
                        self._emit_attempt_span(
                            att_ctx, att_t0, replica.replica_id,
                            attempts, reply.get("event"),
                        )
            except DispatchError as exc:
                if att_ctx is not None:
                    self._emit_attempt_span(
                        att_ctx, att_t0, replica.replica_id, attempts,
                        "transport_error",
                    )
                last_error = str(exc)
                if relayed["tokens"]:
                    # the stream already reached the client: a replay
                    # would re-emit its prefix - fail loudly instead
                    with self._lock:
                        self._stream_aborts += 1
                    return ({
                        "event": "error",
                        "error": f"stream interrupted after "
                                 f"{relayed['tokens']} token(s): "
                                 f"{last_error}; a started stream is "
                                 f"never replayed",
                        "stream_aborted": True,
                    }, {"attempts": attempts,
                        "replica": replica.replica_id})
                if attempt < len(delays):
                    time.sleep(delays[attempt])
                continue
            if reply.get("event") == "error" and (
                reply.get("shed") or reply.get("draining")
            ):
                # the replica rejected before executing anything -
                # idempotent by construction, a sibling may have room
                last_error = str(reply.get("error"))
                if attempt < len(delays):
                    time.sleep(delays[attempt])
                continue
            with self._lock:
                self._retries += attempts - 1
            meta = {"replica": replica.replica_id, "attempts": attempts}
            if hedged:
                meta["hedged"] = True
            return reply, meta
        with self._lock:
            self._retries += max(0, attempts - 1)
        return ({
            "event": "error",
            "error": f"retry budget exhausted after {attempts} "
                     f"attempt(s): {last_error}",
        }, {"attempts": attempts})

    def _emit_attempt_span(self, ctx, t0: float, replica_id: int,
                           attempt: int, outcome,
                           hedge: bool = False) -> None:
        """One dispatch attempt's trace span (child of the route span):
        retries and hedge legs each carry their own context, so sibling
        re-dispatches stay distinguishable in the assembled tree."""
        self.recorder.emit_span(
            "attempt", t0, time.perf_counter() - t0, cat="trace",
            replica=replica_id, attempt=attempt, outcome=outcome,
            hedge=True if hedge else None, **ctx.span_fields(),
        )

    # -- single dispatch -----------------------------------------------------

    def _dispatch(self, replica: Replica, msg: dict, relay, relayed,
                  expiry, cancel_box: dict | None = None) -> dict:
        """One attempt against one replica: dial, send, relay events,
        return the final reply.  Raises :class:`DispatchError` on any
        transport/protocol failure (charged to the replica's breaker),
        :class:`_Cancelled` when a hedge winner closed us out (neutral
        release)."""
        timeout = self.connect_timeout_s
        if expiry is not None:
            timeout = max(0.05, min(timeout, expiry - time.monotonic()))
        try:
            conn = replica.dial(connect_timeout_s=timeout,
                                io_timeout_s=self.io_timeout_s)
        except (OSError, ProtocolError) as exc:
            self.pool.release(replica, ok=False)
            raise DispatchError(
                f"dial replica {replica.replica_id}: {exc}"
            ) from exc
        if cancel_box is not None:
            cancel_box["conn"] = conn
        ok: bool | None = None
        try:
            conn.send(msg)  # protocol: serve request generate
            while True:
                if expiry is not None:
                    remaining = expiry - time.monotonic()
                    if remaining <= 0:
                        raise socket.timeout(
                            "request deadline exceeded mid-dispatch"
                        )
                    conn.set_deadline(min(self.io_timeout_s, remaining))
                reply = conn.recv()
                event = reply.get("event")
                if event == "token":
                    relayed["tokens"] += 1
                    if relay is not None:
                        relay(reply)
                    continue
                if event in ("done", "error"):
                    ok = True
                    return reply
                raise ProtocolError(f"unexpected event {reply}")
        except (OSError, ProtocolError, ValueError) as exc:
            if cancel_box is not None and cancel_box.get("cancelled"):
                raise _Cancelled() from exc
            ok = False
            raise DispatchError(
                f"replica {replica.replica_id}: {exc}"
            ) from exc
        finally:
            conn.close()
            self.pool.release(replica, ok=ok)

    # -- hedging -------------------------------------------------------------

    def _dispatch_hedged(self, primary: Replica, msg: dict, expiry,
                         tried: list, route_ctx=None,
                         attempt_index: int = 1):
        """Primary dispatch with a tail-latency hedge: when the primary
        is silent past ``hedge_after_ms``, dispatch a sibling; the
        first FINAL reply wins and the loser is cancelled (socket
        closed, neutral pool release).  With a ``route_ctx`` each leg
        forwards its OWN child context and emits its own ``attempt``
        span (the loser's with outcome ``cancelled``).  Returns
        ``(reply, winning replica, hedged?)``; raises
        :class:`DispatchError` when every launched dispatch failed."""
        results: queue.Queue = queue.Queue()
        runners: list[tuple[Replica, dict]] = []

        def launch(replica: Replica, hedge: bool = False):
            box = {"conn": None, "cancelled": False}
            runners.append((replica, box))
            ctx, leg_msg = None, msg
            if route_ctx is not None:
                ctx = route_ctx.child()
                # protocol: serve field trace
                leg_msg = {**msg, "trace": ctx.to_wire()}

            def run():
                t0 = None if ctx is None else time.perf_counter()
                state = {"tokens": 0}
                try:
                    reply = self._dispatch(replica, leg_msg, None, state,
                                           expiry, cancel_box=box)
                    if ctx is not None:
                        self._emit_attempt_span(
                            ctx, t0, replica.replica_id, attempt_index,
                            reply.get("event"), hedge=hedge,
                        )
                    results.put((replica, reply, None))
                except _Cancelled:
                    if ctx is not None:
                        self._emit_attempt_span(
                            ctx, t0, replica.replica_id, attempt_index,
                            "cancelled", hedge=hedge,
                        )
                except DispatchError as exc:
                    if ctx is not None:
                        self._emit_attempt_span(
                            ctx, t0, replica.replica_id, attempt_index,
                            "transport_error", hedge=hedge,
                        )
                    results.put((replica, None, exc))

            threading.Thread(
                target=run, daemon=True,
                name=f"pdrnn-router-dispatch-{replica.replica_id}",
            ).start()

        def get(timeout_s: float):
            try:
                return results.get(timeout=max(0.0, timeout_s))
            except queue.Empty:
                return None

        launch(primary)
        budget = self.io_timeout_s + self.connect_timeout_s + 5.0
        if expiry is not None:
            budget = min(budget, max(0.05, expiry - time.monotonic()))
        first = get(min(self.hedge_after_ms / 1e3, budget))
        hedged = False
        if first is None:
            secondary = self.pool.pick(exclude=tried)
            if secondary is not None:
                tried.append(secondary.replica_id)
                hedged = True
                with self._lock:
                    self._hedges += 1
                self.recorder.record(
                    "hedge", primary=primary.replica_id,
                    secondary=secondary.replica_id,
                    request=str(msg.get("id", "")),
                )
                launch(secondary, hedge=True)
            first = get(budget)
        if first is not None and first[1] is None and len(runners) == 2:
            # the first finisher FAILED; give the other dispatch its
            # chance before declaring the attempt dead
            second = get(budget)
            first = second if second is not None else first
        if first is None:
            raise DispatchError(
                f"no reply from replica {primary.replica_id} within "
                f"{budget:.1f}s"
            )
        winner, reply, err = first
        for replica, box in runners:
            if replica is winner:
                continue
            box["cancelled"] = True
            conn = box.get("conn")
            if conn is not None:
                conn.close()
        if reply is None:
            raise err
        if hedged and winner is not primary:
            with self._lock:
                self._hedge_wins += 1
        return reply, winner, hedged

    # -- views ---------------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            body = {
                "submitted": self._submitted, "done": self._done,
                "errors": self._errors, "shed": dict(self._shed),
                "shed_total": sum(self._shed.values()),
                "drain_rejected": self._drain_rejected,
                "inflight": self._inflight, "retries": self._retries,
                "rerouted": self._rerouted, "hedges": self._hedges,
                "hedge_wins": self._hedge_wins,
                "stream_aborts": self._stream_aborts,
                "draining": self._draining,
            }
        latency = self._latency_s.stats()
        body["req_per_s_60s"] = self._completions.count_rate()
        body["latency_s_p50"] = latency["p50"]
        body["latency_s_p95"] = latency["p95"]
        body["pool"] = self.pool.snapshot()
        return body

    def live_source(self) -> dict:
        """The ``router`` gauge block riding every live digest (the
        aggregator exports it as ``pdrnn_router_*``)."""
        stats = self.stats()
        block = {
            "inflight": stats["inflight"], "routed": stats["done"],
            "rerouted": stats["rerouted"], "retries": stats["retries"],
            "hedges": stats["hedges"],
            "hedge_wins": stats["hedge_wins"],
            "errors": stats["errors"], "shed": stats["shed"],
            "drain_rejected": stats["drain_rejected"],
            "replicas": stats["pool"]["states"],
            "max_inflight": self.max_inflight,
            "req_per_s_60s": stats["req_per_s_60s"],
            "latency_s_p50": stats["latency_s_p50"],
            "latency_s_p95": stats["latency_s_p95"],
        }
        by_qos = {
            qos: window.stats()["p95"]
            for qos, window in self._latency_by_qos.items()
            if window.values()
        }
        if by_qos:
            # per-class p95 (the --slo scoping input: watchdog + store)
            block["latency_s_p95_by_qos"] = by_qos
        hist = self._latency_hist.snapshot()
        if hist is not None:
            block["latency_hist"] = hist
        return {"router": block}

    def summary_fields(self) -> dict:
        """The ``run_summary`` contribution (``ROUTER_SUMMARY_KEYS`` in
        ``obs/summary.py`` passes these through ``pdrnn-metrics
        summarize`` verbatim)."""
        stats = self.stats()
        return {
            "routed": stats["done"], "rerouted": stats["rerouted"],
            "retries": stats["retries"], "hedges": stats["hedges"],
            "hedge_wins": stats["hedge_wins"],
            "router_shed": stats["shed_total"],
            "router_errors": stats["errors"],
            "stream_aborts": stats["stream_aborts"],
            "replica_ejections": stats["pool"]["ejections"],
            "replica_readmissions": stats["pool"]["readmissions"],
            "drain_rejected": stats["drain_rejected"],
        }


class RouterServer:
    """JSONL-over-TCP front end for one :class:`RouterCore` - the same
    accept/reader-thread shape as ``serving/server.py`` minus the
    engine (dispatch happens on the connection thread: the router's
    concurrency = its clients')."""

    def __init__(self, core: RouterCore, host: str = "127.0.0.1",
                 port: int = 0, recorder=None):
        self.core = core
        self.recorder = recorder if recorder is not None else NULL_RECORDER
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            self._listener.setsockopt(
                socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._listener.bind((host, port))
            self._listener.listen(128)
            self.host, self.port = self._listener.getsockname()[:2]
        except Exception:
            self._listener.close()
            raise
        self._stop = threading.Event()
        self._conns_lock = threadcheck.lock(threading.Lock(), "router.conns")  # guards: _conns
        self._conns: set[socket.socket] = set()
        self._threads: list[threading.Thread] = []
        self._started = False
        self._t_start = time.perf_counter()

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        if self._started:
            return
        self._started = True
        self.core.pool.start()
        accept_thread = threading.Thread(
            target=self._accept_loop, name="pdrnn-router-accept",
            daemon=True,
        )
        self._threads = [accept_thread]
        accept_thread.start()
        log.info(f"pdrnn-router: listening on {self.host}:{self.port}")

    def wait_ready(self, timeout_s: float = 60.0) -> bool:
        return self.core.pool.wait_ready(timeout_s=timeout_s)

    def shutdown(self, drain_timeout_s: float = 30.0):
        """SIGTERM drain: stop accepting and admitting, let in-flight
        dispatches finish (bounded), then flush telemetry; idempotent."""
        if self._stop.is_set():
            return
        self.core.begin_drain()
        try:
            self._listener.close()
        except OSError:  # pragma: no cover - already closed
            pass
        deadline = time.monotonic() + float(drain_timeout_s)
        while self.core.inflight_count() > 0 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        self._stop.set()
        for thread in self._threads:
            thread.join(timeout=10.0)
        self.core.pool.close()
        # force-drop any client connection whose reader has not exited
        # yet: after this, nothing of ours may still hold a socket -
        # which is exactly what the leak sentinel now verifies
        with self._conns_lock:
            victims = list(self._conns)
            self._conns.clear()
        for sock in victims:
            try:
                sock.close()
            except OSError:  # pragma: no cover - already closed
                pass
        leakcheck.check_drained("router.shutdown")
        if self.recorder.enabled:
            self.recorder.record(
                "router_drain",
                inflight_at_close=self.core.inflight_count(),
            )
            self.recorder.record(
                "run_summary",
                duration_s=time.perf_counter() - self._t_start,
                **self.core.summary_fields(),
            )
            self.recorder.close()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.shutdown()

    # -- accept / connection side --------------------------------------------

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                # deadline-free by contract: shutdown() closing the
                # listener unblocks this accept with OSError
                conn, _addr = self._listener.accept()  # noqa: PD402
            except OSError:  # listener closed = shutdown
                return
            handler = threading.Thread(
                target=self._handle, args=(conn,),
                name="pdrnn-router-conn", daemon=True,
            )
            handler.start()

    def _handle(self, conn: socket.socket):
        wlock = threadcheck.lock(threading.Lock(), "router.conn.write")
        alive = {"ok": True}
        with self._conns_lock:
            self._conns.add(conn)

        def send(obj: dict):
            # dispatch threads (hedges) and the reader both write here;
            # a vanished client must not take the router down with it
            with wlock:
                if not alive["ok"]:
                    return
                try:
                    # client-paced by contract: a timeout here would
                    # drop slow-but-alive clients; dead peers surface
                    # as OSError and just mark the conn down
                    conn.sendall(encode_line(obj))  # noqa: PD402
                except OSError:
                    alive["ok"] = False

        rfile = conn.makefile("r", encoding="utf-8")
        try:
            for line in rfile:
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                    if not isinstance(msg, dict):
                        raise ValueError("messages are JSON objects")
                except ValueError as exc:
                    send({"event": "error", "error": f"bad request: {exc}"})
                    continue
                self._dispatch_op(msg, send)
                if self._stop.is_set():
                    break
        except OSError:
            pass
        finally:
            alive["ok"] = False
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                rfile.close()
            finally:
                conn.close()

    # -- ops -----------------------------------------------------------------

    def _dispatch_op(self, msg: dict, send):
        # protocol: serve handles ping, stats, generate
        # protocol: serve reply ping - pong/error below
        op = msg.get("op")
        if op == "ping":
            info = self.core.pool.pong_info()
            if info is None:
                send({
                    "event": "error",
                    "error": "no replica has answered a ping yet",
                })
                return
            counts = self.core.pool.state_counts()
            send({
                **info, "event": "pong",
                "fleet": {
                    "replicas": len(self.core.pool.replicas),
                    **counts,
                },
            })
        elif op == "stats":
            send({"event": "stats", **self.core.stats()})  # protocol: serve reply stats
        elif op == "generate":
            # protocol: serve reply generate - relayed token stream +
            # terminal done/error from handle_generate
            self.core.handle_generate(msg, send)
        else:
            send({
                "id": msg.get("id"), "event": "error",
                "error": f"unknown op {op!r} (generate|ping|stats)",
            })
