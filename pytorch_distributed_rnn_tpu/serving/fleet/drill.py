"""Kill-mid-burst fleet drill: replicas die, the router reroutes.

``spawn_fleet`` runs N ``pdrnn-serve`` replicas under a
:class:`~pytorch_distributed_rnn_tpu.launcher.supervisor.ReplicaSupervisor`
(subprocesses - the drill must prove PROCESSES survive) plus one
``pdrnn-router`` in front.  Each replica learns an ephemeral port at
first launch and a respawn REBINDS that same port, so the router's
static pool entry stays valid and the breaker re-admits the new
incarnation through half-open pings.

``run_fleet_drill`` is the scenario ``pdrnn-loadgen --spawn-fleet`` and
the CI fleet job share: fleet up, load through the router, SIGKILL one
replica mid-burst, fleet down.  Acceptance is graceful degradation:

- the degradation window (per-second report timeline) CLOSES - traffic
  reroutes to the survivors and the respawned replica rejoins;
- exactly-once accounting holds on BOTH sides of the wire:
  ``done + shed + errors == submitted`` in the load report, and the
  router's own ledger agrees - no duplicated and no lost completions;
- the supervisor respawned the kill (``respawns >= 1``) and every
  process exits clean on teardown.

When the router is started with a live plane (``--live`` +
``--live-port-file`` in ``router_args``), the drill also runs a
:class:`_LiveProbe` against the anchor for the whole burst plus a
short grace window: it scrapes ``/events`` and ``/series`` and attaches
the observability verdict under ``report["fleet"]["live"]`` - did the
SLO error-budget ``slo_burn`` alert fire AND clear, and did the store's
``pdrnn_recommended_replicas`` capacity signal rise while the killed
replica was down.  CI asserts on that JSON instead of racing the burst
with shell polling.
"""

from __future__ import annotations

import contextlib
import logging
import signal
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path

from pytorch_distributed_rnn_tpu.launcher.supervisor import (
    ReplicaSupervisor,
)
from pytorch_distributed_rnn_tpu.serving.drill import trace_handles
from pytorch_distributed_rnn_tpu.serving.loadgen import (
    LoadConfig,
    run_load,
)
from pytorch_distributed_rnn_tpu.serving.protocol import ServingClient

log = logging.getLogger(__name__)


class FleetSpawnError(RuntimeError):
    """A fleet process died or never became ready."""


class _PopenProc:
    """Adapts :class:`subprocess.Popen` to the process contract
    :class:`RespawnSupervisor` polls (``is_alive``/``exitcode``/
    ``terminate``/``join``)."""

    def __init__(self, proc: subprocess.Popen):
        self.proc = proc

    @property
    def pid(self) -> int:
        return self.proc.pid

    def is_alive(self) -> bool:
        return self.proc.poll() is None

    @property
    def exitcode(self):
        return self.proc.poll()

    def terminate(self) -> None:
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()

    def join(self, timeout: float | None = None) -> None:
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            pass


def _await_file(path: Path, what: str, timeout_s: float,
                dead=None) -> list[str]:
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            fields = path.read_text().split()
            if len(fields) == 2:
                return fields
        except OSError:
            pass
        if dead is not None and dead() is not None:
            raise FleetSpawnError(
                f"{what} exited with {dead()} before becoming ready"
            )
        if time.monotonic() > deadline:
            raise FleetSpawnError(f"{what} not ready after {timeout_s}s")
        time.sleep(0.05)


def _router_live_port_file(router_args) -> Path | None:
    """The ``--live-port-file`` value inside ``router_args``, if any -
    how the drill learns where the router anchored its live plane."""
    args = list(router_args or [])
    for i, arg in enumerate(args):
        if arg == "--live-port-file" and i + 1 < len(args):
            return Path(args[i + 1])
        if arg.startswith("--live-port-file="):
            return Path(arg.split("=", 1)[1])
    return None


class _LiveProbe:
    """Polls the router's live anchor (``/events`` + ``/series``) on a
    background thread while the burst runs.  All state is written by
    the probe thread only and read after :meth:`finish` joins it, so no
    lock is needed."""

    def __init__(self, host: str, port: int):
        self.base = f"http://{host}:{port}"
        self.polls = 0
        self.errors = 0
        self.burn_fired = False
        self.burn_cleared = False
        self.recommended: list[float] = []
        self.live_replicas: list[float] = []
        self.series_scrape: dict | None = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="pdrnn-fleet-live-probe", daemon=True,
        )

    def start(self) -> None:
        self._thread.start()

    def _fetch(self, path: str):
        import json
        import urllib.request

        with urllib.request.urlopen(self.base + path,
                                    timeout=5.0) as resp:
            return json.loads(resp.read())

    def _poll_once(self) -> None:
        try:
            events = self._fetch("/events")
            # replay the whole (bounded) event log each poll: cleared
            # only counts when it follows a fire for the same key
            burning: set = set()
            for event in events:
                kind = event.get("alert")
                key = (event.get("source"), event.get("qos"))
                if kind == "slo_burn":
                    self.burn_fired = True
                    burning.add(key)
                elif kind == "slo_burn_cleared" and key in burning:
                    burning.discard(key)
                    self.burn_cleared = True
            for name, sink in (
                ("pdrnn_recommended_replicas", self.recommended),
                ("pdrnn_replicas_live", self.live_replicas),
            ):
                resp = self._fetch(
                    f"/series?name={name}&window=120&agg=last")
                series = resp.get("series") or []
                value = series[0].get("value") if series else None
                if value is not None:
                    sink.append(float(value))
            if self.series_scrape is None:
                scrape = self._fetch(
                    "/series?name=pdrnn_router_request_rate_per_s"
                    "&window=60")
                if scrape.get("series"):
                    self.series_scrape = scrape
            self.polls += 1
        except (OSError, ValueError):
            self.errors += 1

    def _run(self) -> None:
        while not self._stop.wait(timeout=0.5):
            self._poll_once()

    def finish(self, grace_s: float = 15.0) -> None:
        """Keep polling past the burst until a fired burn alert has
        cleared (or the grace expires), then stop the thread."""
        deadline = time.monotonic() + grace_s
        while (time.monotonic() < deadline
               and not (self.burn_fired and self.burn_cleared)):
            time.sleep(0.3)
        self._stop.set()
        self._thread.join(timeout=5.0)

    def verdict(self) -> dict:
        rec = self.recommended
        return {
            "polls": self.polls,
            "errors": self.errors,
            "burn_fired": self.burn_fired,
            "burn_cleared": self.burn_cleared,
            "recommended_replicas": {
                "min": min(rec) if rec else None,
                "peak": max(rec) if rec else None,
                "last": rec[-1] if rec else None,
                "samples": len(rec),
            },
            "recommended_rose": bool(rec and max(rec) > min(rec)),
            "replicas_live_min": (
                min(self.live_replicas) if self.live_replicas else None
            ),
            "series_scrape_ok": self.series_scrape is not None,
        }


class FleetHandle:
    """What ``spawn_fleet`` yields: the router address plus the levers
    the drill pulls (kill a replica, read the supervision verdict)."""

    def __init__(self, host: str, port: int, supervisor,
                 router_proc: subprocess.Popen):
        self.host = host
        self.port = port
        self.supervisor = supervisor
        self.router_proc = router_proc

    def kill_replica(self, worker_id: int) -> int:
        """SIGKILL the CURRENT incarnation of a replica slot (ids are
        1..N); returns the killed pid.  The supervisor notices the
        nonzero exit and respawns into the same port."""
        slot = self.supervisor.slots[int(worker_id)]
        pid = slot.process.pid
        slot.process.kill()
        log.warning(
            f"fleet drill: SIGKILLed replica {worker_id} (pid {pid})"
        )
        return pid

    def router_stats(self, timeout_s: float = 10.0) -> dict:
        with ServingClient(self.host, self.port,
                           timeout_s=timeout_s) as client:
            return client.stats()


@contextlib.contextmanager
def spawn_fleet(replica_args: list[str], n: int, *,
                router_args: list[str] | None = None,
                max_respawns: int = 2,
                ready_timeout_s: float = 180.0,
                stop_timeout_s: float = 30.0):
    """Run N supervised replicas + a router; yields a
    :class:`FleetHandle` once the router reports ready (first pong).

    ``replica_args`` are the ``pdrnn-serve`` model/engine flags shared
    by every replica (the drill adds identity/port flags itself);
    ``router_args`` extend the ``pdrnn-router`` invocation."""
    if n < 1:
        raise ValueError(f"a fleet needs >= 1 replica, got {n}")
    with tempfile.TemporaryDirectory(prefix="pdrnn-fleet-") as tmp:
        tmpdir = Path(tmp)
        port_files = {
            k: tmpdir / f"replica-{k}.port" for k in range(1, n + 1)
        }
        learned: dict[int, tuple[str, int]] = {}

        def spawn_replica(rank: int, worker_id: int,
                          rejoin: bool) -> _PopenProc:
            cmd = [
                sys.executable, "-m",
                "pytorch_distributed_rnn_tpu.serving", "serve",
                *replica_args, "--replica-id", str(worker_id),
            ]
            if rejoin:
                # rebind the SAME learned port: the router's static
                # pool entry stays valid and half-open pings re-admit
                # the new incarnation without any re-registration
                host, port = learned[worker_id]
                cmd += ["--host", host, "--port", str(port)]
            else:
                cmd += ["--port", "0", "--port-file",
                        str(port_files[worker_id])]
            return _PopenProc(subprocess.Popen(cmd))

        supervisor = ReplicaSupervisor(
            spawn_replica, min_workers=1, max_respawns=max_respawns,
            respawn_delay_s=0.2,
        )
        router_proc = None
        stop_polling = threading.Event()
        try:
            supervisor.launch(range(1, n + 1))
            for worker_id, path in port_files.items():
                proc = supervisor.slots[worker_id].process
                host, port = _await_file(
                    path, f"replica {worker_id}", ready_timeout_s,
                    dead=lambda proc=proc: proc.exitcode,
                )
                learned[worker_id] = (host, int(port))

            router_port_file = tmpdir / "router.port"
            router_cmd = [
                sys.executable, "-m",
                "pytorch_distributed_rnn_tpu.serving.fleet",
                "--replica-port-files",
                ",".join(str(port_files[k]) for k in range(1, n + 1)),
                "--port", "0", "--port-file", str(router_port_file),
                *(router_args or []),
            ]
            router_proc = subprocess.Popen(router_cmd)
            host, port = _await_file(
                router_port_file, "router", ready_timeout_s,
                dead=router_proc.poll,
            )

            def poll_loop():
                while not stop_polling.wait(timeout=supervisor.poll_s):
                    if not supervisor.poll():
                        log.error("fleet drill: pool collapsed below "
                                  "the replica floor")
                        return

            poller = threading.Thread(
                target=poll_loop, name="pdrnn-fleet-supervise",
                daemon=True,
            )
            poller.start()
            yield FleetHandle(host, int(port), supervisor, router_proc)
        finally:
            if router_proc is not None and router_proc.poll() is None:
                router_proc.send_signal(signal.SIGTERM)
                try:
                    router_proc.wait(timeout=stop_timeout_s)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    router_proc.kill()
                    router_proc.wait()
            # stop supervision BEFORE terminating replicas: the SIGTERM
            # drain exits 0, but a still-running poll loop could race a
            # slot's reap against shutdown's
            stop_polling.set()
            supervisor.shutdown(timeout_s=stop_timeout_s)


def run_fleet_drill(replica_args: list[str], cfg: LoadConfig, *,
                    n: int = 3, kill_after_s: float | None = None,
                    kill_index: int = 1,
                    router_args: list[str] | None = None,
                    ready_timeout_s: float = 180.0) -> dict:
    """Fleet up, load through the router, optionally SIGKILL one
    replica mid-burst, fleet down.  Returns the load report extended
    with the drill verdict under ``fleet``:

    - ``accounting_ok``: client side (``done + shed + errors ==
      requests``) AND the router's ledger (``submitted == done +
      errors`` with sheds/drain rejections accounted at admission);
    - ``respawns``: supervisor respawn count (>= 1 when a kill was
      scheduled and landed);
    - ``window_closed``: the degradation window is bounded away from
      the run's end - service RECOVERED after the kill;
    - ``router`` / ``supervision``: the raw stats for the report file.
    """
    with spawn_fleet(
        replica_args, n, router_args=router_args,
        ready_timeout_s=ready_timeout_s,
    ) as fleet:
        probe = None
        live_port_file = _router_live_port_file(router_args)
        if live_port_file is not None:
            host, port = _await_file(
                live_port_file, "router live plane", ready_timeout_s,
                dead=fleet.router_proc.poll,
            )
            probe = _LiveProbe(host, int(port))
            probe.start()
        cfg = LoadConfig(**{**cfg.__dict__, "host": fleet.host,
                            "port": fleet.port})
        killed = {"pid": None}
        timer = None
        if kill_after_s is not None:
            timer = threading.Timer(
                float(kill_after_s),
                lambda: killed.update(
                    pid=fleet.kill_replica(kill_index)),
            )
            timer.daemon = True
            timer.start()
        try:
            report = run_load(cfg)
        finally:
            if timer is not None:
                timer.cancel()
        if probe is not None:
            # grace: the clear needs the fast burn window to slide
            # clean of the burst before the watchdog can emit it
            probe.finish()
        router_stats = fleet.router_stats()
        supervision = fleet.supervisor.verdict()
    router_stats.pop("event", None)
    client_ok = (
        report["done"] + report["shed"] + report["errors"]
        == report["requests"]
    )
    router_ok = (
        router_stats["submitted"]
        == router_stats["done"] + router_stats["errors"]
    )
    window = report["degradation_window_s"]
    # recovered = the last degraded second is strictly inside the run:
    # at least one CLEAN second followed it (a window butted against
    # the end of the load would mean we never saw the fleet healthy
    # again)
    window_closed = (
        window is None or window[1] < int(report["wall_s"]) - 1
        or report["timeline"][-1]["second"] > window[1]
    )
    report["fleet"] = {
        "replicas": n,
        "killed_pid": killed["pid"],
        "kill_after_s": kill_after_s,
        "respawns": supervision["respawns"],
        "accounting_ok": bool(client_ok and router_ok),
        "client_accounting_ok": bool(client_ok),
        "router_accounting_ok": bool(router_ok),
        "window_closed": bool(window_closed),
        "router": router_stats,
        "supervision": supervision,
        "router_exit": fleet.router_proc.returncode,
    }
    if probe is not None:
        report["fleet"]["live"] = probe.verdict()
    report["trace_handles"] = trace_handles(report)
    return report
