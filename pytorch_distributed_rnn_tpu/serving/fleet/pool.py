"""Health-checked replica pool: membership, load signal, circuit breaking.

The router's view of the fleet.  Each :class:`Replica` is an address
plus a ``dial`` factory producing one JSONL connection per dispatch
(the same connection-per-request shape as the load generator - tests
inject in-memory fakes through the same factory, so none of the breaker
or dispatch logic needs a socket to exercise).

Replica state machine (the classic circuit breaker, per replica)::

    healthy --[eject_after consecutive failures]--> open
    open    --[cooldown_s elapsed]---------------> half_open
    half_open --[half_open_probes ping successes
                 OR one successful trial request]-> healthy  (readmit)
    half_open --[any failure]--------------------> open      (re-open)
    any     --[drain()]--------------------------> draining  (never picked)

Failures are counted from BOTH paths that can observe one: the health
loop's periodic pings (a SIGKILLed replica is ejected without waiting
for traffic to hit it) and dispatch outcomes reported by the router
(``release(replica, ok=False)``).  Re-admission is symmetric: a
recovering replica comes back through half-open probing - consecutive
ping successes, or one successful trial request when the healthy set
is empty - never by silently resetting the breaker.

Load signal for least-loaded dispatch: the router's own in-flight count
per replica (always available) plus an optional ``load_hint(replica)``
callable the CLI wires to the live plane's aggregator digests (queue
depth + active slots from each replica's ``serving`` gauge block), so
a replica busy with OTHER clients' work is avoided even before this
router has sent it anything.

Locking: one pool lock (``fleet.pool`` via ``utils/threadcheck.lock``)
guards all mutable per-replica state; pings and dispatches - anything
that can block - run strictly outside it.
"""

from __future__ import annotations

import logging
import socket
import threading
import time

from pytorch_distributed_rnn_tpu.serving.protocol import (
    ProtocolError,
    decode_line,
    encode_line,
)
from pytorch_distributed_rnn_tpu.utils import threadcheck

log = logging.getLogger(__name__)

HEALTHY = "healthy"
OPEN = "open"
HALF_OPEN = "half_open"
DRAINING = "draining"

REPLICA_STATES = (HEALTHY, OPEN, HALF_OPEN, DRAINING)


class TcpReplicaConnection:
    """One dialed JSONL connection to a replica (the real transport
    behind a :class:`Replica`'s ``dial``; tests substitute in-memory
    fakes with the same ``send``/``recv``/``set_deadline``/``close``
    surface)."""

    def __init__(self, host: str, port: int,
                 connect_timeout_s: float = 2.0,
                 io_timeout_s: float = 30.0):
        self.sock = socket.create_connection(
            (host, port), timeout=connect_timeout_s
        )
        try:
            self.sock.settimeout(io_timeout_s)
            self._rfile = self.sock.makefile("r", encoding="utf-8")
        except Exception:
            self.sock.close()
            raise

    def send(self, obj: dict) -> None:
        self.sock.sendall(encode_line(obj))

    def recv(self) -> dict:
        line = self._rfile.readline()
        if not line:
            raise ProtocolError("replica closed the connection")
        return decode_line(line)

    def set_deadline(self, seconds: float) -> None:
        """Bound the NEXT read; the router re-arms this with the
        request's remaining deadline before every receive."""
        self.sock.settimeout(max(0.05, float(seconds)))

    def close(self) -> None:
        try:
            self._rfile.close()
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class Replica:
    """One pool member: identity + dial factory + breaker state.

    All mutable fields are guarded by the owning pool's lock."""

    def __init__(self, replica_id: int, host: str | None = None,
                 port: int | None = None, dial=None):
        self.replica_id = int(replica_id)
        self.host = host
        self.port = port
        if dial is None:
            if host is None or port is None:
                raise ValueError("a Replica needs host/port or a dial")
            dial = (
                lambda connect_timeout_s=2.0, io_timeout_s=30.0:
                TcpReplicaConnection(
                    host, int(port), connect_timeout_s=connect_timeout_s,
                    io_timeout_s=io_timeout_s,
                )
            )
        self.dial = dial
        self.state = HEALTHY
        self.inflight = 0
        self.consecutive_failures = 0
        self.opened_tm: float | None = None
        self.trial_inflight = False
        self.probe_successes = 0
        self.dispatched = 0
        self.failures = 0
        self.ejections = 0
        self.readmissions = 0
        self.info: dict = {}
        self.last_pong_tm: float | None = None


class ReplicaPool:
    """The router's replica set: health loop + breaker + pick/release."""

    def __init__(self, replicas, *, eject_after: int = 3,
                 cooldown_s: float = 2.0, half_open_probes: int = 2,
                 health_every_s: float = 0.5,
                 connect_timeout_s: float = 2.0,
                 ping_timeout_s: float = 2.0,
                 load_hint=None, on_event=None):
        """``on_event(kind, **fields)`` observes breaker transitions
        (``replica_eject`` / ``replica_probe`` / ``replica_readmit``) -
        the router wires it to its recorder so the transitions land on
        the ``router`` timeline lane.  Hook failures are swallowed."""
        replicas = list(replicas)
        self.replicas: dict[int, Replica] = {
            r.replica_id: r for r in replicas
        }
        if len(self.replicas) != len(replicas):
            raise ValueError("replica ids must be unique")
        self.eject_after = int(eject_after)
        self.cooldown_s = float(cooldown_s)
        self.half_open_probes = int(half_open_probes)
        self.health_every_s = float(health_every_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.ping_timeout_s = float(ping_timeout_s)
        self.load_hint = load_hint
        self._on_event = on_event
        self._lock = threadcheck.lock(threading.Lock(), "fleet.pool")
        self._ready = threading.Event()  # first pong seen
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- events --------------------------------------------------------------

    def _emit(self, events) -> None:
        """Fire queued (kind, fields) transitions - strictly OUTSIDE the
        pool lock (observers record to sidecars / push digests)."""
        if self._on_event is None:
            return
        for kind, fields in events:
            try:
                self._on_event(kind, **fields)
            except Exception:  # observability must never kill routing
                log.exception(f"fleet: on_event({kind}) hook failed")

    # -- breaker transitions (all called under the pool lock) ----------------

    def _advance_breakers_locked(self, now: float) -> list:
        events = []
        for replica in self.replicas.values():
            if replica.state == OPEN and replica.opened_tm is not None \
                    and now - replica.opened_tm >= self.cooldown_s:
                replica.state = HALF_OPEN
                replica.probe_successes = 0
                replica.trial_inflight = False
                events.append(("replica_probe", {
                    "replica": replica.replica_id, "phase": "half_open",
                }))
        return events

    def _mark_failure_locked(self, replica: Replica, now: float,
                             reason: str) -> list:
        replica.consecutive_failures += 1
        replica.failures += 1
        replica.probe_successes = 0
        if replica.state == HALF_OPEN:
            # the probe/trial failed: straight back to open, fresh
            # cooldown - a flapping replica never oscillates into the
            # healthy set
            replica.state = OPEN
            replica.opened_tm = now
            return [("replica_eject", {
                "replica": replica.replica_id, "reason": reason,
                "reopened": True,
            })]
        if replica.state == HEALTHY \
                and replica.consecutive_failures >= self.eject_after:
            replica.state = OPEN
            replica.opened_tm = now
            replica.ejections += 1
            return [("replica_eject", {
                "replica": replica.replica_id, "reason": reason,
                "consecutive_failures": replica.consecutive_failures,
            })]
        return []

    def _mark_success_locked(self, replica: Replica, via: str) -> list:
        replica.consecutive_failures = 0
        if replica.state == HALF_OPEN:
            replica.state = HEALTHY
            replica.readmissions += 1
            return [("replica_readmit", {
                "replica": replica.replica_id, "via": via,
            })]
        return []

    # -- dispatch interface --------------------------------------------------

    def _load_key(self, replica: Replica):  # holds: _lock
        hint = 0.0
        if self.load_hint is not None:
            try:
                hint = float(self.load_hint(replica) or 0.0)
            except Exception:  # hint sources must not kill dispatch
                hint = 0.0
        return (replica.inflight + hint, replica.replica_id)

    def pick(self, exclude=()) -> Replica | None:
        """Reserve the least-loaded healthy replica (a pick increments
        its in-flight count atomically - callers MUST ``release``).

        ``exclude`` holds replica ids already tried for this request
        (retry/hedge siblings); when every healthy replica is excluded
        the exclusion is dropped rather than failing the request - a
        retry against the same replica beats no retry at all.  With no
        healthy replica, a half-open one may take a single in-flight
        TRIAL request (the request-path half of half-open probing)."""
        exclude = set(exclude)
        now = time.monotonic()
        with self._lock:
            events = self._advance_breakers_locked(now)
            healthy = [r for r in self.replicas.values()
                       if r.state == HEALTHY]
            fresh = [r for r in healthy if r.replica_id not in exclude]
            candidates = fresh or healthy
            picked = None
            if candidates:
                picked = min(candidates, key=self._load_key)
            else:
                trials = sorted(
                    (r for r in self.replicas.values()
                     if r.state == HALF_OPEN and not r.trial_inflight),
                    key=lambda r: (r.replica_id in exclude,
                                   r.replica_id),
                )
                if trials:
                    picked = trials[0]
                    picked.trial_inflight = True
                    events.append(("replica_probe", {
                        "replica": picked.replica_id, "phase": "trial",
                    }))
            if picked is not None:
                picked.inflight += 1
                picked.dispatched += 1
        self._emit(events)
        return picked

    def release(self, replica: Replica, ok: bool | None) -> None:
        """Return a pick: ``ok=True`` feeds the breaker a success,
        ``ok=False`` a failure, ``ok=None`` is neutral (a cancelled
        hedge loser - the replica did nothing wrong)."""
        now = time.monotonic()
        with self._lock:
            replica.inflight = max(0, replica.inflight - 1)
            replica.trial_inflight = False
            if ok is True:
                events = self._mark_success_locked(replica, via="request")
            elif ok is False:
                events = self._mark_failure_locked(replica, now,
                                                   reason="dispatch")
            else:
                events = []
        self._emit(events)

    def drain(self, replica_id: int) -> None:
        """Mark a replica draining: never picked again (its own server
        finishes what it already owns)."""
        with self._lock:
            replica = self.replicas[int(replica_id)]
            replica.state = DRAINING
        self._emit([("replica_drain", {"replica": int(replica_id)})])

    # -- health loop ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._health_loop, name="pdrnn-router-health",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def wait_ready(self, timeout_s: float = 30.0) -> bool:
        """Block until at least one replica has answered a ping (the
        router CLI gates its port-file write on this, so a client that
        can connect can also be served a pong)."""
        return self._ready.wait(timeout=timeout_s)

    def _health_loop(self) -> None:
        self.check_once()  # immediate first pass: readiness without
        # waiting out one full cadence
        while not self._stop.wait(timeout=self.health_every_s):
            self.check_once()

    def check_once(self) -> None:
        """One health pass: ping every non-draining replica, feed the
        breaker, advance cooldowns.  Pings run OUTSIDE the pool lock."""
        now = time.monotonic()
        with self._lock:
            events = self._advance_breakers_locked(now)
            targets = [r for r in self.replicas.values()
                       if r.state != DRAINING]
        self._emit(events)
        for replica in targets:
            ok, info = self._ping(replica)
            now = time.monotonic()
            with self._lock:
                if ok:
                    replica.last_pong_tm = now
                    replica.info = info or {}
                    replica.consecutive_failures = 0
                    if replica.state == HALF_OPEN:
                        replica.probe_successes += 1
                        events = [("replica_probe", {
                            "replica": replica.replica_id,
                            "phase": "ping", "ok": True,
                            "successes": replica.probe_successes,
                        })]
                        if replica.probe_successes \
                                >= self.half_open_probes:
                            replica.state = HEALTHY
                            replica.readmissions += 1
                            events.append(("replica_readmit", {
                                "replica": replica.replica_id,
                                "via": "ping_probes",
                            }))
                    else:
                        events = []
                else:
                    events = self._mark_failure_locked(replica, now,
                                                       reason="ping")
            self._emit(events)
            if ok:
                self._ready.set()

    def _ping(self, replica: Replica) -> tuple[bool, dict | None]:
        try:
            conn = replica.dial(
                connect_timeout_s=self.connect_timeout_s,
                io_timeout_s=self.ping_timeout_s,
            )
        except (OSError, ProtocolError):
            return False, None
        try:
            conn.send({"op": "ping"})  # protocol: serve request ping
            reply = conn.recv()
            if reply.get("event") != "pong":
                return False, None
            return True, reply
        except (OSError, ProtocolError, ValueError):
            return False, None
        finally:
            conn.close()

    # -- views ---------------------------------------------------------------

    def pong_info(self) -> dict | None:
        """The most recent pong payload of any replica (healthy
        preferred) - the router's own ping reply is built from it."""
        with self._lock:
            ordered = sorted(
                (r for r in self.replicas.values() if r.info),
                key=lambda r: (r.state != HEALTHY, r.replica_id),
            )
            return dict(ordered[0].info) if ordered else None

    def state_counts(self) -> dict:
        counts = dict.fromkeys(REPLICA_STATES, 0)
        with self._lock:
            for replica in self.replicas.values():
                counts[replica.state] += 1
        return counts

    def snapshot(self) -> dict:
        """Per-replica detail + state counts (the router's stats op)."""
        with self._lock:
            members = [
                {
                    "replica": r.replica_id, "host": r.host,
                    "port": r.port, "state": r.state,
                    "inflight": r.inflight,
                    "dispatched": r.dispatched, "failures": r.failures,
                    "consecutive_failures": r.consecutive_failures,
                    "ejections": r.ejections,
                    "readmissions": r.readmissions,
                }
                for r in sorted(self.replicas.values(),
                                key=lambda r: r.replica_id)
            ]
        counts = dict.fromkeys(REPLICA_STATES, 0)
        for member in members:
            counts[member["state"]] += 1
        return {
            "replicas": members, "states": counts,
            "ejections": sum(m["ejections"] for m in members),
            "readmissions": sum(m["readmissions"] for m in members),
        }
