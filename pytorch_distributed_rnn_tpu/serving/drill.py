"""Chaos SLO drill: a server under `resilience/` faults meets traffic.

``spawn_server`` runs ``pdrnn-serve`` as a subprocess (the deployment
shape - the drill must prove the PROCESS survives, so in-process
threads would not do), waits for the port file, and tears it down with
SIGTERM on exit - asserting a clean exit code, because graceful
shutdown under chaos is part of the contract.

``run_drill`` is the end-to-end scenario the CI job and
``pdrnn-loadgen --spawn-server`` share: start a server (typically with
``--faults 'step:N:stall:S'``), drive the configured load, and return
``(report, server_exit_code)``.  The report's per-second timeline shows
the degradation window the fault opened; the drill's acceptance is that
the window CLOSES - load is shed or queued while the fault holds, and
service recovers when it passes.

A failed drill is actionable, not just red: the report names the
slowest and every SLO-violating request (``slowest`` /
``slo_violations``, each with its request id and - when tracing is on -
trace id), and ``trace_handles`` collects the distinct trace ids to
pull with ``pdrnn-metrics trace``.
"""

from __future__ import annotations

import contextlib
import signal
import subprocess
import sys
import tempfile
import time
from pathlib import Path

from pytorch_distributed_rnn_tpu.serving.loadgen import LoadConfig, run_load


class ServerSpawnError(RuntimeError):
    """The spawned server died or never became ready."""


@contextlib.contextmanager
def spawn_server(serve_args: list[str], *, ready_timeout_s: float = 120.0,
                 stop_timeout_s: float = 30.0):
    """Run ``pdrnn-serve <serve_args>`` in a subprocess.

    Yields ``(host, port, proc)`` once the server wrote its port file;
    on exit sends SIGTERM and waits.  ``proc.returncode`` is available
    after the ``with`` block; callers asserting graceful shutdown check
    it is 0.
    """
    with tempfile.TemporaryDirectory(prefix="pdrnn-serve-") as tmp:
        port_file = Path(tmp) / "port"
        cmd = [
            sys.executable, "-m", "pytorch_distributed_rnn_tpu.serving",
            "serve", *serve_args, "--port-file", str(port_file),
        ]
        proc = subprocess.Popen(cmd)
        try:
            deadline = time.monotonic() + ready_timeout_s
            while not port_file.exists():
                if proc.poll() is not None:
                    raise ServerSpawnError(
                        f"server exited with {proc.returncode} before "
                        f"becoming ready: {' '.join(cmd)}"
                    )
                if time.monotonic() > deadline:
                    raise ServerSpawnError(
                        f"server not ready after {ready_timeout_s}s"
                    )
                time.sleep(0.05)
            host, port = port_file.read_text().split()
            yield host, int(port), proc
        finally:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=stop_timeout_s)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    proc.kill()
                    proc.wait()


def run_drill(serve_args: list[str], cfg: LoadConfig,
              ready_timeout_s: float = 120.0) -> tuple[dict, int]:
    """Spawn, load, tear down.  Returns ``(report, server_exit_code)``
    with ``report['server_exit']`` filled in too."""
    with spawn_server(
        serve_args, ready_timeout_s=ready_timeout_s
    ) as (host, port, proc):
        cfg = LoadConfig(**{**cfg.__dict__, "host": host, "port": port})
        report = run_load(cfg)
    report["server_exit"] = proc.returncode
    report["server_pid"] = proc.pid
    report["trace_handles"] = trace_handles(report)
    return report, proc.returncode


def trace_handles(report: dict) -> list[str]:
    """The distinct trace ids a failed drill should pull with
    ``pdrnn-metrics trace``: slowest requests first, then every SLO
    violation (order-preserving dedup)."""
    handles: list[str] = []
    for entry in [*report.get("slowest", ()),
                  *report.get("slo_violations", ())]:
        trace_id = entry.get("trace_id")
        if trace_id and trace_id not in handles:
            handles.append(trace_id)
    return handles
