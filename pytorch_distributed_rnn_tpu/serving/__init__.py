"""Continuous-batching inference serving.

The repo's first non-training workload (ROADMAP open item 1): load a
crash-safe checkpoint (``training/checkpoint.py``), accept generation
requests over a JSON-lines TCP protocol, and decode them in
continuously-batched jitted steps - new requests join the in-flight
batch at step boundaries, finished sequences leave, and freed slots
refill without restarting decode.  Padded bucket shapes (batch slots +
prompt-length buckets) keep steady-state serving retrace-free; the
decode entries are registered in ``lint/trace_registry.py`` so the
jaxpr deep pass covers them like every trainer step.

Layering (each importable without the ones above it):

- :mod:`.buckets`    - prompt-length bucket policy (pure, no jax)
- :mod:`.scheduler`  - the continuous-batching core (pure, no jax):
  admission / shedding, FIFO slot assignment at step boundaries
- :mod:`.adapters`   - per-family prefill / decode-step programs
  sharing the reference ``generate`` math bit for bit
- :mod:`.engine`     - jitted execution + sampling + telemetry
- :mod:`.server`     - the TCP JSON-lines server (``pdrnn-serve``)
- :mod:`.loadgen`    - Poisson load generator + SLO report
  (``pdrnn-loadgen``), chaos SLO drill via ``--spawn-server``
"""

from pytorch_distributed_rnn_tpu.serving.buckets import BucketSpec
from pytorch_distributed_rnn_tpu.serving.scheduler import (
    ContinuousBatcher,
    ServeRequest,
)

__all__ = ["BucketSpec", "ContinuousBatcher", "ServeRequest"]
