"""The TCP serving front end: connections in, engine slots out.

Thread layout: one accept loop, one engine loop
(``ServingEngine.serve_forever``), and one reader thread per client
connection.  Connection threads only PARSE and ENQUEUE - all device
work happens on the engine thread, so a slow or hostile client can
never stall decode.  Responses are written from the engine thread via
per-connection locked callbacks; a dead client's writes are dropped
(the request still completes and is accounted - its slot must free
either way).

Graceful shutdown (``shutdown()``, wired to SIGTERM/SIGINT by the CLI):
stop accepting, fail queued requests, finish nothing mid-step, emit the
``run_summary`` telemetry event and close the recorder - so a drill's
``kill -TERM`` still yields a summarizable metrics sidecar.
"""

from __future__ import annotations

import itertools
import json
import logging
import socket
import threading

from pytorch_distributed_rnn_tpu.serving.protocol import (
    encode_line,
    text_to_tokens,
    tokens_to_text,
)
from pytorch_distributed_rnn_tpu.serving.scheduler import ServeRequest
from pytorch_distributed_rnn_tpu.utils import threadcheck

log = logging.getLogger(__name__)


class ServingServer:
    """JSONL-over-TCP front end for one :class:`ServingEngine`."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 model_name: str = "?", recorder=None):
        self.engine = engine
        self.model_name = model_name
        self.recorder = recorder
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self.host, self.port = self._listener.getsockname()[:2]
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._ids = itertools.count()
        self._started = False

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        """Spawn the engine and accept threads; returns immediately."""
        if self._started:
            return
        self._started = True
        engine_thread = threading.Thread(
            target=self.engine.serve_forever, args=(self._stop,),
            name="pdrnn-serve-engine", daemon=True,
        )
        accept_thread = threading.Thread(
            target=self._accept_loop, name="pdrnn-serve-accept", daemon=True,
        )
        self._threads = [engine_thread, accept_thread]
        engine_thread.start()
        accept_thread.start()
        log.info(f"pdrnn-serve: listening on {self.host}:{self.port}")

    def shutdown(self):
        """Stop accepting, stop the engine loop, flush telemetry;
        idempotent and safe from signal handlers' main thread."""
        if self._stop.is_set():
            return
        self._stop.set()
        try:
            self._listener.close()
        except OSError:  # pragma: no cover - already closed
            pass
        for thread in self._threads:
            thread.join(timeout=10.0)
        self.engine.close()
        if self.recorder is not None:
            self.recorder.close()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.shutdown()

    # -- accept / connection side --------------------------------------------

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _addr = self._listener.accept()
            except OSError:  # listener closed = shutdown
                return
            handler = threading.Thread(
                target=self._handle, args=(conn,),
                name="pdrnn-serve-conn", daemon=True,
            )
            handler.start()

    def _handle(self, conn: socket.socket):
        wlock = threadcheck.lock(threading.Lock(), "server.conn.write")
        alive = {"ok": True}

        def send(obj: dict):
            # engine-thread callbacks and the reader both write here; a
            # vanished client must not take the engine down with it
            with wlock:
                if not alive["ok"]:
                    return
                try:
                    conn.sendall(encode_line(obj))
                except OSError:
                    alive["ok"] = False

        rfile = conn.makefile("r", encoding="utf-8")
        try:
            for line in rfile:
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                    if not isinstance(msg, dict):
                        raise ValueError("messages are JSON objects")
                except ValueError as exc:
                    send({"event": "error", "error": f"bad request: {exc}"})
                    continue
                self._dispatch(msg, send)
                if self._stop.is_set():
                    break
        except OSError:
            pass
        finally:
            alive["ok"] = False
            try:
                rfile.close()
            finally:
                conn.close()

    # -- ops -----------------------------------------------------------------

    def _dispatch(self, msg: dict, send):
        op = msg.get("op")
        if op == "ping":
            send({
                "event": "pong", "model": self.model_name,
                "vocab_size": self.engine.adapter.vocab_size,
                "max_prompt_len": self.engine.buckets.max_prompt_len,
                "prompt_buckets": list(self.engine.buckets.prompt_buckets),
                "max_new_tokens": self.engine.max_new_tokens,
                "slots": self.engine.batcher.num_slots,
            })
        elif op == "stats":
            stats = self.engine.stats()
            stats.pop("trace_counts", None)
            send({"event": "stats", **stats})
        elif op == "generate":
            self._generate(msg, send)
        else:
            send({
                "id": msg.get("id"), "event": "error",
                "error": f"unknown op {op!r} (generate|ping|stats)",
            })

    def _generate(self, msg: dict, send):
        request_id = str(msg.get("id", next(self._ids)))
        used_text = "text" in msg
        try:
            if used_text:
                if self.engine.adapter.vocab_size < 256:
                    raise ValueError(
                        "text prompts need a byte vocab (>= 256 ids); "
                        "this model serves token-id prompts only"
                    )
                prompt = text_to_tokens(str(msg["text"]))
            else:
                prompt = [int(t) for t in msg.get("prompt", [])]
            if any(not 0 <= t < self.engine.adapter.vocab_size
                   for t in prompt):
                raise ValueError(
                    f"prompt ids must be in [0, "
                    f"{self.engine.adapter.vocab_size})"
                )
            max_new = int(msg.get("max_new_tokens", 16))
            temperature = float(msg.get("temperature", 0.0))
            seed = int(msg.get("seed", next(self._ids)))
            stream = bool(msg.get("stream", False))
        except (TypeError, ValueError) as exc:
            send({"id": request_id, "event": "error",
                  "error": f"bad generate request: {exc}"})
            return

        def on_token(request: ServeRequest, token: int):
            if request.stream:
                send({
                    "id": request_id, "event": "token",
                    "index": len(request.tokens) - 1, "token": token,
                })

        def on_done(request: ServeRequest):
            if request.status != "done":
                send({
                    "id": request_id, "event": "error",
                    "error": request.error or request.status,
                    "shed": request.status == "shed",
                })
                return
            payload = {
                "id": request_id, "event": "done", "status": "done",
                "tokens": request.tokens,
                "token_count": len(request.tokens),
                "latency_ms": _ms(request.latency_s),
                "ttft_ms": _ms(request.ttft_s),
                "queue_ms": _ms(request.queue_wait_s),
                "seed": seed,
            }
            if used_text:
                payload["text"] = tokens_to_text(request.tokens)
            send(payload)

        request = ServeRequest(
            prompt=prompt, max_new_tokens=max_new, temperature=temperature,
            seed=seed, id=request_id, stream=stream,
            on_token=on_token, on_done=on_done,
        )
        if not self.engine.submit(request):
            send({
                "id": request_id, "event": "error",
                "error": request.error or "queue full - request shed",
                "shed": request.status == "shed",
            })


def _ms(seconds: float | None) -> float | None:
    return None if seconds is None else round(seconds * 1e3, 3)
