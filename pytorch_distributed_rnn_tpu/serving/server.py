"""The TCP serving front end: connections in, engine slots out.

Thread layout: one accept loop, one engine loop
(``ServingEngine.serve_forever``), and one reader thread per client
connection.  Connection threads only PARSE and ENQUEUE - all device
work happens on the engine thread, so a slow or hostile client can
never stall decode.  Responses are written from the engine thread via
per-connection locked callbacks; a dead client's writes are dropped
(the request still completes and is accounted - its slot must free
either way).

Graceful shutdown (``shutdown()``, wired to SIGTERM/SIGINT by the CLI):
stop accepting, fail queued requests, finish nothing mid-step, emit the
``run_summary`` telemetry event and close the recorder - so a drill's
``kill -TERM`` still yields a summarizable metrics sidecar.  Fleet
replicas drain instead (``shutdown(drain=True)``): stop accepting and
reject NEW generates, but let the engine finish everything already
queued or decoding before the loop stops - the router reroutes fresh
traffic while this replica completes what it owns.

Fleet membership (``serving/fleet/``): with a ``pusher`` (the live
plane's :class:`~pytorch_distributed_rnn_tpu.obs.live.EventPusher`
``push``) the server announces ``replica_register`` on start and
``replica_drain`` on teardown through the aggregator's ``/events``,
so the router's pool view and ``pdrnn-metrics watch`` agree on who is
in the fleet.  ``flap_s`` (the ``net:flap:<s>`` chaos action via
``PDRNN_FAULT_FLAP_S``) drops every open client connection each
period - the flaky-replica mode, distinct from kill: the process and
its engine survive, its connections do not.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import socket
import threading
import time

from pytorch_distributed_rnn_tpu.obs.tracectx import TraceContext
from pytorch_distributed_rnn_tpu.resilience.faults import FAULT_FLAP_ENV
from pytorch_distributed_rnn_tpu.serving.protocol import (
    encode_line,
    text_to_tokens,
    tokens_to_text,
)
from pytorch_distributed_rnn_tpu.serving.scheduler import ServeRequest
from pytorch_distributed_rnn_tpu.utils import leakcheck, threadcheck

log = logging.getLogger(__name__)


class ServingServer:
    """JSONL-over-TCP front end for one :class:`ServingEngine`."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 model_name: str = "?", recorder=None, pusher=None,
                 replica_id: int | None = None,
                 flap_s: float | None = None):
        self.engine = engine
        self.model_name = model_name
        self.recorder = recorder
        self.pusher = pusher
        self.replica_id = replica_id
        if flap_s is None:
            flap_s = float(os.environ.get(FAULT_FLAP_ENV, 0) or 0)
        self.flap_s = float(flap_s)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            self._listener.setsockopt(
                socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._listener.bind((host, port))
            self._listener.listen(128)
            self.host, self.port = self._listener.getsockname()[:2]
        except Exception:
            self._listener.close()
            raise
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._conns_lock = threadcheck.lock(threading.Lock(), "server.conns")  # guards: _conns
        self._conns: set[socket.socket] = set()
        self._threads: list[threading.Thread] = []
        self._ids = itertools.count()
        self._started = False

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        """Spawn the engine and accept threads; returns immediately."""
        if self._started:
            return
        self._started = True
        engine_thread = threading.Thread(
            target=self.engine.serve_forever, args=(self._stop,),
            name="pdrnn-serve-engine", daemon=True,
        )
        accept_thread = threading.Thread(
            target=self._accept_loop, name="pdrnn-serve-accept", daemon=True,
        )
        self._threads = [engine_thread, accept_thread]
        if self.flap_s > 0:
            log.warning(
                f"pdrnn-serve: net:flap:{self.flap_s:g} active - dropping "
                f"every open connection each {self.flap_s:g}s"
            )
            self._threads.append(threading.Thread(
                target=self._flap_loop, name="pdrnn-serve-flap",
                daemon=True,
            ))
        for thread in self._threads:
            thread.start()
        if self.pusher is not None:
            self.pusher(
                "replica_register", severity="info",
                replica=self.replica_id, host=self.host, port=self.port,
                model=self.model_name,
            )
        log.info(f"pdrnn-serve: listening on {self.host}:{self.port}")

    def shutdown(self, drain: bool = False,
                 drain_timeout_s: float = 30.0):
        """Stop accepting, stop the engine loop, flush telemetry;
        idempotent and safe from signal handlers' main thread.

        With ``drain=True`` (the fleet replica's SIGTERM path): reject
        new generates, keep the engine stepping until everything queued
        or in-flight completes (bounded by ``drain_timeout_s``), then
        stop - and DEREGISTER through the ``replica_drain`` heartbeat."""
        if self._stop.is_set():
            return
        self._draining.set()
        try:
            self._listener.close()
        except OSError:  # pragma: no cover - already closed
            pass
        if drain:
            deadline = time.monotonic() + float(drain_timeout_s)
            while self.engine.batcher.has_work \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
        drained_clean = not self.engine.batcher.has_work
        self._stop.set()
        for thread in self._threads:
            thread.join(timeout=10.0)
        self.engine.close()
        # force-drop any client connection whose reader has not exited
        # yet: after this, nothing of ours may still hold a socket -
        # which is exactly what the leak sentinel now verifies
        with self._conns_lock:
            victims = list(self._conns)
            self._conns.clear()
        for sock in victims:
            try:
                sock.close()
            except OSError:  # pragma: no cover - already closed
                pass
        leakcheck.check_drained("serve.shutdown")
        if self.pusher is not None:
            self.pusher(
                "replica_drain", severity="info",
                replica=self.replica_id, host=self.host, port=self.port,
                drained_clean=drained_clean,
            )
        if self.recorder is not None:
            self.recorder.close()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.shutdown()

    # -- accept / connection side --------------------------------------------

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                # deadline-free by contract: shutdown() closing the
                # listener unblocks this accept with OSError
                conn, _addr = self._listener.accept()  # noqa: PD402
            except OSError:  # listener closed = shutdown
                return
            handler = threading.Thread(
                target=self._handle, args=(conn,),
                name="pdrnn-serve-conn", daemon=True,
            )
            handler.start()

    def _flap_loop(self):
        """The ``net:flap:<s>`` chaos action: every period, drop every
        open client connection (mid-request or idle) while the listener
        keeps accepting - peers see ECONNRESET/EOF, exactly what a
        flaky replica or link looks like from the router's side."""
        while not self._stop.wait(timeout=self.flap_s):
            with self._conns_lock:
                victims = list(self._conns)
            for sock in victims:
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            if victims:
                log.warning(
                    f"pdrnn-serve: net:flap dropped {len(victims)} "
                    f"connection(s)"
                )

    def _handle(self, conn: socket.socket):
        wlock = threadcheck.lock(threading.Lock(), "server.conn.write")
        alive = {"ok": True}
        with self._conns_lock:
            self._conns.add(conn)

        def send(obj: dict):
            # engine-thread callbacks and the reader both write here; a
            # vanished client must not take the engine down with it
            with wlock:
                if not alive["ok"]:
                    return
                try:
                    # client-paced by contract: a timeout here would
                    # drop slow-but-alive clients; dead peers surface
                    # as OSError/flap and just mark the conn down
                    conn.sendall(encode_line(obj))  # noqa: PD402
                except OSError:
                    alive["ok"] = False

        rfile = conn.makefile("r", encoding="utf-8")
        try:
            for line in rfile:
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                    if not isinstance(msg, dict):
                        raise ValueError("messages are JSON objects")
                except ValueError as exc:
                    send({"event": "error", "error": f"bad request: {exc}"})
                    continue
                self._dispatch(msg, send)
                if self._stop.is_set():
                    break
        except OSError:
            pass
        finally:
            alive["ok"] = False
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                rfile.close()
            finally:
                conn.close()

    # -- ops -----------------------------------------------------------------

    def _dispatch(self, msg: dict, send):
        # protocol: serve handles ping, stats, generate
        op = msg.get("op")
        if op == "ping":
            # protocol: serve reply ping
            send({
                "event": "pong", "model": self.model_name,
                "vocab_size": self.engine.adapter.vocab_size,
                "max_prompt_len": self.engine.buckets.max_prompt_len,
                "prompt_buckets": list(self.engine.buckets.prompt_buckets),
                "max_new_tokens": self.engine.max_new_tokens,
                "slots": self.engine.batcher.num_slots,
            })
        elif op == "stats":
            stats = self.engine.stats()
            stats.pop("trace_counts", None)
            send({"event": "stats", **stats})  # protocol: serve reply stats
        elif op == "generate":
            # protocol: serve reply generate - done/error/token events
            # (the draining rejection below and every _generate exit)
            if self._draining.is_set():
                # a draining replica finishes what it owns but accepts
                # nothing new - an EXPLICIT rejection (never a silent
                # drop) the router reads as "dispatch elsewhere"
                send({
                    "id": str(msg.get("id", "")), "event": "error",
                    "error": "replica draining - not accepting requests",
                    "draining": True,
                })
                return
            self._generate(msg, send)
        else:
            send({
                "id": msg.get("id"), "event": "error",
                "error": f"unknown op {op!r} (generate|ping|stats)",
            })

    def _generate(self, msg: dict, send):
        request_id = str(msg.get("id", next(self._ids)))
        used_text = "text" in msg
        try:
            if used_text:
                if self.engine.adapter.vocab_size < 256:
                    raise ValueError(
                        "text prompts need a byte vocab (>= 256 ids); "
                        "this model serves token-id prompts only"
                    )
                prompt = text_to_tokens(str(msg["text"]))
            else:
                prompt = [int(t) for t in msg.get("prompt", [])]
            if any(not 0 <= t < self.engine.adapter.vocab_size
                   for t in prompt):
                raise ValueError(
                    f"prompt ids must be in [0, "
                    f"{self.engine.adapter.vocab_size})"
                )
            max_new = int(msg.get("max_new_tokens", 16))
            temperature = float(msg.get("temperature", 0.0))
            seed = int(msg.get("seed", next(self._ids)))
            stream = bool(msg.get("stream", False))
        except (TypeError, ValueError) as exc:
            send({"id": request_id, "event": "error",
                  "error": f"bad generate request: {exc}"})
            return

        def on_token(request: ServeRequest, token: int):
            if request.stream:
                send({
                    "id": request_id, "event": "token",
                    "index": len(request.tokens) - 1, "token": token,
                })

        def on_done(request: ServeRequest):
            if request.status != "done":
                send({
                    "id": request_id, "event": "error",
                    "error": request.error or request.status,
                    "shed": request.status == "shed",
                })
                return
            payload = {
                "id": request_id, "event": "done", "status": "done",
                "tokens": request.tokens,
                "token_count": len(request.tokens),
                "latency_ms": _ms(request.latency_s),
                "ttft_ms": _ms(request.ttft_s),
                "queue_ms": _ms(request.queue_wait_s),
                "seed": seed,
            }
            if used_text:
                payload["text"] = tokens_to_text(request.tokens)
            send(payload)

        # distributed tracing: adopt the sender's context only when this
        # replica actually records spans - otherwise no TraceContext is
        # ever constructed on the untraced/unrecorded path (the
        # zero-overhead-off pin); malformed contexts parse to None and
        # never fail the request
        trace = None
        if "trace" in msg and self.engine.recorder.enabled:
            # protocol: serve field trace
            trace = TraceContext.from_wire(msg.get("trace"))
        request = ServeRequest(
            prompt=prompt, max_new_tokens=max_new, temperature=temperature,
            seed=seed, id=request_id, stream=stream, trace=trace,
            on_token=on_token, on_done=on_done,
        )
        if not self.engine.submit(request):
            send({
                "id": request_id, "event": "error",
                "error": request.error or "queue full - request shed",
                "shed": request.status == "shed",
            })


def _ms(seconds: float | None) -> float | None:
    return None if seconds is None else round(seconds * 1e3, 3)
