"""``pdrnn-serve`` and ``pdrnn-loadgen`` console entry points.

Serve::

  pdrnn-serve --checkpoint models/ --model char --hidden-units 32 \\
      --stacked-layer 2 --port 7071 --metrics serve-metrics.jsonl

The model flags mirror the training CLI's family surface
(``families.build_model``): a checkpoint only stores arrays, so the
server reconstructs the architecture from the same flags the training
run used and loads the model section of the newest valid checkpoint
(``--checkpoint`` may be the file or the training
``--checkpoint-directory``).  ``--faults`` accepts the chaos grammar of
``resilience/faults.py`` - the SLO drill injects stalls/NaN through it.

Load::

  pdrnn-loadgen --connect 127.0.0.1:7071 --requests 100 --rate 40 \\
      --slo-p95-ms 500 --report report.json
  pdrnn-loadgen --spawn-server "--checkpoint models/ --model char \\
      --hidden-units 32 --faults step:60:stall:2" --requests 120

``--spawn-server`` runs the chaos SLO drill: server subprocess up, load
through it, SIGTERM down, report (incl. the degradation window and the
server's exit code) out.  Exit codes: 0 = SLO pass, 1 = SLO fail /
errors, 2 = usage or spawn failure.

``--spawn-fleet N`` runs the kill-mid-burst fleet drill
(``serving/fleet/drill.py``): N supervised replicas behind a
``pdrnn-router``, one SIGKILLed mid-burst, and the verdict is graceful
degradation - rerouting, exactly-once accounting, a CLOSED degradation
window - instead of a bare SLO pass::

  pdrnn-loadgen --spawn-fleet 3 --replica-args "--checkpoint models/ \\
      --model char --hidden-units 32" --fleet-kill-after-s 2 \\
      --requests 120 --rate 40
"""

from __future__ import annotations

import argparse
import json
import logging
import shlex
import signal
import sys
import threading
from pathlib import Path

from pytorch_distributed_rnn_tpu.utils import leakcheck

log = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# pdrnn-serve


def build_serve_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="pdrnn-serve",
        description="continuous-batching inference server",
    )
    parser.add_argument(
        "--checkpoint", required=True, type=Path, metavar="PATH",
        help="checkpoint file, or a training --checkpoint-directory (the "
        "newest VALID checkpoint is used, corrupt files skipped)",
    )
    parser.add_argument(
        "--model", default="char", choices=["char", "attention", "moe"],
        help="served family: the char LM (CharRNN), the attention LM "
        "(AttentionLM - KV-cache decode), or the MoE LM (MoELM - dense "
        "token-choice routing)",
    )
    parser.add_argument("--vocab-size", default=256, type=int)
    parser.add_argument(
        "--hidden-units", default=32, type=int,
        help="hidden/model width (training-CLI convention: the char "
        "family's embed dim equals this; attention uses it as the block "
        "dim)",
    )
    parser.add_argument("--stacked-layer", default=2, type=int)
    parser.add_argument("--cell", default="lstm", choices=["lstm", "gru"])
    parser.add_argument("--num-heads", default=4, type=int)
    parser.add_argument(
        "--max-len", default=512, type=int,
        help="attention family: KV-cache capacity / positional extent",
    )
    parser.add_argument("--num-experts", default=4, type=int)
    parser.add_argument("--moe-top-k", default=1, type=int, choices=[1, 2])
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument(
        "--port", default=0, type=int,
        help="TCP port (0 = ephemeral; see --port-file)",
    )
    parser.add_argument(
        "--port-file", default=None, type=Path, metavar="PATH",
        help="write 'host port' here once listening (how scripts and "
        "the drill find an ephemeral port)",
    )
    parser.add_argument(
        "--slots", default=8, type=int,
        help="decode batch slots - the continuous batch width",
    )
    parser.add_argument(
        "--prompt-buckets", default="16,32,64,128", metavar="L1,L2,...",
        help="prompt-length pad buckets; one prefill program traces per "
        "bucket and the mix can never retrace after warm-up",
    )
    parser.add_argument(
        "--max-new-tokens", default=128, type=int,
        help="per-request decode-length cap",
    )
    parser.add_argument(
        "--max-queue", default=64, type=int,
        help="admission-queue depth; requests past it are SHED with an "
        "overload error instead of waiting unboundedly",
    )
    parser.add_argument(
        "--no-warmup", action="store_true",
        help="skip tracing all programs at startup (first requests then "
        "pay the compiles; the zero-retrace guarantee still holds after "
        "each shape's first use)",
    )
    parser.add_argument(
        "--faults", default=None, metavar="SPEC",
        help="chaos schedule on the decode loop (resilience/faults.py "
        "grammar; step index = decode step): stall holds the loop, nan "
        "poisons in-flight logits (affected requests fail cleanly), "
        "exc is absorbed, kill preempts the process; net:flap:<s> "
        "drops every open client connection each period",
    )
    parser.add_argument(
        "--replica-id", default=None, type=int, metavar="K",
        help="fleet membership (serving/fleet/): this server is "
        "replica K (1..N) behind a pdrnn-router - it pushes its live "
        "digests to the router's aggregator instead of anchoring one, "
        "announces itself via register/drain heartbeats, and SIGTERM "
        "DRAINS (finish in-flight, reject new) instead of aborting",
    )
    parser.add_argument(
        "--drain-timeout", default=30.0, type=float, metavar="S",
        help="bound on the SIGTERM drain wait (fleet replicas)",
    )
    parser.add_argument("--metrics", default=None, type=Path, metavar="PATH")
    parser.add_argument("--metrics-sample-every", default=None, type=int)
    parser.add_argument(
        "--live", default=None, metavar="[HOST:]PORT",
        help="live observability plane (obs/live.py; needs --metrics): "
        "serve GET /metrics (Prometheus), /health, /events, /fleet and "
        "/series on this address, with the time-series store and the "
        "anomaly watchdog (stall / NaN / SLO breach + budget-burn "
        "alerts, stack dumps) armed; also read from the PDRNN_LIVE "
        "env.  SLO objectives via --slo (the global "
        "PDRNN_WATCHDOG_SLO_P95_MS env is deprecated)",
    )
    parser.add_argument(
        "--live-port-file", default=None, type=Path, metavar="PATH",
        help="write 'host port' of the live endpoint here once bound "
        "(how scripts find a --live 0 ephemeral port)",
    )
    parser.add_argument(
        "--slo", action="append", default=None, metavar="SPEC",
        help="per-QoS SLO objective (repeatable, one per class): "
        "'qos=high:p95_ms=250:availability=99.9'.  Arms the watchdog's "
        "per-class SLO detector, and - on the live-plane anchor - the "
        "store's multi-window error-budget burn alerts (slo_burn / "
        "slo_burn_cleared on /events)",
    )
    parser.add_argument(
        "--slo-windows", default=None, metavar="FAST,SLOW",
        help="burn-rate window pair in seconds (default 300,3600 - the "
        "Google SRE fast/slow pair); drills shrink it to fit a burst",
    )
    parser.add_argument("--log", default="INFO")
    return parser


def build_model(args):
    if args.model == "char":
        from pytorch_distributed_rnn_tpu.models import CharRNN

        return CharRNN(
            vocab_size=args.vocab_size, embed_dim=args.hidden_units,
            hidden_dim=args.hidden_units, layer_dim=args.stacked_layer,
            cell=args.cell, impl="scan",
        )
    if args.model == "attention":
        from pytorch_distributed_rnn_tpu.models import AttentionLM

        return AttentionLM(
            vocab_size=args.vocab_size, dim=args.hidden_units,
            depth=args.stacked_layer, num_heads=args.num_heads,
            max_len=args.max_len,
        )
    from pytorch_distributed_rnn_tpu.models import MoELM

    return MoELM(
        vocab_size=args.vocab_size, embed_dim=args.hidden_units,
        hidden_dim=args.hidden_units, layer_dim=args.stacked_layer,
        num_experts=args.num_experts, num_selected=args.moe_top_k,
        cell=args.cell,
    )


def _resolve_checkpoint(path: Path) -> Path:
    from pytorch_distributed_rnn_tpu.training.checkpoint import (
        find_latest_checkpoint,
    )

    if path.is_dir():
        found = find_latest_checkpoint(path)
        if found is None:
            raise SystemExit(
                f"no valid checkpoint under {path} (corrupt files are "
                "skipped; train one first or pass the file directly)"
            )
        return found
    if not path.exists():
        raise SystemExit(f"checkpoint {path} does not exist")
    return path


def serve_main(argv=None) -> int:
    args = build_serve_parser().parse_args(argv)
    logging.basicConfig(level=args.log.upper())
    # before any socket/thread/file exists, so every acquisition is seen
    leakcheck.maybe_install()

    import jax

    from pytorch_distributed_rnn_tpu.obs.recorder import MetricsRecorder
    from pytorch_distributed_rnn_tpu.resilience.faults import FaultSchedule
    from pytorch_distributed_rnn_tpu.serving.adapters import adapter_for
    from pytorch_distributed_rnn_tpu.serving.buckets import BucketSpec
    from pytorch_distributed_rnn_tpu.serving.engine import ServingEngine
    from pytorch_distributed_rnn_tpu.serving.server import ServingServer
    from pytorch_distributed_rnn_tpu.training.checkpoint import (
        load_model_params,
    )

    ckpt = _resolve_checkpoint(args.checkpoint)
    model = build_model(args)
    template = model.init(jax.random.PRNGKey(0))
    params, meta = load_model_params(ckpt, template)
    log.info(
        f"pdrnn-serve: loaded {ckpt} (epoch {meta['epoch']}, "
        f"loss {meta['loss']:.4f})"
    )

    replica_id = args.replica_id
    recorder = MetricsRecorder.resolve(
        args, rank=replica_id or 0,
        meta={"role": "serve", "argv": sys.argv[1:]},
    )
    faults = FaultSchedule.resolve(args)
    if faults is not None:
        log.warning(f"pdrnn-serve: chaos schedule active: {faults}")
    if recorder.enabled:
        # on-demand hang diagnosis: kill -USR2 <pid> dumps all-thread
        # stacks next to the sidecar (obs/watchdog.py)
        from pytorch_distributed_rnn_tpu.obs.watchdog import (
            install_stack_dump_handler,
        )

        install_stack_dump_handler(recorder.path)
    engine = ServingEngine(
        adapter_for(model), params, num_slots=args.slots,
        bucket_spec=BucketSpec.parse(args.prompt_buckets),
        max_new_tokens=args.max_new_tokens, max_queue=args.max_queue,
        recorder=recorder, faults=faults,
    )
    # live plane: /metrics + /health + /events served from this process
    # (the serving engine IS the rank-0 anchor), with the engine's gauge
    # block riding every digest.  A fleet REPLICA (--replica-id >= 1)
    # pushes to the router's aggregator instead of anchoring its own -
    # its digest doubles as the router's load signal
    from pytorch_distributed_rnn_tpu.obs.live import LivePlane

    plane = LivePlane.resolve(args, recorder, rank=replica_id or 0,
                              role="serve", faults=faults)
    if plane is not None:
        plane.exporter.add_source(engine.live_source)
    pusher = None
    if replica_id is not None:
        # register/drain heartbeats ride the aggregator's /events feed
        # (alert-only EventPusher - distinct id space from the digest
        # exporter, so the membership announcements never collide with
        # the replica's own gauge digests)
        import os

        from pytorch_distributed_rnn_tpu.obs.live import (
            LIVE_ENV,
            EventPusher,
            parse_live_spec,
            resolve_push_url,
        )

        spec = args.live or os.environ.get(LIVE_ENV)
        if spec and recorder.enabled:
            lhost, lport = parse_live_spec(spec)
            pusher = EventPusher(
                lambda: resolve_push_url(args, lhost, lport),
                role="replica", rank=replica_id,
            ).push
    if not args.no_warmup:
        engine.warmup()
    server = ServingServer(
        engine, host=args.host, port=args.port,
        model_name=args.model, recorder=recorder, pusher=pusher,
        replica_id=replica_id,
    )
    if args.port_file is not None:
        args.port_file.parent.mkdir(parents=True, exist_ok=True)
        args.port_file.write_text(f"{server.host} {server.port}\n")

    stop = threading.Event()
    received = {"signum": None}

    def _on_signal(signum, _frame):
        log.info(f"pdrnn-serve: signal {signum}, shutting down")
        received["signum"] = signum
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    server.start()
    print(f"pdrnn-serve: listening on {server.host}:{server.port}",
          flush=True)
    while not stop.is_set():
        stop.wait(timeout=0.5)
    # a fleet replica DRAINS on SIGTERM: finish what it owns, reject
    # new work, and mark its digests drained so the aggregator (and
    # `pdrnn-metrics health`) classifies the coming silence as a
    # voluntary exit, never a death
    drain = (replica_id is not None
             and received["signum"] == signal.SIGTERM)
    if drain and plane is not None:
        plane.exporter.note_drained()
    server.shutdown(drain=drain, drain_timeout_s=args.drain_timeout)
    if plane is not None:
        # after server.shutdown(): the recorder's close pushed the final
        # finished digest, so the last scrape-able state is honest
        plane.close()
    stats = engine.stats()
    log.info(
        f"pdrnn-serve: served {stats['requests']} requests "
        f"({stats['tokens_out']} tokens), shed {stats['requests_shed']}"
    )
    return 0


# ---------------------------------------------------------------------------
# pdrnn-loadgen


def build_loadgen_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="pdrnn-loadgen",
        description="Poisson load generator + SLO report for pdrnn-serve",
    )
    target = parser.add_mutually_exclusive_group(required=True)
    target.add_argument(
        "--connect", default=None, metavar="HOST:PORT",
        help="an already-running server",
    )
    target.add_argument(
        "--port-file", default=None, type=Path,
        help="read the target from a pdrnn-serve --port-file",
    )
    target.add_argument(
        "--spawn-server", default=None, metavar="ARGS",
        help="chaos SLO drill: spawn `pdrnn-serve ARGS` (shell-quoted "
        "string), load it, SIGTERM it, and report - including the "
        "degradation window and the server's exit code",
    )
    target.add_argument(
        "--spawn-fleet", default=None, type=int, metavar="N",
        help="kill-mid-burst fleet drill: spawn N supervised replicas "
        "(--replica-args) behind a pdrnn-router (--router-args), load "
        "through the router, optionally SIGKILL one replica mid-burst "
        "(--fleet-kill-after-s), and assert rerouting + exactly-once "
        "accounting + a CLOSED degradation window",
    )
    parser.add_argument(
        "--replica-args", default=None, metavar="ARGS",
        help="pdrnn-serve flags shared by every --spawn-fleet replica "
        "(shell-quoted; identity/port flags are added by the drill)",
    )
    parser.add_argument(
        "--router-args", default="", metavar="ARGS",
        help="extra pdrnn-router flags for --spawn-fleet "
        "(shell-quoted), e.g. '--retries 2 --hedge-after-ms 250'",
    )
    parser.add_argument(
        "--fleet-kill-after-s", default=None, type=float, metavar="S",
        help="SIGKILL one replica this long after load start",
    )
    parser.add_argument(
        "--fleet-kill-index", default=1, type=int, metavar="K",
        help="which replica slot (1..N) the kill hits",
    )
    parser.add_argument("--requests", default=50, type=int)
    parser.add_argument(
        "--rate", default=25.0, type=float,
        help="mean Poisson arrival rate, requests/second",
    )
    parser.add_argument("--prompt-len-min", default=2, type=int)
    parser.add_argument("--prompt-len-max", default=24, type=int)
    parser.add_argument("--new-tokens-min", default=4, type=int)
    parser.add_argument("--new-tokens-max", default=24, type=int)
    parser.add_argument(
        "--temperature", default=0.8, type=float,
        help="sampling temperature for the sampled share of the mix",
    )
    parser.add_argument(
        "--sampled-fraction", default=0.5, type=float,
        help="share of requests sampled at --temperature (the rest are "
        "greedy)",
    )
    parser.add_argument("--seed", default=0, type=int)
    parser.add_argument("--stream", action="store_true",
                        help="request streamed tokens")
    parser.add_argument("--timeout", default=120.0, type=float, metavar="S")
    parser.add_argument(
        "--connect-timeout", default=5.0, type=float, metavar="S",
        help="dial bound per request connection (separate from "
        "--timeout so a vanished target fails fast)",
    )
    parser.add_argument(
        "--low-priority-fraction", default=0.0, type=float,
        help="share of requests tagged priority=low (router QoS: low "
        "sheds first under overload; plain servers ignore the tag)",
    )
    parser.add_argument(
        "--deadline-ms", default=None, type=float,
        help="per-request deadline_ms field (router QoS: bounds "
        "dispatch + retries server-side)",
    )
    parser.add_argument("--slo-p95-ms", default=2000.0, type=float)
    parser.add_argument("--slo-ttft-p95-ms", default=None, type=float)
    parser.add_argument(
        "--trace-sample", default=0.0, type=float, metavar="RATE",
        help="head-sample this fraction of requests into distributed "
        "traces (deterministic, does not shift the seeded plan); the "
        "report then names trace ids pullable with pdrnn-metrics trace",
    )
    parser.add_argument(
        "--report", default=None, type=Path, metavar="PATH",
        help="also write the full JSON report here",
    )
    parser.add_argument("--json", action="store_true",
                        help="print the JSON report instead of the table")
    return parser


def loadgen_main(argv=None) -> int:
    from pytorch_distributed_rnn_tpu.serving.loadgen import (
        LoadConfig,
        format_report,
        run_load,
    )

    args = build_loadgen_parser().parse_args(argv)
    logging.basicConfig(level="INFO")
    leakcheck.maybe_install()
    cfg = LoadConfig(
        requests=args.requests, rate=args.rate,
        prompt_len_min=args.prompt_len_min,
        prompt_len_max=args.prompt_len_max,
        new_tokens_min=args.new_tokens_min,
        new_tokens_max=args.new_tokens_max,
        temperature=args.temperature,
        sampled_fraction=args.sampled_fraction,
        seed=args.seed, stream=args.stream, timeout_s=args.timeout,
        connect_timeout_s=args.connect_timeout,
        low_priority_fraction=args.low_priority_fraction,
        deadline_ms=args.deadline_ms,
        slo_p95_ms=args.slo_p95_ms, slo_ttft_p95_ms=args.slo_ttft_p95_ms,
        trace_sample=args.trace_sample,
    )

    if args.spawn_fleet is not None:
        from pytorch_distributed_rnn_tpu.serving.fleet.drill import (
            FleetSpawnError,
            run_fleet_drill,
        )

        if args.replica_args is None:
            print("pdrnn-loadgen: --spawn-fleet needs --replica-args",
                  file=sys.stderr)
            return 2
        try:
            report = run_fleet_drill(
                shlex.split(args.replica_args), cfg,
                n=args.spawn_fleet,
                kill_after_s=args.fleet_kill_after_s,
                kill_index=args.fleet_kill_index,
                router_args=shlex.split(args.router_args),
            )
        except FleetSpawnError as exc:
            print(f"pdrnn-loadgen: {exc}", file=sys.stderr)
            return 2
        if args.report is not None:
            args.report.parent.mkdir(parents=True, exist_ok=True)
            args.report.write_text(json.dumps(report, indent=1) + "\n")
        fleet = report["fleet"]
        if args.json:
            print(json.dumps(report, indent=1))
        else:
            print(format_report(report))
            print(
                f"fleet: {fleet['replicas']} replicas, "
                f"{fleet['respawns']} respawn(s), router rerouted "
                f"{fleet['router']['rerouted']} "
                f"({fleet['router']['retries']} retries, "
                f"{fleet['router']['hedges']} hedges), accounting "
                f"{'OK' if fleet['accounting_ok'] else 'BROKEN'}, "
                f"window "
                f"{'closed' if fleet['window_closed'] else 'OPEN'}"
            )
            if "live" in fleet:
                live = fleet["live"]
                rec = live["recommended_replicas"]
                print(
                    f"fleet live: slo_burn "
                    f"{'fired' if live['burn_fired'] else 'quiet'}"
                    f"{'+cleared' if live['burn_cleared'] else ''}, "
                    f"recommended_replicas {rec['min']}->{rec['peak']} "
                    f"({rec['samples']} samples), series scrape "
                    f"{'ok' if live['series_scrape_ok'] else 'MISSING'}"
                )
        # the drill's gate: degradation bounded + nothing lost or
        # duplicated + the kill actually respawned + clean teardown
        # (a killed stream may legitimately error, so `errors == 0`
        # is NOT part of this verdict - accounting is)
        ok = (
            fleet["accounting_ok"] and fleet["window_closed"]
            and fleet["router_exit"] == 0
            and (args.fleet_kill_after_s is None
                 or fleet["respawns"] >= 1)
        )
        return 0 if ok else 1

    if args.spawn_server is not None:
        from pytorch_distributed_rnn_tpu.serving.drill import (
            ServerSpawnError,
            run_drill,
        )

        try:
            report, server_exit = run_drill(
                shlex.split(args.spawn_server), cfg
            )
        except ServerSpawnError as exc:
            print(f"pdrnn-loadgen: {exc}", file=sys.stderr)
            return 2
    else:
        if args.port_file is not None:
            host, port = args.port_file.read_text().split()
        else:
            host, _, port = args.connect.rpartition(":")
            if not host:
                print("pdrnn-loadgen: --connect needs HOST:PORT",
                      file=sys.stderr)
                return 2
        cfg = LoadConfig(**{**cfg.__dict__, "host": host,
                            "port": int(port)})
        report = run_load(cfg)
        server_exit = None

    if args.report is not None:
        args.report.parent.mkdir(parents=True, exist_ok=True)
        args.report.write_text(json.dumps(report, indent=1) + "\n")
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(format_report(report))
        if server_exit is not None:
            print(f"server exit code: {server_exit}")

    ok = (
        report["errors"] == 0
        and report["slo"].get("p95_ok", False)
        and report["slo"].get("ttft_p95_ok", True)
        and (server_exit in (None, 0))
    )
    return 0 if ok else 1
