"""Spawn-and-drain helper for multi-process rank worlds.

One implementation shared by the native-transport DDP launcher
(``training/native_ddp.py``) and the jax.distributed world launcher
(``launcher/bench.py``) - the spawn/drain/timeout/failure machinery is
identical; only each rank's argv/env differ.
"""

from __future__ import annotations

import subprocess
import threading


def spawn_world(rank_cmds, *, timeout: float = 600.0, cwd=None):
    """Run one process per ``(argv, env)`` in ``rank_cmds``; returns
    ``[(returncode, stdout, stderr)]`` in rank order.

    Pipes are drained CONCURRENTLY: a rank blocked on a full stderr pipe
    stops participating in collectives and would deadlock the world if
    ranks were drained one at a time.  On error, ranks that FAILED are
    reported before ranks that timed out - a crashed rank is usually the
    root cause of its peers' hangs, so its stderr is what the operator
    needs first.
    """
    procs = [
        subprocess.Popen(
            argv, env=env, cwd=cwd, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
        for argv, env in rank_cmds
    ]

    results = [None] * len(procs)
    errors = [None] * len(procs)

    def drain(rank, proc):
        try:
            out, err = proc.communicate(timeout=timeout)
            results[rank] = (proc.returncode, out, err)
        except subprocess.TimeoutExpired as e:
            errors[rank] = e
            proc.kill()
            proc.communicate()

    threads = [
        threading.Thread(target=drain, args=(rank, proc))
        for rank, proc in enumerate(procs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    failed = [
        (rank, res[2][-2000:])
        for rank, res in enumerate(results)
        if res is not None and res[0] != 0
    ]
    if failed:
        raise RuntimeError(f"world ranks failed: {failed}")
    timed_out = [r for r, e in enumerate(errors) if e is not None]
    if timed_out:
        raise RuntimeError(
            f"world ranks timed out after {timeout}s: {timed_out}"
        )
    return results
