"""Platform selection that works when jax was pre-imported at startup.

Some environments (including this one) register a TPU PJRT plugin from
``sitecustomize`` at interpreter start, which imports jax and freezes
``JAX_PLATFORMS`` before user code runs - worse, exporting
``JAX_PLATFORMS=cpu`` in the shell can hang the plugin's registration.  The
reliable override is ``jax.config.update("jax_platforms", ...)`` before the
first backend use.  This helper reads our own env vars and applies that:

- ``PDRNN_PLATFORM=cpu`` forces the CPU backend.
- ``PDRNN_NUM_CPU_DEVICES=8`` requests N virtual CPU devices (only honored
  if XLA_FLAGS was not already forcing a count; must run before backend
  init).
"""

from __future__ import annotations

import os
import subprocess
import sys

_PROBE_CACHE: dict = {}


def probe_backend(timeout: float = 45.0):
    """Check ambient-backend health in a throwaway subprocess.

    The ambient backend (a TPU PJRT plugin registered from sitecustomize)
    can HANG during init when its tunnel is down - not raise, hang
    (observed round 2: a bare ``jax.devices()`` blocked >120s,
    VERDICT.md "driver-contract fragility").  Anything that must stay
    runnable therefore may never gate on in-process backend init.  This
    probes ``jax.default_backend()`` + device count in a subprocess with a
    hard timeout; the parent's backend state is untouched.

    Returns ``(platform, n_devices)`` on success, ``None`` when init
    raises, hangs, or produces garbage.  Result is cached per-process.
    """
    # One probe per process: the answer (backend healthy or not) does not
    # change meaningfully within a run, and probes cost seconds.
    key = "probe"
    if key in _PROBE_CACHE:
        return _PROBE_CACHE[key]
    # a sentinel-prefixed line keeps the parse robust against anything
    # else (sitecustomize banners, plugin chatter) written to the child's
    # stdout - a healthy backend must never be misread as broken
    code = (
        "import jax, sys; "
        "sys.stdout.write('\\nPDRNN_PROBE %s %d\\n' "
        "% (jax.default_backend(), len(jax.devices())))"
    )
    result = None
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            timeout=timeout,
        )
        if proc.returncode == 0:
            for line in proc.stdout.decode().splitlines():
                parts = line.strip().split()
                if len(parts) == 3 and parts[0] == "PDRNN_PROBE":
                    result = (parts[1], int(parts[2]))
    except (subprocess.TimeoutExpired, OSError, ValueError):
        result = None
    _PROBE_CACHE[key] = result
    return result


def ensure_usable_backend(min_devices: int = 1, timeout: float = 45.0):
    """Force the CPU platform when the ambient backend is hung or broken.

    Must run before the first in-process backend use.  When
    ``PDRNN_PLATFORM`` is already set the caller has chosen a platform and
    no probe runs.  Returns a dict: ``platform`` (best knowledge),
    ``n_devices`` (probed, or None), ``fallback`` (True when the ambient
    backend was unusable and CPU was forced) - callers surface the
    fallback in their output rather than dying with the tunnel
    (VERDICT.md round-3 item 1).
    """
    if os.environ.get("PDRNN_PLATFORM"):
        apply_platform_overrides()
        return {
            "platform": os.environ["PDRNN_PLATFORM"],
            "n_devices": None,
            "fallback": False,
        }
    probe = probe_backend(timeout)
    if probe is None or probe[1] < min_devices:
        os.environ["PDRNN_PLATFORM"] = "cpu"
        if min_devices > 1:
            os.environ.setdefault("PDRNN_NUM_CPU_DEVICES", str(min_devices))
        apply_platform_overrides()
        return {"platform": "cpu", "n_devices": None, "fallback": True}
    apply_platform_overrides()
    return {"platform": probe[0], "n_devices": probe[1], "fallback": False}


def apply_platform_overrides():
    platform = os.environ.get("PDRNN_PLATFORM")
    n_cpu = os.environ.get("PDRNN_NUM_CPU_DEVICES")
    if n_cpu and "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n_cpu}"
        ).strip()
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    _enable_compile_cache(jax)
    return jax


def _enable_compile_cache(jax):
    """Persistent XLA compilation cache, on by default.

    The reference's eager PyTorch pays no compile cost; under XLA every
    fresh process re-traces and re-compiles (~20-40s for the TPU epoch
    programs), which would dominate the reference-style 1-epoch CLI runs
    the launcher records.  Caching compiled executables on disk makes
    repeat runs of the same program shapes start in steady state - each
    launcher subprocess, bench invocation, and multi-process world rank
    hits the shared cache (JAX's cache layout is concurrency-safe).

    ``PDRNN_COMPILE_CACHE_DIR`` overrides the location; ``off`` disables.
    Only compilations >= 1s are cached, so the many tiny test programs
    don't churn the cache.  Forced-CPU runs (``PDRNN_PLATFORM=cpu`` - the
    virtual-device study/test platform) skip the cache unless a dir is set
    explicitly: XLA:CPU AOT cache loads warn about compile-vs-host machine
    feature tuning mismatches on every hit, and the hermetic suite doesn't
    need cross-process reuse.
    """
    if (
        os.environ.get("PDRNN_PLATFORM") == "cpu"
        and "PDRNN_COMPILE_CACHE_DIR" not in os.environ
    ):
        return
    # Default under the user's own cache root, never a predictable /tmp
    # path: cache entries are compiled executables, and a /tmp dir can be
    # pre-created (and then owned) by another local user, who would then
    # control what this process deserializes.
    default_dir = os.path.join(
        os.environ.get("XDG_CACHE_HOME")
        or os.path.join(os.path.expanduser("~"), ".cache"),
        "pdrnn-xla",
    )
    cache_dir = os.environ.get("PDRNN_COMPILE_CACHE_DIR", default_dir)
    if cache_dir.lower() in ("", "0", "off", "none"):
        return
    if not _cache_dir_is_safe(cache_dir):
        import logging

        logging.getLogger(__name__).warning(
            "compile cache DISABLED: %s is not a private directory owned "
            "by this user (need uid-owned, no group/world write) - fix "
            "its permissions or set PDRNN_COMPILE_CACHE_DIR", cache_dir,
        )
        return
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # pragma: no cover - older jax without the flags
        pass


def _cache_dir_is_safe(cache_dir: str) -> bool:
    """Create the cache dir 0700 if absent; refuse to use a dir another
    user owns or can write (it would feed us their compiled executables)."""
    try:
        os.makedirs(cache_dir, mode=0o700, exist_ok=True)
        st = os.stat(cache_dir)
    except OSError:
        return False
    if not hasattr(os, "getuid"):  # non-POSIX: ownership model differs
        return True
    if st.st_uid != os.getuid():
        return False
    if st.st_mode & 0o022:  # group/world-writable
        return False
    return True
