"""Platform selection that works when jax was pre-imported at startup.

Some environments (including this one) register a TPU PJRT plugin from
``sitecustomize`` at interpreter start, which imports jax and freezes
``JAX_PLATFORMS`` before user code runs - worse, exporting
``JAX_PLATFORMS=cpu`` in the shell can hang the plugin's registration.  The
reliable override is ``jax.config.update("jax_platforms", ...)`` before the
first backend use.  This helper reads our own env vars and applies that:

- ``PDRNN_PLATFORM=cpu`` forces the CPU backend.
- ``PDRNN_NUM_CPU_DEVICES=8`` requests N virtual CPU devices (only honored
  if XLA_FLAGS was not already forcing a count; must run before backend
  init).
"""

from __future__ import annotations

import os


def apply_platform_overrides():
    platform = os.environ.get("PDRNN_PLATFORM")
    n_cpu = os.environ.get("PDRNN_NUM_CPU_DEVICES")
    if n_cpu and "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n_cpu}"
        ).strip()
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    _enable_compile_cache(jax)
    return jax


def _enable_compile_cache(jax):
    """Persistent XLA compilation cache, on by default.

    The reference's eager PyTorch pays no compile cost; under XLA every
    fresh process re-traces and re-compiles (~20-40s for the TPU epoch
    programs), which would dominate the reference-style 1-epoch CLI runs
    the launcher records.  Caching compiled executables on disk makes
    repeat runs of the same program shapes start in steady state - each
    launcher subprocess, bench invocation, and multi-process world rank
    hits the shared cache (JAX's cache layout is concurrency-safe).

    ``PDRNN_COMPILE_CACHE_DIR`` overrides the location; ``off`` disables.
    Only compilations >= 1s are cached, so the many tiny test programs
    don't churn the cache.  Forced-CPU runs (``PDRNN_PLATFORM=cpu`` - the
    virtual-device study/test platform) skip the cache unless a dir is set
    explicitly: XLA:CPU AOT cache loads warn about compile-vs-host machine
    feature tuning mismatches on every hit, and the hermetic suite doesn't
    need cross-process reuse.
    """
    if (
        os.environ.get("PDRNN_PLATFORM") == "cpu"
        and "PDRNN_COMPILE_CACHE_DIR" not in os.environ
    ):
        return
    # per-user default path: a world-shared fixed /tmp path would let one
    # local user's cache entries (compiled executables) be loaded by another
    uid = getattr(os, "getuid", lambda: 0)()
    cache_dir = os.environ.get(
        "PDRNN_COMPILE_CACHE_DIR", f"/tmp/pdrnn-xla-cache-{uid}"
    )
    if cache_dir.lower() in ("", "0", "off", "none"):
        return
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # pragma: no cover - older jax without the flags
        pass
