"""Platform selection that works when jax was pre-imported at startup.

Some environments (including this one) register a TPU PJRT plugin from
``sitecustomize`` at interpreter start, which imports jax and freezes
``JAX_PLATFORMS`` before user code runs - worse, exporting
``JAX_PLATFORMS=cpu`` in the shell can hang the plugin's registration.  The
reliable override is ``jax.config.update("jax_platforms", ...)`` before the
first backend use.  This helper reads our own env vars and applies that:

- ``PDRNN_PLATFORM=cpu`` forces the CPU backend.
- ``PDRNN_NUM_CPU_DEVICES=8`` requests N virtual CPU devices (only honored
  if XLA_FLAGS was not already forcing a count; must run before backend
  init).
"""

from __future__ import annotations

import os


def apply_platform_overrides():
    platform = os.environ.get("PDRNN_PLATFORM")
    n_cpu = os.environ.get("PDRNN_NUM_CPU_DEVICES")
    if n_cpu and "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n_cpu}"
        ).strip()
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    return jax
