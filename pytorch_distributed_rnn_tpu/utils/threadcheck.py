"""Runtime lock-order / race sentinel (``PDRNN_THREADCHECK``).

The dynamic half of the PD3xx concurrency pass
(``lint/concurrency.py`` is the static half): where the lint proves
discipline about the lock acquisitions it can SEE, the sentinel checks
the ones that actually HAPPEN.  Every lock-using module routes its
locks through :func:`lock`; with the sentinel off that call returns
the raw ``threading.Lock`` unchanged - no proxy object, no extra
thread, no per-acquire bookkeeping, the same zero-overhead-when-off
doctrine as the recorder/live plane (``obs/recorder.py``'s
``NULL_RECORDER``).  With ``PDRNN_THREADCHECK=1`` (on in the CI chaos,
serving and streaming jobs) each lock becomes a :class:`TrackedLock`
proxy and the sentinel detects, live:

- **lock-order inversions** (the runtime PD303): every blocking
  acquire adds ``held -> wanted`` edges to a process-wide acquisition
  graph; a cycle means two threads can deadlock under the right
  interleaving.  The check runs BEFORE the acquire, so the offending
  test fails loudly with :class:`LockOrderError` instead of hanging
  until the job times out.
- **hold-while-blocking** (the runtime PD302):
  :func:`assert_unlocked` / :func:`blocking` mark operations that must
  never run under a lock (socket sends, checkpoint writes,
  ``block_until_ready``); entering one with a tracked lock held raises
  :class:`HeldWhileBlockingError`.
- **long holds**: a lock held past ``PDRNN_THREADCHECK_HOLD_S``
  (default 5s) emits a warning alert on release - the smoking gun for
  "serialization sneaked inside the round lock" regressions.

Violations are *structured*: the sentinel records a normal ``alert``
event (``alert=lock_order_inversion|lock_held_while_blocking|
lock_long_hold``) through whatever recorder :func:`install` was given,
flushes it, appends a :mod:`faulthandler` all-thread stack dump via
the watchdog's sidecar-adjacent stacks file, and *then* raises - the
post-mortem is on disk before the exception unwinds.  The alert
payload carries every thread's acquisition stack (lock names + hold
ages), which is usually enough to name both sides of an inversion
without opening the faulthandler dump.

Activation is lazy and env-driven: the first :func:`lock` call
resolves ``PDRNN_THREADCHECK`` once; :func:`install` forces the
sentinel on (tests, drills) and :func:`uninstall` resets it.  Locks
created while the sentinel is off stay raw forever - mixing raw and
tracked locks is safe (raw locks are simply invisible to the graph).

Lock NAMES are contracts: two locks with the same name share a node in
the order graph, so name locks by role (``"engine.stats"``,
``"master.round"``), not by instance.  The static pass's
``# lock-order:`` declarations mirror the edges this sentinel learns
at runtime.
"""

from __future__ import annotations

import logging
import os
import threading
import time

log = logging.getLogger(__name__)

THREADCHECK_ENV = "PDRNN_THREADCHECK"
HOLD_ENV = "PDRNN_THREADCHECK_HOLD_S"
_OFF_VALUES = ("", "0", "false", "off", "no")


class LockOrderError(RuntimeError):
    """A blocking acquire would close a cycle in the acquisition-order
    graph: some interleaving of the participating threads deadlocks."""


class HeldWhileBlockingError(RuntimeError):
    """A declared-blocking operation started while this thread held a
    tracked lock (the exact bug class PD302 flags statically)."""


class _Sentinel:
    """Process-wide tracking state.  Its internal mutex is a leaf: it
    is only ever held for dict/graph surgery, never while touching a
    user lock, so the sentinel cannot itself deadlock the patient."""

    def __init__(self, recorder=None):
        from pytorch_distributed_rnn_tpu.obs.recorder import NULL_RECORDER

        self.recorder = recorder if recorder is not None else NULL_RECORDER
        self._mu = threading.Lock()
        # name -> set of names acquired while `name` was held
        self.edges: dict[str, set[str]] = {}
        # thread ident -> [(lock name, acquire perf_counter), ...]
        self.held: dict[int, list[tuple[str, float]]] = {}
        self.hold_warn_s = float(os.environ.get(HOLD_ENV, "5.0"))
        self.seq = 0
        self.violations: list[dict] = []
        self.locks_created = 0
        # reentrancy latch: alert emission goes through the recorder,
        # whose OWN locks are tracked - a violation found while already
        # reporting one must raise bare, not recurse into the reporter
        self._reporting = threading.local()

    # -- graph ---------------------------------------------------------

    def _reaches(self, src: str, dst: str) -> list[str] | None:
        """Path src -> ... -> dst over the current edges (caller holds
        ``_mu``); returns the node path or None."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self.edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def before_acquire(self, name: str) -> None:
        """Order check for a BLOCKING acquire: run before touching the
        user lock so an inversion raises instead of deadlocking."""
        ident = threading.get_ident()
        with self._mu:
            held = [h for h, _ in self.held.get(ident, ())]
            cycle = None
            for h in held:
                if h == name:
                    continue  # reentrant same-role acquire (RLock)
                path = self._reaches(name, h)
                if path is not None:
                    cycle = path + [name]
                    break
            if cycle is None:
                for h in held:
                    if h != name:
                        self.edges.setdefault(h, set()).add(name)
        if cycle is not None:
            self._violation(
                "lock_order_inversion", LockOrderError,
                f"acquiring '{name}' while holding {held} closes the "
                f"order cycle {' -> '.join(cycle)}",
                wanted=name, held=held, cycle=cycle,
            )

    def after_acquire(self, name: str) -> None:
        ident = threading.get_ident()
        with self._mu:
            self.held.setdefault(ident, []).append(
                (name, time.perf_counter()))

    def after_release(self, name: str) -> None:
        ident = threading.get_ident()
        held_s = None
        with self._mu:
            stack = self.held.get(ident, [])
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][0] == name:
                    held_s = time.perf_counter() - stack[i][1]
                    del stack[i]
                    break
        if held_s is not None and held_s > self.hold_warn_s:
            # warn-only: a long hold is a perf smell, not a deadlock
            self._alert("lock_long_hold", severity="warn", lock=name,
                        held_s=round(held_s, 3))
            log.warning(f"threadcheck: '{name}' held {held_s:.3f}s "
                        f"(> {self.hold_warn_s}s)")

    def check_unlocked(self, what: str, allow: tuple = ()) -> None:
        ident = threading.get_ident()
        with self._mu:
            held = [h for h, _ in self.held.get(ident, ())
                    if h not in allow]
        if held:
            self._violation(
                "lock_held_while_blocking", HeldWhileBlockingError,
                f"blocking operation '{what}' entered while holding "
                f"{held}", what=what, held=held,
            )

    # -- reporting -----------------------------------------------------

    def snapshot(self) -> dict:
        """Every thread's acquisition stack: lock names + hold ages."""
        now = time.perf_counter()
        with self._mu:
            return {
                str(ident): [
                    {"lock": h, "held_s": round(now - t0, 3)}
                    for h, t0 in stack
                ]
                for ident, stack in self.held.items() if stack
            }

    def _alert(self, kind: str, severity: str = "error", **fields):
        with self._mu:
            self.seq += 1
            seq = self.seq
        payload = dict(alert=kind, severity=severity, seq=seq,
                       source="threadcheck", **fields)
        try:
            self.recorder.record("alert", **payload)
            self.recorder.flush()
        except Exception:  # diagnosis must never kill the patient
            log.exception("threadcheck: alert emission failed")
        return payload

    def _violation(self, kind: str, exc_type, msg: str, **fields):
        if getattr(self._reporting, "active", False):
            raise exc_type(msg)
        self._reporting.active = True
        try:
            payload = self._alert(kind, severity="error",
                                  threads=self.snapshot(), **fields)
            self.violations.append(payload)
            path = getattr(self.recorder, "path", None)
            if path is not None:
                try:
                    from pytorch_distributed_rnn_tpu.obs import watchdog

                    watchdog.dump_stacks(watchdog.stacks_path_for(path),
                                         reason=f"threadcheck:{kind}")
                except Exception:
                    log.exception("threadcheck: stack dump failed")
        finally:
            self._reporting.active = False
        log.error(f"threadcheck: {msg}")
        raise exc_type(msg)


class TrackedLock:
    """Order-tracking proxy around a raw lock.

    Deliberately exposes ONLY the waiter-facing surface (``acquire`` /
    ``release`` / ``locked`` / context manager): no ``_release_save``
    or ``_is_owned`` delegation, so ``threading.Condition`` wraps it
    through its stdlib fallback paths - which call ``release()`` and
    ``acquire()`` right back through this proxy, keeping the held
    stack symmetric across ``cv.wait()``.
    """

    __slots__ = ("_raw", "name", "_sentinel")

    def __init__(self, raw, name: str, sentinel: _Sentinel):
        self._raw = raw
        self.name = name
        self._sentinel = sentinel

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            # a nonblocking probe (Condition._is_owned's fallback uses
            # acquire(False)) cannot deadlock, so only blocking
            # acquires feed and consult the order graph
            self._sentinel.before_acquire(self.name)
        got = self._raw.acquire(blocking, timeout)
        if got:
            self._sentinel.after_acquire(self.name)
        return got

    def release(self):
        self._raw.release()
        self._sentinel.after_release(self.name)

    def locked(self):
        return self._raw.locked()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<TrackedLock {self.name!r} of {self._raw!r}>"


# ---------------------------------------------------------------------------
# module-level switchboard

_STATE: _Sentinel | None = None
_RESOLVED = False


def _state() -> _Sentinel | None:
    """Lazy env resolve: the first lock() call decides, once.  After
    that only install()/uninstall() change the answer."""
    global _STATE, _RESOLVED
    if not _RESOLVED:
        _RESOLVED = True
        if os.environ.get(THREADCHECK_ENV, "").lower() not in _OFF_VALUES:
            _STATE = _Sentinel()
    return _STATE


def installed() -> bool:
    return _state() is not None


def install(recorder=None) -> _Sentinel:
    """Force the sentinel on (tests, drills, entrypoints that already
    resolved a recorder); idempotent - re-install updates the recorder
    but keeps the learned order graph."""
    global _STATE, _RESOLVED
    _RESOLVED = True
    if _STATE is None:
        _STATE = _Sentinel(recorder)
    elif recorder is not None:
        _STATE.recorder = recorder
    return _STATE


def uninstall() -> None:
    """Reset to unresolved (tests).  Locks already wrapped stay
    wrapped but their sentinel stops receiving new installs."""
    global _STATE, _RESOLVED
    _STATE = None
    _RESOLVED = False


def lock(raw=None, name: str = "anonymous"):
    """Route a lock through the sentinel.  Off: returns ``raw``
    unchanged (identity - no proxy, no overhead).  On: returns a
    :class:`TrackedLock` participating in the order graph under
    ``name``."""
    if raw is None:
        raw = threading.Lock()
    st = _state()
    if st is None:
        return raw
    st.locks_created += 1
    return TrackedLock(raw, name, st)


def assert_unlocked(what: str, allow: tuple = ()) -> None:
    """Declare a must-not-hold point (socket send, checkpoint write,
    ``block_until_ready``): raises :class:`HeldWhileBlockingError` if
    this thread holds any tracked lock not in ``allow``.  Off: a
    single global read."""
    st = _STATE  # deliberate: no lazy resolve on the hot path
    if st is not None:
        st.check_unlocked(what, allow)


class blocking:
    """``with threadcheck.blocking("checkpoint write"):`` - the
    context-manager spelling of :func:`assert_unlocked`."""

    __slots__ = ("what", "allow")

    def __init__(self, what: str, allow: tuple = ()):
        self.what = what
        self.allow = allow

    def __enter__(self):
        assert_unlocked(self.what, self.allow)
        return self

    def __exit__(self, *exc):
        return False


def held_names() -> tuple:
    """Lock names the calling thread currently holds (empty when
    off)."""
    st = _STATE
    if st is None:
        return ()
    with st._mu:
        return tuple(h for h, _ in st.held.get(threading.get_ident(), ()))


def stats() -> dict:
    """Sentinel introspection for tests: learned edges, violation
    count, locks wrapped."""
    st = _STATE
    if st is None:
        return {"installed": False}
    with st._mu:
        return {
            "installed": True,
            "locks_created": st.locks_created,
            "edges": {k: sorted(v) for k, v in st.edges.items()},
            "violations": len(st.violations),
        }
