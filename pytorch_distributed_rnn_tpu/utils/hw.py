"""Per-backend peak-FLOPs table for MFU/HFU denominators.

The efficiency ledger (``obs/ledger.py``) divides analytically counted
model FLOPs by a *claimed hardware peak* to get an MFU-style ratio.  The
table below is deliberately small and honest about provenance:

- TPU entries are vendor datasheet numbers (bf16, per chip).
- The CPU entry is an order-of-magnitude **estimate** (a few AVX2 cores
  at f32), flagged ``estimated=True`` and labeled in every surface that
  prints it.  CPU MFU is only meaningful as a *relative* cross-run
  signal on the same host, never as an absolute utilization claim.

``peak_flops()`` never raises: unknown hardware falls back to the CPU
estimate so ledger output is always populated (with the estimate label).
"""

from __future__ import annotations

from typing import Optional

# bf16 (TPU) / f32 (CPU) peak FLOP/s per device.  Keys are lowercase
# substrings matched against ``device_kind`` (see ``peak_flops``).
# V5E figure matches bench.py's V5E_BF16_PEAK_FLOPS.
PEAK_FLOPS_TABLE: dict[str, float] = {
    "tpu v5 lite": 197e12,
    "tpu v5e": 197e12,
    "tpu v5p": 459e12,
    "tpu v4": 275e12,
    "tpu v3": 123e12,
    "tpu v2": 45e12,
}

# Estimated: ~8 cores x ~3 GHz x 2 FMA ports x 8 f32 lanes.  Labeled
# wherever it is surfaced; see module docstring.
CPU_PEAK_FLOPS_ESTIMATE = 4e11


def peak_flops(backend: Optional[str] = None,
               device_kind: Optional[str] = None) -> dict:
    """Claimed per-device peak FLOP/s for a backend/device pair.

    Returns ``{"peak_flops_per_device", "device", "estimated"}`` where
    ``estimated`` is True whenever the number did not come from the
    datasheet table (CPU, GPU, unknown TPU generations).
    """
    kind = (device_kind or "").lower()
    for key, peak in PEAK_FLOPS_TABLE.items():
        if key in kind:
            return {
                "peak_flops_per_device": peak,
                "device": device_kind,
                "estimated": False,
            }
    return {
        "peak_flops_per_device": CPU_PEAK_FLOPS_ESTIMATE,
        "device": device_kind or backend or "cpu",
        "estimated": True,
    }


def local_peak_flops() -> dict:
    """``peak_flops`` for the ambient jax backend (total across devices).

    Lazy-imports jax and degrades to the labeled CPU estimate when jax
    is unavailable, so offline CLI consumers never fail here.
    """
    backend = device_kind = None
    count = 1
    try:  # pragma: no cover - exercised only when jax import fails
        import jax

        backend = jax.default_backend()
        devices = jax.devices()
        count = len(devices)
        device_kind = devices[0].device_kind
    except Exception:
        pass
    info = peak_flops(backend, device_kind)
    info["device_count"] = count
    info["peak_flops_total"] = info["peak_flops_per_device"] * count
    info["backend"] = backend or "cpu"
    return info
