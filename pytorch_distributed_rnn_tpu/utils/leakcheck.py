"""Runtime resource-leak sentinel (``PDRNN_LEAKCHECK``).

The dynamic half of the PD4xx lifecycle pass (``lint/lifecycle.py`` is
the static half): where the lint proves close-on-every-path about the
acquisitions it can SEE, the sentinel checks the ones that actually
HAPPEN.  With the sentinel off nothing is patched - ``socket.socket``,
``builtins.open``, ``tempfile.TemporaryDirectory`` and
``threading.Thread.start`` keep their stdlib identity, no extra
threads, no per-acquire bookkeeping; the same zero-overhead-when-off
doctrine as :mod:`utils.threadcheck` and ``NULL_RECORDER``.  With
``PDRNN_LEAKCHECK=1`` (on in the CI chaos, serving, streaming and
fleet jobs) the factories become tracking wrappers and every
acquisition records its creation stack:

- **sockets** - created via ``socket.socket(...)`` / everything built
  on it (``create_connection``, ``accept``); drained when closed or
  detached.
- **files** - ``open(...)`` returns; drained when ``.closed``.
- **tempdirs** - ``tempfile.TemporaryDirectory``; drained on
  ``cleanup()`` (or when the directory is gone).
- **threads** - non-daemon ``Thread.start()``; drained once no longer
  alive (a successful ``join`` therefore drains it).

:func:`check_drained` is the drain boundary: server/router SIGTERM
shutdowns call it after closing their listeners/conns/threads, and an
``atexit`` hook runs it at process exit.  Anything still live raises
(or, at non-raising boundaries, alerts): a structured ``alert`` event
(``alert=resource_leak``) carrying each leak's kind, name, age and
creation stack goes through whatever recorder :func:`install` was
given, is flushed, and a faulthandler all-thread dump lands in the
watchdog's sidecar-adjacent stacks file - the post-mortem is on disk
before the exception unwinds.

Deliberately long-lived resources (a cached connection owned by a
pool, a module-lifetime log file) are excused with :func:`adopt` - the
runtime spelling of the lint's ``# owner:`` comment.

Activation mirrors threadcheck: the first :func:`maybe_install` call
(every CLI entry point makes one) resolves ``PDRNN_LEAKCHECK`` once;
:func:`install` forces the sentinel on (tests, drills) and
:func:`uninstall` restores the original factories.  The metrics
recorder self-registers on construction, so alerts reach the rank's
sidecar without extra wiring.
"""

from __future__ import annotations

import builtins
import logging
import os
import socket as socket_mod
import tempfile
import threading
import time
import traceback
import weakref

log = logging.getLogger(__name__)

LEAKCHECK_ENV = "PDRNN_LEAKCHECK"
_OFF_VALUES = ("", "0", "false", "off", "no")
# lazy prune threshold: registries of short-lived trackables (files!)
# must not grow without bound over a long run
_PRUNE_AT = 512


class LeakError(RuntimeError):
    """A drain boundary found resources still live: some exit path
    skipped a close/join (the runtime PD403/PD404)."""


def _creation_stack() -> list[str]:
    """Trimmed creation stack: the wrapper frames themselves are
    noise, the caller's frames are the evidence."""
    frames = traceback.format_stack(limit=18)[:-2]
    return [ln.rstrip("\n") for ln in frames][-12:]


class _Sentinel:
    """Process-wide tracking state.  Its mutex is a leaf, only held
    for dict surgery - never while closing or joining anything - so
    the sentinel cannot deadlock the patient."""

    def __init__(self, recorder=None):
        from pytorch_distributed_rnn_tpu.obs.recorder import NULL_RECORDER

        self.recorder = recorder if recorder is not None else NULL_RECORDER
        self._mu = threading.Lock()
        # id(obj) -> entry dict (kind, name, ref, stack, t0)
        self.entries: dict[int, dict] = {}
        self.created: dict[str, int] = {
            "socket": 0, "file": 0, "tempdir": 0, "thread": 0,
        }
        self.adopted = 0
        self.seq = 0
        self.violations: list[dict] = []
        self._reporting = threading.local()
        self._originals: dict = {}
        self.patched = False

    # -- registry ------------------------------------------------------

    def track(self, kind: str, obj, name: str) -> None:
        try:
            ref = weakref.ref(obj)
        except TypeError:
            return  # not weakrefable: cannot track without pinning it
        entry = {
            "kind": kind, "name": name, "ref": ref,
            "stack": _creation_stack(), "t0": time.monotonic(),
        }
        with self._mu:
            self.created[kind] += 1
            self.entries[id(obj)] = entry
            if len(self.entries) > _PRUNE_AT:
                self._prune_locked()

    def untrack(self, obj) -> None:
        with self._mu:
            self.entries.pop(id(obj), None)

    def adopt(self, obj, reason: str = "") -> None:
        with self._mu:
            if self.entries.pop(id(obj), None) is not None:
                self.adopted += 1

    def _is_leaked(self, entry: dict):
        """The live object when the entry still holds a leak, else
        None (GC'd, closed, finished - all count as drained)."""
        obj = entry["ref"]()
        if obj is None:
            return None
        kind = entry["kind"]
        try:
            if kind == "socket":
                return obj if obj.fileno() != -1 else None
            if kind == "file":
                return obj if not obj.closed else None
            if kind == "tempdir":
                return obj if os.path.exists(obj.name) else None
            if kind == "thread":
                if (obj.is_alive() and not obj.daemon
                        and obj is not threading.current_thread()
                        and obj is not threading.main_thread()):
                    return obj
                return None
        except Exception:  # pragma: no cover - defensive
            return None
        return None

    def _prune_locked(self) -> None:
        dead = [key for key, entry in self.entries.items()
                if self._is_leaked(entry) is None]
        for key in dead:
            del self.entries[key]

    def leaks(self) -> list[dict]:
        now = time.monotonic()
        with self._mu:
            entries = list(self.entries.values())
        out = []
        for entry in entries:
            if self._is_leaked(entry) is not None:
                out.append({
                    "kind": entry["kind"], "name": entry["name"],
                    "age_s": round(now - entry["t0"], 3),
                    "stack": entry["stack"],
                })
        return out

    def check(self, boundary: str, raise_on_leak: bool) -> list[dict]:
        found = self.leaks()
        if found:
            self._violation(boundary, found, raise_on_leak)
        return found

    # -- reporting -----------------------------------------------------

    def _alert(self, severity: str = "error", **fields):
        with self._mu:
            self.seq += 1
            seq = self.seq
        payload = dict(alert="resource_leak", severity=severity,
                       seq=seq, source="leakcheck", **fields)
        try:
            self.recorder.record("alert", **payload)
            self.recorder.flush()
        except Exception:  # diagnosis must never kill the patient
            log.exception("leakcheck: alert emission failed")
        return payload

    def _violation(self, boundary: str, found: list[dict],
                   raise_on_leak: bool) -> None:
        msg = (
            f"leakcheck: {len(found)} resource(s) still live at "
            f"drain boundary '{boundary}': "
            + ", ".join(f"{f['kind']} {f['name']} ({f['age_s']}s)"
                        for f in found[:8])
        )
        if getattr(self._reporting, "active", False):
            if raise_on_leak:
                raise LeakError(msg)
            return
        self._reporting.active = True
        try:
            payload = self._alert(boundary=boundary, count=len(found),
                                  leaks=found)
            self.violations.append(payload)
            path = getattr(self.recorder, "path", None)
            if path is not None:
                try:
                    from pytorch_distributed_rnn_tpu.obs import watchdog

                    watchdog.dump_stacks(
                        watchdog.stacks_path_for(path),
                        reason=f"leakcheck:resource_leak:{boundary}",
                    )
                except Exception:
                    log.exception("leakcheck: stack dump failed")
        finally:
            self._reporting.active = False
        log.error(msg)
        for f in found:
            log.error("leakcheck: %s %r created at:\n%s", f["kind"],
                      f["name"], "\n".join(f["stack"]))
        if raise_on_leak:
            raise LeakError(msg)

    # -- factory patches -----------------------------------------------

    def patch(self) -> None:
        if self.patched:
            return
        self.patched = True
        sentinel = self
        raw_socket = socket_mod.socket
        raw_open = builtins.open
        raw_tempdir = tempfile.TemporaryDirectory
        raw_start = threading.Thread.start
        self._originals = {
            "socket": raw_socket, "open": raw_open,
            "tempdir": raw_tempdir, "start": raw_start,
        }

        class TrackedSocket(raw_socket):  # type: ignore[valid-type,misc]
            # patching the MODULE attribute covers every construction
            # path: create_connection and accept() both build their
            # sockets through the module-global `socket` name

            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                sentinel.track("socket", self, _sock_label(self))

            def close(self):
                sentinel.untrack(self)
                super().close()

            def detach(self):
                sentinel.untrack(self)
                return super().detach()

        def tracked_open(file, *a, **kw):
            fh = raw_open(file, *a, **kw)
            try:
                sentinel.track("file", fh, str(file))
            except Exception:  # pragma: no cover - defensive
                pass
            return fh

        class TrackedTempDir(raw_tempdir):  # type: ignore[valid-type,misc]
            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                sentinel.track("tempdir", self, self.name)

            def cleanup(self):
                sentinel.untrack(self)
                super().cleanup()

        def tracked_start(thread, *a, **kw):
            if not thread.daemon:
                sentinel.track("thread", thread, thread.name)
            return raw_start(thread, *a, **kw)

        socket_mod.socket = TrackedSocket  # type: ignore[misc]
        builtins.open = tracked_open  # type: ignore[assignment]
        tempfile.TemporaryDirectory = TrackedTempDir  # type: ignore[misc]
        threading.Thread.start = tracked_start  # type: ignore[assignment]

    def unpatch(self) -> None:
        if not self.patched:
            return
        self.patched = False
        socket_mod.socket = self._originals["socket"]
        builtins.open = self._originals["open"]
        tempfile.TemporaryDirectory = self._originals["tempdir"]
        threading.Thread.start = self._originals["start"]
        self._originals = {}


def _sock_label(sock) -> str:
    try:
        return f"socket(fd={sock.fileno()})"
    except OSError:  # pragma: no cover - defensive
        return "socket(fd=?)"


# ---------------------------------------------------------------------------
# module-level switchboard (threadcheck's shape)

_STATE: _Sentinel | None = None
_RESOLVED = False
_ATEXIT_REGISTERED = False


def _register_atexit() -> None:
    global _ATEXIT_REGISTERED
    if _ATEXIT_REGISTERED:
        return
    _ATEXIT_REGISTERED = True
    import atexit

    def _at_exit():
        st = _STATE
        if st is not None:
            # report-only: raising inside atexit is noise, the alert +
            # dump on the sidecar are the useful artifacts
            st.check("process_exit", raise_on_leak=False)

    atexit.register(_at_exit)


def installed() -> bool:
    return _STATE is not None


def maybe_install() -> None:
    """Lazy env resolve - every CLI entry point calls this once.
    Unlike threadcheck there is no lock()-style chokepoint to hide
    the resolve in, so activation is an explicit entry-point call."""
    global _RESOLVED
    if _RESOLVED:
        return
    _RESOLVED = True
    if os.environ.get(LEAKCHECK_ENV, "").lower() not in _OFF_VALUES:
        install()


def install(recorder=None) -> _Sentinel:
    """Force the sentinel on (tests, drills, recorder self-register);
    idempotent - re-install updates the recorder but keeps the
    registry and patches."""
    global _STATE, _RESOLVED
    _RESOLVED = True
    if _STATE is None:
        _STATE = _Sentinel(recorder)
        _STATE.patch()
        _register_atexit()
    elif recorder is not None:
        _STATE.recorder = recorder
    return _STATE


def uninstall() -> None:
    """Restore the stdlib factories and reset to unresolved (tests).
    Objects created while tracked stay alive and functional - they
    just stop being watched."""
    global _STATE, _RESOLVED
    if _STATE is not None:
        _STATE.unpatch()
    _STATE = None
    _RESOLVED = False


def adopt(obj, reason: str = "") -> None:
    """Transfer ownership out of the sentinel's custody - the runtime
    spelling of the lint's ``# owner:`` comment.  Off: a single global
    read."""
    st = _STATE
    if st is not None:
        st.adopt(obj, reason)


def check_drained(boundary: str) -> list[dict]:
    """Non-raising drain boundary (server/router SIGTERM shutdown):
    anything still live emits the structured alert + creation-site
    dump and is returned.  Off: a single global read."""
    st = _STATE
    if st is None:
        return []
    return st.check(boundary, raise_on_leak=False)


def assert_drained(boundary: str) -> None:
    """Raising drain boundary (tests, drills): still-live resources
    alert, dump, then raise :class:`LeakError`."""
    st = _STATE
    if st is None:
        return
    st.check(boundary, raise_on_leak=True)


def stats() -> dict:
    """Sentinel introspection for tests: per-kind creation counts,
    live tracked entries, violations."""
    st = _STATE
    if st is None:
        return {"installed": False}
    with st._mu:
        return {
            "installed": True,
            "created": dict(st.created),
            "tracked": len(st.entries),
            "adopted": st.adopted,
            "violations": len(st.violations),
        }
