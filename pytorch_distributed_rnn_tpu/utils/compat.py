"""JAX version-compatibility shims.

The framework targets the jax>=0.9 public API (``jax.shard_map``,
``pltpu.CompilerParams``); older 0.4.x installs keep the same objects
under their pre-promotion names.  Everything version-sensitive imports
through here so the call sites stay written against the current API.
"""

from __future__ import annotations

import jax

try:  # jax >= 0.6: top-level export
    from jax import shard_map
except ImportError:  # jax 0.4.x
    import functools

    from jax.experimental.shard_map import shard_map as _shard_map_04

    @functools.wraps(_shard_map_04)
    def shard_map(*args, **kwargs):  # type: ignore[no-redef]
        # the replication-check kwarg was renamed check_rep -> check_vma
        # when shard_map was promoted out of jax.experimental; the
        # framework is written against the promoted spelling
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_04(*args, **kwargs)

    # Re-export so `from jax import shard_map` resolves in any module
    # loaded after this one (the package __init__ imports this shim).
    jax.shard_map = shard_map


if not hasattr(jax.distributed, "is_initialized"):  # added after 0.4.x
    def _distributed_is_initialized() -> bool:
        from jax._src import distributed as _distributed

        return _distributed.global_state.client is not None

    jax.distributed.is_initialized = _distributed_is_initialized


try:  # jax >= 0.6
    axis_size = jax.lax.axis_size
except AttributeError:  # jax 0.4.x: axis_frame(name) IS the size
    def axis_size(axis_name):
        return jax.core.axis_frame(axis_name)

    # patch onto jax.lax so the package's `lax.axis_size(...)` call
    # sites (written against the promoted API) resolve everywhere
    jax.lax.axis_size = axis_size


def pallas_tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` (jax>=0.7) / ``TPUCompilerParams`` (0.4.x)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)


__all__ = ["shard_map", "axis_size", "pallas_tpu_compiler_params"]
