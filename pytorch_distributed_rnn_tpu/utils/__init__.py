from pytorch_distributed_rnn_tpu.utils.platform import (
    apply_platform_overrides,
    ensure_usable_backend,
    probe_backend,
)

__all__ = [
    "apply_platform_overrides",
    "ensure_usable_backend",
    "probe_backend",
]
