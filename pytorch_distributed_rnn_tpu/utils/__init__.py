from pytorch_distributed_rnn_tpu.utils.platform import apply_platform_overrides

__all__ = ["apply_platform_overrides"]
