from pytorch_distributed_rnn_tpu.utils.hw import (
    CPU_PEAK_FLOPS_ESTIMATE,
    PEAK_FLOPS_TABLE,
    local_peak_flops,
    peak_flops,
)
from pytorch_distributed_rnn_tpu.utils.platform import (
    apply_platform_overrides,
    ensure_usable_backend,
    probe_backend,
)

__all__ = [
    "CPU_PEAK_FLOPS_ESTIMATE",
    "PEAK_FLOPS_TABLE",
    "apply_platform_overrides",
    "ensure_usable_backend",
    "local_peak_flops",
    "peak_flops",
    "probe_backend",
]
