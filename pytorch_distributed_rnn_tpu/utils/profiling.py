"""Peak-memory and wall-clock measurement around the training loop.

Capability parity with the reference's use of
``memory_profiler.memory_usage((train_inner, ...))`` + ``time.perf_counter``
(``/root/reference/src/motion/trainer/base.py:93-96``): run a callable,
sample peak RSS while it runs, return (result, peak_mb, seconds).

TPU-native differences: no external dependency - a sampler thread reads
``/proc/self/status`` VmRSS directly - and, when the backend exposes it,
device HBM peaks from ``device.memory_stats()`` are collected too (RSS alone
says nothing about accelerator footprint).
"""

from __future__ import annotations

import threading
import time


def _rss_mb() -> float:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) / 1024.0  # kB -> MiB
    except OSError:
        pass
    return 0.0


def device_memory_peaks_mb() -> dict:
    """Per-device peak HBM in MiB, where the PJRT backend reports it."""
    import jax

    peaks = {}
    for device in jax.local_devices():
        try:
            stats = device.memory_stats()
        except Exception:
            continue
        if stats and "peak_bytes_in_use" in stats:
            peaks[str(device)] = stats["peak_bytes_in_use"] / (1024.0 * 1024.0)
    return peaks


def measure_memory_and_time(fn, interval: float = 0.1,
                            include_device_memory: bool = False):
    """Run ``fn()``; return ``(result, peak_rss_mb, duration_seconds)``.

    With ``include_device_memory=True`` a fourth element is appended:
    the per-device HBM peak dict from :func:`device_memory_peaks_mb`,
    read AFTER ``fn`` completes (PJRT peaks are cumulative, so the
    post-run read covers the run).  Opt-in keyword so the historical
    3-tuple contract - and every existing caller - is untouched."""
    peak = [_rss_mb()]
    stop = threading.Event()

    def sample():
        while not stop.is_set():
            peak[0] = max(peak[0], _rss_mb())
            stop.wait(interval)

    sampler = threading.Thread(target=sample, daemon=True)
    start = time.perf_counter()
    sampler.start()
    try:
        result = fn()
    finally:
        stop.set()
        sampler.join(timeout=2.0)
    duration = time.perf_counter() - start
    peak[0] = max(peak[0], _rss_mb())
    if include_device_memory:
        try:
            device_peaks = device_memory_peaks_mb()
        except Exception:  # backend without memory_stats: peaks are a bonus
            device_peaks = {}
        return result, peak[0], duration, device_peaks
    return result, peak[0], duration
