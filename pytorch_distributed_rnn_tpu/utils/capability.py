"""Runtime capability probes for backend features that vary by platform.

Some tier-1 tests exercise features the ambient XLA backend may not
implement (the CPU backend cannot run multiprocess computations, and its
SPMD partitioner rejects programs that lower to a ``PartitionId``
instruction).  These are ENVIRONMENT limits, not code regressions - so
the tests probe the actual capability and ``skipif`` on the result,
keeping the suite green where the feature is honestly absent and red
where it truly broke.

Each probe runs the smallest program that exercises the capability and
caches its verdict for the process (``lru_cache``), so a suite pays each
probe once.
"""

from __future__ import annotations

import functools
import os
import subprocess
import sys

_PROBE_COORD_PORT = 12911


@functools.lru_cache(maxsize=None)
def supports_spmd_ring_collectives() -> bool:
    """Whether jitting a shard_map ring (scan over ``lax.ppermute`` with
    per-shard ``lax.axis_index`` offsets, the ``ring_flash_attention``
    shape) compiles on this backend.  XLA:CPU's SPMD partitioner rejects
    the lowered ``PartitionId`` instruction; TPU/GPU accept it."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from pytorch_distributed_rnn_tpu.ops.pallas_attention import (
        ring_flash_attention,
    )
    from pytorch_distributed_rnn_tpu.parallel import make_mesh
    from pytorch_distributed_rnn_tpu.utils.compat import shard_map

    if len(jax.devices()) < 2:
        return False
    mesh = make_mesh({"sp": 2})
    fn = shard_map(
        functools.partial(ring_flash_attention, axis="sp", causal=False),
        mesh=mesh,
        in_specs=(P(None, None, "sp"),) * 3,
        out_specs=P(None, None, "sp"),
        check_vma=False,
    )
    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 1, 16, 8)), dtype=jnp.float32)
        for _ in range(3)
    )
    try:
        jax.jit(fn)(q, k, v)
    except Exception as exc:
        if "PartitionId" in str(exc):
            return False
        raise  # an unknown failure is a regression, not a missing feature
    return True


_MULTIPROCESS_PROBE = """
import os
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["PDRNN_PROBE_COORD"],
    num_processes=2, process_id=int(os.environ["PDRNN_PROBE_PID"]))
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
devs = jax.devices()
n = len(devs)
mesh = Mesh(np.array(devs), ("dp",))
arr = jax.make_array_from_callback(
    (n,), NamedSharding(mesh, P("dp")),
    lambda idx: np.arange(n, dtype=np.float32)[idx])
total = jax.jit(lambda x: jnp.sum(x), out_shardings=NamedSharding(mesh, P()))(arr)
assert float(total) == n * (n - 1) / 2, float(total)
print("CAP_OK")
"""


@functools.lru_cache(maxsize=None)
def supports_multiprocess_backend(timeout: float = 120.0) -> bool:
    """Whether a 2-process ``jax.distributed`` world can jit a
    computation spanning both processes' devices.  XLA:CPU raises
    "Multiprocess computations aren't implemented on the CPU backend";
    real TPU/GPU backends implement the cross-process collectives."""
    coord = f"127.0.0.1:{_PROBE_COORD_PORT}"
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        # exactly one virtual device per process: an inherited
        # device-count flag would change the probe's world shape
        flags = " ".join(
            f for f in env.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_force_host_platform_device_count")
        )
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=1"
        ).strip()
        env["PDRNN_PROBE_COORD"] = coord
        env["PDRNN_PROBE_PID"] = str(pid)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _MULTIPROCESS_PROBE],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True,
            )
        )
    ok = True
    try:
        for proc in procs:
            out, _ = proc.communicate(timeout=timeout)
            ok = ok and proc.returncode == 0 and "CAP_OK" in out
    except subprocess.TimeoutExpired:
        ok = False
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
    return ok
