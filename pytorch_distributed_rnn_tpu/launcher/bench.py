"""Benchmark sweep runner: shuffled runs, append-only JSON, resume-by-skip.

Capability parity with the reference's fabfile benchmark harness
(``/root/reference/fabfile.py:48-66,130-191,257-290``):

- ``BENCHMARK_RUN`` / ``DEBUG_RUN`` sweep definitions — cartesian product of
  trainers × device counts × batch sizes, seed 123456789, 1 epoch,
  ``--no-validation`` (``fabfile.py:48-66``).
- runs execute in shuffled order; each result is appended to a JSON file
  with the full command, stdout and stderr (``fabfile.py:257-290``).
- a crashed sweep resumes by skipping configs whose command string already
  appears in the results file (``fabfile.py:270-276``).
- the network-perturbation sweep applies delay/loss around runs
  (``fabfile.py:130-191``) — here injected into the native TCP transport
  via the ``PDRNN_FAULT_*`` env contract instead of ``tc netem``.
"""

from __future__ import annotations

import itertools
import json
import os
import shlex
import random
import subprocess
import sys
import time
from pathlib import Path

from pytorch_distributed_rnn_tpu.launcher.commands import (
    RunConfig,
    command_string,
    get_command,
    make_config,
)

# Sweep definitions mirroring fabfile.py:29-66.  "devices" replaces the
# reference's host counts {1,2,4,8,12}; 8 is the canonical TPU-slice/virtual
# CPU mesh size here.
# The one run-parameter base shared by every sweep (reference sweep
# constants, fabfile.py:48-66; the 0.05 split is what yields the
# reference's 6912-seq train set - SURVEY §5 config quirks).
BASE_PARAMETERS = {
    "epochs": 1,
    "seed": 123456789,
    "learning-rate": 0.0025,
    "validation-fraction": 0.05,
    "no-validation": True,
    "log": "INFO",
}

BENCHMARK_RUN = {
    "trainers": ["local", "distributed", "horovod", "distributed-native",
                 "fsdp"],
    "devices": [1, 2, 4, 8],
    "slots": [1],
    "batch_sizes": [480, 960, 1440],
    "parameters": dict(BASE_PARAMETERS),
}

# Real multi-slot topologies (the reference's processes-per-host dimension,
# slots 1/2/4 in its results data): `slots` OS processes per run -
# `distributed` rendezvouses them into one jax.distributed world,
# `distributed-native` runs process-per-rank over the TCP collectives.
SLOTS_RUN = {
    "trainers": ["distributed", "distributed-native"],
    "devices": [1, 2, 4],
    "slots": [2],
    "batch_sizes": [1440],
    "parameters": dict(BASE_PARAMETERS),
}

DEBUG_RUN = {
    "trainers": ["local"],
    "devices": [1],
    "slots": [1],
    "batch_sizes": [1440],
    "parameters": dict(BASE_PARAMETERS),
}

# Real-chip rows (the reference's committed results_baseline_{1,2,3}.json
# re-runs, /root/reference: local trainer at the three sweep batch sizes):
# run with --backend native so the trainer uses the attached accelerator
# instead of the virtual-device study platform.
CHIP_RUN = {
    "trainers": ["local"],
    "devices": [1],
    "slots": [1],
    # 2880 extends the reference's {480,960,1440} grid one doubling up:
    # the batch-scaling curve is what ONE chip can honestly measure
    # (VERDICT r2: the virtual-CPU mesh has no scaling signal)
    "batch_sizes": [480, 960, 1440, 2880],
    "parameters": dict(BASE_PARAMETERS),
}

# Amortized end-to-end chip row (VERDICT r3 item 2): the 1-epoch CLI
# rows above are ~99% fixed cost on a jit framework (backend probe,
# compile, data upload), understating steady state ~80x vs the bench
# loop.  20 epochs amortize the fixed costs so per-epoch time approaches
# the steady-state number; honest counterpart to the reference's 1-epoch
# sweeps, which had no compile cliff (eager PyTorch on a Pi).
CHIP_AMORTIZED_RUN = {
    "trainers": ["local"],
    "devices": [1],
    "slots": [1],
    "batch_sizes": [1440],
    "parameters": {**BASE_PARAMETERS, "epochs": 20},
}

# Fused flavor of the amortized row: --fuse-run compiles all 20 epochs
# into ONE lax.scan program (training/base.py fused_run gate), so the
# tunnel round-trip is paid once per RUN instead of once per epoch,
# while INFO logging keeps the perf-line contract intact.  The r4 chip
# window measured the per-epoch row at 2.23 s/epoch = one ~2.1 s tunnel
# RTT per epoch-dispatch on top of the ~0.1 s device compute; this row
# is the same workload with the per-epoch host syncs removed - the
# CLI-path number that should land within ~2x of the bench loop
# (VERDICT r3 item 2's target).
# dropout 0 here: (a) the fused path keeps bit-parity with the per-epoch
# path only when the batch divides the training set, which 1440 does not
# (base.py fusable gate), and (b) the reference's --dropout flag was DEAD
# (parsed, never applied - PARITY.md), so no-dropout IS its effective
# measured workload.
CHIP_FUSED_RUN = {
    "trainers": ["local"],
    "devices": [1],
    "slots": [1],
    "batch_sizes": [1440],
    "parameters": {**BASE_PARAMETERS, "epochs": 20, "fuse-run": True,
                   "dropout": 0},
}

# Per-epoch companion at dropout 0: the fused-vs-per-epoch delta is a
# clean measurement of dispatch granularity (one tunnel RTT per run vs
# per epoch) only when dropout matches - CHIP_AMORTIZED_RUN carries the
# CLI-default dropout 0.1, which changes per-batch mask work and the
# compiled program, not just the dispatch count.
CHIP_AMORTIZED_NODROP_RUN = {
    "trainers": ["local"],
    "devices": [1],
    "slots": [1],
    "batch_sizes": [1440],
    "parameters": {**BASE_PARAMETERS, "epochs": 20, "dropout": 0},
}

# Companion char-LM chip row (the LM family as a CLI citizen on real
# hardware): H=512 keeps the fused Pallas kernel in play ('auto' takes the
# fused path for hidden <= 512 on TPU - ops/rnn.py resolve_rnn_impl).
CHIP_LM_RUN = {
    "trainers": ["local"],
    "devices": [1],
    "slots": [1],
    "batch_sizes": [256],
    "parameters": {
        **BASE_PARAMETERS,
        "model": "char",
        "seq-length": 128,
        "hidden-units": 512,
        "stacked-layer": 2,
        "dropout": 0,
    },
}

# The strategy x family matrix as explicit runs - one committed run per
# README matrix cell (every cell trainable since r3).  Explicit configs,
# not a cartesian product: each family carries its own flag constraints
# (attention/moe reject dropout; char sp needs sp | seq_length+1) and
# each strategy its own world shape.  `devices` is the dp world for the
# dp strategies and the TOTAL mesh size for mesh rows.
_MATRIX_BASE = {
    "epochs": 1, "seed": 123456789, "learning-rate": 0.0025,
    "validation-fraction": 0.05, "no-validation": True, "log": "INFO",
    "batch-size": 48, "hidden-units": 16, "stacked-layer": 2,
    "dropout": 0,
}


def _mesh_spec_of(trainer_string: str) -> str:
    """Extract the --mesh value from a trainer string, accepting both
    ``--mesh spec`` and ``--mesh=spec`` forms."""
    tokens = shlex.split(trainer_string)
    for i, tok in enumerate(tokens):
        if tok == "--mesh" and i + 1 < len(tokens):
            return tokens[i + 1]
        if tok.startswith("--mesh="):
            return tok.split("=", 1)[1]
    raise ValueError(f"no --mesh value in trainer string: {trainer_string!r}")


def matrix_configs(extra_parameters=None, backend="cpu"):
    """One RunConfig per strategy x family matrix cell."""
    from math import prod

    from pytorch_distributed_rnn_tpu.parallel.strategy import parse_mesh_spec

    rows = []
    # mesh rows are (trainer_string, extra main-parser params): subcommand
    # flags (--mesh/--pp-schedule/--pp-chunks) ride in the trainer string,
    # main-parser flags (--stacked-layer/--moe-top-k) must precede the
    # subcommand and therefore go through params
    for family, fam_params, meshes in (
        ("rnn", {}, [
            ("mesh --mesh dp=2,sp=2 --sp-schedule sequential", {}),
            # interleaved 1F1B: 2 virtual chunks per pp device
            # (4 layers = 2 stages x 2 chunks x 1 layer)
            ("mesh --mesh dp=1,pp=2 --pp-schedule interleaved "
             "--pp-chunks 2", {"stacked-layer": 4}),
        ]),
        ("char", {"seq-length": 15}, [
            ("mesh --mesh dp=2,sp=2", {}),
            ("mesh --mesh dp=2,sp=2,tp=2", {}),
        ]),
        ("attention", {}, [
            ("mesh --mesh dp=2,sp=2,tp=2", {}),
            ("mesh --mesh dp=2,pp=2", {}),
            # Megatron tp inside each GPipe stage (r4)
            ("mesh --mesh dp=1,pp=2,tp=2", {}),
        ]),
        ("moe", {}, [
            ("mesh --mesh dp=2,ep=2", {}),
            # GShard top-2 routing over the ep mesh (r4)
            ("mesh --mesh dp=2,ep=2", {"moe-top-k": 2}),
            # expert-choice routing over the ep mesh (r4)
            ("mesh --mesh dp=2,ep=2", {"moe-router": "expert"}),
            # GShard grouped routing: per-shard tokens (48/4 rows x 128
            # steps = 1536) split into groups of 256 (r5)
            ("mesh --mesh dp=2,ep=2", {"moe-group-size": 256}),
        ]),
    ):
        params = {**_MATRIX_BASE, "model": family, **fam_params,
                  **(extra_parameters or {})}
        for trainer, devices in (
            ("local", 1), ("distributed", 2), ("horovod", 2),
            ("fsdp", 2), ("distributed-native", 2),
            ("parameter-server", 2),
        ):
            rows.append(make_config(trainer, devices, 1, params, backend))
        for mesh_trainer, mesh_params in meshes:
            size = prod(parse_mesh_spec(_mesh_spec_of(mesh_trainer)).values())
            rows.append(make_config(mesh_trainer, size, 1,
                                    {**params, **mesh_params}, backend))
    return rows


# fabfile.py:130-191: delays 0-400 ms, loss 0-15 %.
NETWORK_RULES = [
    ("delay", 0.0),
    ("delay", 100.0),
    ("delay", 200.0),
    ("delay", 400.0),
    ("loss", 0.05),
    ("loss", 0.10),
    ("loss", 0.15),
]


def expand_run_configs(run: dict, extra_parameters=None, backend="cpu",
                       fault_type=None, fault_value=0.0):
    """Cartesian expansion of a sweep definition into RunConfigs."""
    configs = []
    for trainer, devices, slots, bs in itertools.product(
        run["trainers"], run["devices"], run["slots"], run["batch_sizes"]
    ):
        if trainer == "local" and devices * slots != 1:
            continue  # local is single-device by definition
        params = dict(run["parameters"])
        params["batch-size"] = bs
        params.update(extra_parameters or {})
        configs.append(
            make_config(trainer, devices, slots, params, backend,
                        fault_type, fault_value)
        )
    return configs


def load_results(path) -> list:
    path = Path(path)
    if not path.exists():
        return []
    with open(path) as f:
        return json.load(f)


def _append_result(path, results: list, entry: dict):
    results.append(entry)
    tmp = Path(str(path) + ".tmp")
    with open(tmp, "w") as f:
        json.dump(results, f, indent=1)
    os.replace(tmp, path)


def metrics_sidecar_path(metrics_dir, config: RunConfig,
                         salt: str = "") -> Path:
    """The per-run metrics sidecar path under ``metrics_dir``: keyed by
    the hash of (``salt``, command string).  ``salt`` is the sweep's
    results path, so re-running the SAME config into a different results
    file (a baseline-vs-candidate diff sharing one --metrics-dir) gets
    its own sidecar instead of truncating the earlier sweep's - while
    repeats over the same results file still overwrite only their own."""
    import hashlib

    digest = hashlib.sha1(
        f"{salt}\n{command_string(config)}".encode()
    ).hexdigest()[:16]
    return Path(metrics_dir) / f"run-{digest}.jsonl"


def execute_run(config: RunConfig, timeout: float | None = None,
                cwd=None, metrics_dir=None, metrics_salt: str = "") -> dict:
    """Run one config as a subprocess; capture everything the notebooks and
    resume logic need (the per-run dict shape follows fabfile.py:280-290).

    With ``metrics_dir`` set, the run gets a ``--metrics`` sidecar under
    it and the entry archives the path as ``metrics_path`` - the
    structured measurement channel ``evaluation/analysis.py`` prefers
    over the stderr perf-line regex.  The archived ``command`` stays the
    UNinstrumented one so resume-by-skip matches runs across sweeps with
    and without telemetry.
    """
    metrics_path = None
    run_config = config
    if metrics_dir is not None:
        sidecar = metrics_sidecar_path(metrics_dir, config, metrics_salt)
        sidecar.parent.mkdir(parents=True, exist_ok=True)
        metrics_path = str(sidecar)
        run_config = make_config(
            config.trainer, config.devices, config.slots,
            {**config.parameters_dict(), "metrics": metrics_path},
            config.backend, config.fault_type, config.fault_value,
        )
    argv, extra_env = get_command(run_config)
    start_wall = time.time()
    env = dict(os.environ)
    env.update(extra_env)
    # make the framework importable regardless of the run's cwd (the
    # rsync-deploy analogue: the launcher guarantees code visibility)
    repo_root = str(Path(__file__).resolve().parents[2])
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH")) if p
    )
    start = time.perf_counter()
    try:
        proc = subprocess.run(
            argv, capture_output=True, text=True, env=env, timeout=timeout,
            cwd=cwd,
        )
        returncode, stdout, stderr = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as exc:
        # record the timeout as a FAILED run so the append+resume contract
        # holds: a hung config must not re-block the sweep on every re-run
        returncode = -1
        stdout = exc.stdout.decode() if isinstance(exc.stdout, bytes) else (
            exc.stdout or "")
        stderr = (exc.stderr.decode() if isinstance(exc.stderr, bytes) else (
            exc.stderr or "")) + f"\n[launcher] timed out after {timeout}s"
    duration = time.perf_counter() - start
    entry = {
        "trainer": config.trainer,
        "devices": config.devices,
        "slots": config.slots,
        "parameters": config.parameters_dict(),
        "rule_type": config.fault_type,
        "rule_value": config.fault_value,
        "command": command_string(config),
        "returncode": returncode,
        "stdout": stdout,
        "stderr": stderr,
        "wall_seconds": duration,
    }
    if metrics_path is not None:
        entry["metrics_path"] = metrics_path
        _append_run_span(metrics_path, config, start_wall, duration,
                         returncode)
        ledger = _ledger_excerpt(metrics_path)
        if ledger is not None:
            entry["ledger"] = ledger
    return entry


def _ledger_excerpt(metrics_path) -> dict | None:
    """The archived efficiency-ledger block of one run entry: the four
    headline numbers (obs/ledger.py aggregate), so sweep results carry
    goodput/MFU/fault-tax evidence without re-reading sidecars.  Best
    effort - schema-1 or absent sidecars archive nothing, never fail
    the sweep."""
    try:
        from pytorch_distributed_rnn_tpu.obs.ledger import ledger_run

        agg = ledger_run(metrics_path)["aggregate"]
        return {k: agg.get(k) for k in (
            "goodput", "mfu_est", "fault_tax_s", "comm_wait_frac")}
    except Exception:
        return None


def _append_run_span(metrics_path, config: RunConfig, start_wall: float,
                     duration: float, returncode: int) -> None:
    """Append the run's ROOT span to the rank-0 sidecar: the launcher
    is the only process that saw the whole subprocess lifetime (spawn,
    backend probe, compile, train, teardown), so the trace timeline
    gets its enclosing bar from here.  Wall-clock only (``t``; no
    ``tm``): the child's monotonic epoch is not ours - the timeline
    exporter maps wall-only events directly onto the aligned timeline.

    Skipped when the sidecar is missing (run died before its recorder)
    or ends mid-line (killed mid-append): appending after a torn tail
    would glue the span onto the partial line and turn the loader's
    tolerated-torn-tail case into a hard error."""
    path = Path(metrics_path)
    try:
        if not path.exists():
            return
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            if f.tell() == 0:
                return
            f.seek(-1, os.SEEK_END)
            if f.read(1) != b"\n":
                return
        span = {
            "kind": "span", "name": "run", "cat": "run", "rank": 0,
            "t": start_wall, "dur_s": duration,
            "clock": "launcher",
            "trainer": config.trainer, "devices": config.devices,
            "slots": config.slots, "returncode": returncode,
        }
        with open(path, "a") as f:
            f.write(json.dumps(span) + "\n")
    except OSError:
        pass  # telemetry must never fail the sweep


def run_benchmark(
    configs,
    results_path,
    shuffle_seed: int | None = 0,
    timeout: float | None = None,
    executor=execute_run,
    log=print,
    metrics_dir=None,
):
    """Execute ``configs`` (shuffled), appending to ``results_path``.

    Configs whose command string already appears in the results file are
    skipped — re-running after a crash continues where it left off.
    Returns the list of result entries actually executed (callers can
    check ``returncode`` to distinguish a clean sweep from failures).
    ``metrics_dir`` turns on per-run telemetry sidecars (see
    :func:`execute_run`).
    """
    results = load_results(results_path)
    executed_commands = {r.get("command") for r in results}

    pending = [c for c in configs if command_string(c) not in executed_commands]
    skipped = len(configs) - len(pending)
    if skipped:
        log(f"resume: skipping {skipped} already-executed run(s)")

    if shuffle_seed is not None:
        random.Random(shuffle_seed).shuffle(pending)

    # only forwarded when set, so custom executors (tests inject stubs
    # with the historical signature) keep working untouched
    extra_kwargs = {} if metrics_dir is None else {
        "metrics_dir": metrics_dir,
        # salt the sidecar names with the results path so two sweeps
        # sharing a --metrics-dir (baseline vs candidate) never
        # truncate each other's telemetry
        "metrics_salt": str(results_path),
    }
    executed = []
    for i, config in enumerate(pending):
        log(f"[{i + 1}/{len(pending)}] {command_string(config)}")
        entry = executor(config, timeout=timeout, **extra_kwargs)
        _append_result(results_path, results, entry)
        executed.append(entry)
        status = "ok" if entry.get("returncode") == 0 else "FAILED"
        log(f"  -> {status} in {entry.get('wall_seconds', 0):.1f}s")
    return executed


def run_network_test(
    results_path,
    devices: int = 2,
    batch_size: int = 1440,
    rules=NETWORK_RULES,
    extra_parameters=None,
    backend: str = "cpu",
    timeout: float | None = None,
    executor=execute_run,
    log=print,
    native_ranks: int = 4,
    metrics_dir=None,
):
    """Network-perturbation sweep (``fab run_network_test`` analogue).

    The reference perturbed DDP **and** Horovod over MPI/Ethernet with
    ``tc netem`` (fabfile.py:130-183).  Here the two true-network
    strategies are the parameter server AND process-per-rank native DDP -
    both ride the C++ TCP transport, whose ``PDRNN_FAULT_*`` delay/loss
    injection stands in for netem - so the sweep perturbs both:
    per delay/loss rule, one PS world at ``devices`` ranks and one
    ``distributed-native`` world at ``native_ranks`` ranks (the strategy
    whose ring allreduce actually crosses the injected links at every
    step).  The in-process SPMD ``distributed`` strategy has no host
    network to perturb (its collectives ride ICI) and runs unperturbed as
    the control row.  The (delay, 0) rule doubles as each strategy's
    own unperturbed baseline.
    """
    params = dict(BASE_PARAMETERS)
    params["batch-size"] = batch_size
    params.update(extra_parameters or {})

    configs = [make_config("distributed", devices, 1, params, backend)]
    for rule_type, rule_value in rules:
        configs.append(
            make_config(
                "parameter-server", devices, 1, params, backend,
                fault_type=rule_type, fault_value=rule_value,
            )
        )
        configs.append(
            make_config(
                "distributed-native", native_ranks, 1, params, backend,
                fault_type=rule_type, fault_value=rule_value,
            )
        )
    return run_benchmark(
        configs, results_path, shuffle_seed=None, timeout=timeout,
        executor=executor, log=log, metrics_dir=metrics_dir,
    )


def launch_jax_world(
    num_processes: int,
    cli_args,
    *,
    devices_per_process: int = 1,
    trainer: str = "distributed",
    coordinator_port: int = 29601,
    timeout: float = 600.0,
    cwd=None,
    backend: str = "cpu",
):
    """Stand up a ``num_processes``-process multi-controller JAX world.

    Each process runs ``python -m pytorch_distributed_rnn_tpu.main
    <cli_args> <trainer>`` with ``PDRNN_COORDINATOR`` set, so they
    rendezvous through ``jax.distributed`` into ONE global mesh of
    ``num_processes * devices_per_process`` devices - the mpirun-world
    analogue over DCN instead of MPI (``/root/reference/fabfile.py:
    216-223``).  ``backend="cpu"`` gives each rank a virtual CPU platform;
    ``"native"`` keeps the ambient (accelerator) platform.  Returns
    per-rank ``(returncode, stdout, stderr)`` in rank order; raises if any
    rank fails or times out."""
    from pytorch_distributed_rnn_tpu.utils.worlds import spawn_world

    repo_root = str(Path(__file__).resolve().parents[2])
    rank_cmds = []
    for pid in range(num_processes):
        env = dict(os.environ)
        env.update(
            PDRNN_COORDINATOR=f"127.0.0.1:{coordinator_port}",
            PDRNN_NUM_PROCESSES=str(num_processes),
            PDRNN_PROCESS_ID=str(pid),
        )
        if backend == "cpu":
            env["PDRNN_PLATFORM"] = "cpu"
            env["PDRNN_NUM_CPU_DEVICES"] = str(devices_per_process)
            # an inherited device-count flag (e.g. the test suite's
            # 8-device XLA_FLAGS) would win over PDRNN_NUM_CPU_DEVICES and
            # inflate the global world: rank-local meshes built from the
            # first N global devices could then land entirely on process
            # 0's devices - unfetchable from the other controllers
            flags = " ".join(
                f for f in env.get("XLA_FLAGS", "").split()
                if not f.startswith("--xla_force_host_platform_device_count")
            )
            if flags:
                env["XLA_FLAGS"] = flags
            else:
                env.pop("XLA_FLAGS", None)
        else:
            # native: partition the host's TPU chips between ranks so each
            # controller owns devices_per_process chips (libtpu allows one
            # owner per chip; without this every rank would claim - and
            # fight over - the full ambient device set)
            first = pid * devices_per_process
            env["TPU_VISIBLE_DEVICES"] = ",".join(
                str(first + i) for i in range(devices_per_process)
            )
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (repo_root, env.get("PYTHONPATH")) if p
        )
        rank_cmds.append((
            [sys.executable, "-m", "pytorch_distributed_rnn_tpu.main",
             *map(str, cli_args), *shlex.split(trainer)],
            env,
        ))
    return spawn_world(rank_cmds, timeout=timeout, cwd=cwd)


def parse_hosts(spec: str):
    """``"h1:2,h2:2"`` -> ``[("h1", 2), ("h2", 2)]`` (the reference's
    mpirun host:slots strings, ``fabfile.py:51,203-206``)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, slots = part.partition(":")
        n = int(slots) if slots else 1
        if n < 1:
            raise ValueError(f"host {host!r} has non-positive slots {n}")
        out.append((host, n))
    if not out:
        raise ValueError(f"empty hosts spec {spec!r}")
    return out


def host_world_commands(hosts, cli_args, *, trainer: str = "distributed",
                        coordinator_port: int = 29601,
                        python: str = "python3",
                        repo_dir: str = "~/pytorch_distributed_rnn_tpu"):
    """Synthesize the per-host SSH command lines that stand up one
    multi-host ``jax.distributed`` world - the ``fab run_all`` command
    synthesis re-targeted from ``mpirun --host h1:s,...``
    (``/root/reference/fabfile.py:216-223``) to coordinator-env worlds.

    Host 0 is the coordinator; each host h with s slots runs s processes
    (process ids assigned host-major), every one exporting
    ``PDRNN_COORDINATOR/PDRNN_NUM_PROCESSES/PDRNN_PROCESS_ID``.  Returns
    ``[(host, command_string), ...]`` - one SSH invocation per process.
    On TPU pods this is usually unnecessary (``jax.distributed``
    auto-discovers from the metadata service); it exists for generic
    CPU/GPU clusters and for parity with the reference's launcher.
    """
    pairs = list(hosts)
    num_processes = sum(s for _, s in pairs)
    coordinator = f"{pairs[0][0]}:{coordinator_port}"
    flag_str = " ".join(shlex.quote(str(a)) for a in cli_args)
    commands = []
    pid = 0
    for host, slots in pairs:
        for _ in range(slots):
            env = (
                f"PDRNN_COORDINATOR={coordinator} "
                f"PDRNN_NUM_PROCESSES={num_processes} "
                f"PDRNN_PROCESS_ID={pid}"
            )
            inner = (
                f"cd {repo_dir} && {env} {python} -m "
                f"pytorch_distributed_rnn_tpu.main {flag_str} {trainer}"
            )
            commands.append((host, f"ssh {host} {shlex.quote(inner)}"))
            pid += 1
    return commands


def preflight(world_size: int = 2, master_port: int = 29531) -> list:
    """Connectivity check: the ``mpirun ... hostname`` analogue
    (``fabfile.py:69-77``).  Spawns ``world_size`` processes that rendezvous
    over the native transport and allgather their identities; returns the
    list of ``"hostname:pid"`` strings (raises if any rank fails)."""
    code = (
        "import os, socket, numpy as np\n"
        "from pytorch_distributed_rnn_tpu.runtime import Communicator\n"
        "rank = int(os.environ['RANK']); world = int(os.environ['WORLD_SIZE'])\n"
        "comm = Communicator('127.0.0.1', int(os.environ['MASTER_PORT']),"
        " rank, world)\n"
        "ident = f'{socket.gethostname()}:{os.getpid()}'.encode()[:64]\n"
        "buf = np.zeros(64, np.uint8)\n"
        "buf[:len(ident)] = np.frombuffer(ident, np.uint8)\n"
        "out = comm.allgather(buf)\n"
        "if rank == 0:\n"
        "    for row in out:\n"
        "        print(bytes(row.tobytes()).rstrip(b'\\0').decode())\n"
        "comm.close()\n"
    )
    procs = []
    for rank in range(world_size):
        env = dict(os.environ)
        env.update(
            RANK=str(rank),
            WORLD_SIZE=str(world_size),
            MASTER_PORT=str(master_port),
            PDRNN_PLATFORM="cpu",
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", code],
                env=env, stdout=subprocess.PIPE, text=True,
            )
        )
    identities = []
    try:
        for rank, proc in enumerate(procs):
            out, _ = proc.communicate(timeout=60)
            if proc.returncode != 0:
                raise RuntimeError(f"preflight rank {rank} failed")
            if rank == 0:
                identities = [line for line in out.splitlines() if line]
    finally:
        # a failed/hung rank must not orphan the others: an orphaned rank 0
        # would keep master_port bound and poison every later rendezvous
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
    if len(identities) != world_size:
        raise RuntimeError(
            f"preflight saw {len(identities)} ranks, expected {world_size}"
        )
    return identities
