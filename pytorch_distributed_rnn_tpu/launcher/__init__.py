"""Launcher + benchmark harness (the fabfile layer, TPU-native).

SURVEY §2.9 parity: run-config → command synthesis, shuffled benchmark
sweeps with append-only ``results_*.json`` and resume-by-skip, network
fault-injection sweep, and a rendezvous preflight — targeting local virtual
device meshes and native-transport process worlds instead of SSH-to-Pis.
"""

from pytorch_distributed_rnn_tpu.launcher.commands import (
    RunConfig,
    command_string,
    get_command,
    make_config,
)
from pytorch_distributed_rnn_tpu.launcher.bench import (
    BENCHMARK_RUN,
    DEBUG_RUN,
    NETWORK_RULES,
    SLOTS_RUN,
    execute_run,
    expand_run_configs,
    launch_jax_world,
    load_results,
    preflight,
    run_benchmark,
    run_network_test,
)
from pytorch_distributed_rnn_tpu.launcher.supervisor import ElasticSupervisor

__all__ = [
    "ElasticSupervisor",
    "RunConfig",
    "command_string",
    "get_command",
    "make_config",
    "BENCHMARK_RUN",
    "DEBUG_RUN",
    "NETWORK_RULES",
    "SLOTS_RUN",
    "execute_run",
    "expand_run_configs",
    "launch_jax_world",
    "load_results",
    "preflight",
    "run_benchmark",
    "run_network_test",
]
