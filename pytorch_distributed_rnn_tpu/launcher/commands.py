"""Run-config → command synthesis (the fabfile command builder, TPU-native).

Capability parity with the reference's ``get_command``
(``/root/reference/fabfile.py:194-235``), which turned run-config dicts
``{trainer, hosts, slots, parameters}`` into ``python main.py ...`` /
``mpirun --host h1:s,... python main.py ... distributed`` /
``horovodrun -np N --hosts ...`` strings.

TPU-native translation of the launch topology:

- "hosts" become **devices**: positions along the data-parallel mesh axis.
  On real hardware the trainer uses every visible chip; for hardware-free
  runs (the docker-compose fake-cluster analogue, SURVEY §4.2) we export
  ``PDRNN_PLATFORM=cpu`` + ``PDRNN_NUM_CPU_DEVICES=N`` so one process hosts
  an N-device virtual mesh — the ``mpirun -np N`` analogue without MPI.
- "slots" (processes per host, ``fabfile.py:51,203-206``) multiply the
  world size exactly like ``--map-by slot`` did.
- the parameter-server strategy stays a true multi-process launch over the
  native TCP transport; its world is ``devices * slots`` workers + 1 master.
- fault injection is an env contract (``PDRNN_FAULT_DELAY_MS`` /
  ``PDRNN_FAULT_LOSS_PROB``) consumed by the native transport at
  communicator construction — the ``tc netem`` analogue.
"""

from __future__ import annotations

import shlex
import sys
from dataclasses import dataclass, field


@dataclass(frozen=True)
class RunConfig:
    """One benchmark run (the reference's run-config dict made explicit)."""

    trainer: str  # local | distributed | horovod | fsdp |
    # distributed-native | parameter-server
    devices: int = 1  # "hosts" analogue: dp world size
    slots: int = 1  # processes-per-host analogue: multiplies world
    parameters: tuple = field(default_factory=tuple)  # ((flag, value), ...)
    backend: str = "cpu"  # cpu (virtual-device sim) | native (attached chips)
    fault_type: str | None = None  # delay | loss
    fault_value: float = 0.0

    @property
    def world_size(self) -> int:
        return self.devices * self.slots

    def parameters_dict(self) -> dict:
        return dict(self.parameters)


def make_config(trainer, devices=1, slots=1, parameters=None, backend="cpu",
                fault_type=None, fault_value=0.0) -> RunConfig:
    """RunConfig from a plain parameter dict (hashable/frozen inside)."""
    items = tuple(sorted((str(k), v) for k, v in (parameters or {}).items()))
    return RunConfig(trainer, devices, slots, items, backend,
                     fault_type, fault_value)


def get_command(config: RunConfig, python: str | None = None):
    """Synthesize ``(argv, env)`` for a run config.

    ``argv`` is the subprocess argument vector; ``env`` holds only the
    *additional* environment this run needs (platform override, virtual
    device count, fault injection) — the caller merges it over ``os.environ``.
    """
    python = python or sys.executable

    flag_argv = []
    for flag, value in config.parameters:
        if value is True:
            flag_argv.append(f"--{flag}")
        elif value is False or value is None:
            continue
        else:
            flag_argv.extend([f"--{flag}", str(value)])

    env: dict[str, str] = {}
    world = config.world_size

    if (
        config.trainer in ("distributed", "horovod", "fsdp")
        and config.slots > 1
    ):
        # REAL multi-slot topology (the reference's processes-per-host,
        # fabfile.py:51,203-206): `slots` OS processes rendezvous through a
        # jax.distributed coordinator into ONE multi-controller world, each
        # contributing `devices` chips to the global mesh
        argv = [
            python, "-m", "pytorch_distributed_rnn_tpu.launcher",
            "run-world", "--transport", "jax",
            "--num-processes", str(config.slots),
            "--devices-per-process", str(config.devices),
            "--trainer", config.trainer,
            "--backend", config.backend, "--", *flag_argv,
        ]
    elif (config.trainer in ("local", "distributed", "horovod", "fsdp")
          or config.trainer.startswith("mesh")):
        # a "mesh --mesh dp=2,sp=2 ..." trainer string carries its own
        # subcommand options (the run-world --trainer convention);
        # `devices` is the TOTAL mesh size for mesh rows
        argv = [python, "-m", "pytorch_distributed_rnn_tpu.main",
                *flag_argv, *shlex.split(config.trainer)]
        if config.backend == "cpu":
            # local rows too: the whole study must run on ONE platform,
            # like the reference's local row running on the same Pi
            # hardware as its distributed rows (fabfile.py:48-66)
            env["PDRNN_PLATFORM"] = "cpu"
            env["PDRNN_NUM_CPU_DEVICES"] = str(world)
    elif config.trainer == "distributed-native":
        # process-per-rank DDP over the native TCP collectives (the
        # mpirun analogue): world = devices x slots OS processes
        argv = [
            python, "-m", "pytorch_distributed_rnn_tpu.launcher",
            "run-world", "--transport", "native",
            "--world-size", str(world),
            "--backend", config.backend, "--", *flag_argv,
        ]
    elif config.trainer == "parameter-server":
        argv = [python, "-m", "pytorch_distributed_rnn_tpu.main",
                *flag_argv, "parameter-server", "--world-size",
                str(world + 1)]
        if config.backend == "cpu":
            env["PDRNN_PLATFORM"] = "cpu"
    else:
        raise ValueError(f"unknown trainer {config.trainer!r}")

    # the netem-analogue env contract lives in resilience/faults.py so the
    # bench sweep and the chaos harness's net:* events share one mechanism
    from pytorch_distributed_rnn_tpu.resilience import fault_env

    env.update(fault_env(config.fault_type, config.fault_value))

    return argv, env


def command_string(config: RunConfig) -> str:
    """Canonical shell string for a config — the resume key.

    The reference resumed a crashed sweep by comparing already-run command
    strings in the results JSON (``fabfile.py:270-276``); this string plays
    the same role, with the env prefix included so the same CLI under a
    different topology/fault is a distinct run.
    """
    argv, env = get_command(config, python="python")
    prefix = [f"{k}={v}" for k, v in sorted(env.items())]
    return " ".join(prefix + [shlex.quote(a) for a in argv])
