"""Process supervisors: watch, respawn, rejoin.

The process half of elastic membership (``resilience/membership.py``)
and of MPMD stage fault tolerance (``parallel/mpmd.py``): a roster or a
pipeline can re-admit a process mid-run, but something has to notice
the death and relaunch it.  :class:`RespawnSupervisor` is that
something for the single-machine spawn world (the fake-cluster pattern,
SURVEY §4.2) - the local analogue of a k8s restart policy or a
preemptible-VM instance group:

- each slot keeps its stable **worker-id** across respawns: the
  relaunched process re-enters the world under the same identity (a PS
  worker star-joins and REGISTERs under its id; an MPMD stage re-dials
  its fixed link ports as the same stage-id), so watermarks, shards,
  and replay windows carry over;
- a process exiting **0** is terminal (normal completion or a SIGTERM
  drain) - never respawned;
- a nonzero/signal exit is a death: respawned with ``rejoin=True`` up
  to ``max_respawns`` times per slot (exponential-free fixed delay -
  the join protocols are cheap; the model rebuild dominates);
- when a slot's respawn budget is exhausted, the supervisor keeps the
  run alive only while at least ``min_workers`` slots remain live or
  completed - below the floor it tears the world down instead of
  letting the survivors idle out their join/link timeouts.

The supervisor is deliberately dumb about *state*: everything a rejoin
needs to continue correctly lives outside it (the PS master's
STATE_SYNC reply; an MPMD stage's own crash-safe checkpoint plus its
neighbors' replay buffers), which is what makes the kill -> respawn ->
rejoin path drillable with the chaos actions in
``resilience/faults.py``.  The two deployment flavors -
:class:`ElasticSupervisor` (PS workers around an unsupervised master)
and :class:`StageSupervisor` (every pipeline stage supervised, floor =
the whole pipeline) - share this one implementation; neither forks the
respawn/min-workers core.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field

log = logging.getLogger(__name__)


@dataclass
class _Slot:
    """One supervised process slot (worker-id == launch rank)."""

    worker_id: int
    rank: int
    process: object
    respawns: int = 0
    completed: bool = False
    failed: bool = False
    history: list = field(default_factory=list)  # exit codes observed


class RespawnSupervisor:
    """The respawn/min-workers core: watches spawned processes, reaps
    exits, respawns deaths into the same slot."""

    def __init__(self, spawn_worker, *, min_workers: int = 1,
                 max_respawns: int = 3, respawn_delay_s: float = 0.1,
                 poll_s: float = 0.05, on_event=None):
        """``spawn_worker(rank, worker_id, rejoin) -> process`` launches
        one process (``process`` needs ``is_alive()``, ``exitcode`` and
        ``terminate()``/``join()``).

        ``on_event(kind, **fields)`` is an optional observer hook fired
        on supervision transitions (``worker_respawn`` / ``worker_lost``
        / ``pool_collapse``): the PS runner wires it to the live plane's
        alert pusher (``obs/live.EventPusher``), the MPMD runner to its
        supervisor sidecar - the supervisor itself stays
        transport-agnostic.  Hook failures are swallowed."""
        self._spawn_worker = spawn_worker
        self.min_workers = int(min_workers)
        self.max_respawns = int(max_respawns)
        self.respawn_delay_s = float(respawn_delay_s)
        self.poll_s = float(poll_s)
        self.slots: dict[int, _Slot] = {}
        self.total_respawns = 0
        self._on_event = on_event

    def _emit(self, kind: str, **fields) -> None:
        if self._on_event is None:
            return
        try:
            self._on_event(kind, **fields)
        except Exception:  # observability must never kill supervision
            log.exception(f"supervisor: on_event({kind}) hook failed")

    def launch(self, ranks) -> None:
        """Spawn the initial process set (worker-id == launch rank)."""
        for rank in ranks:
            proc = self._spawn_worker(rank, rank, False)
            self.slots[rank] = _Slot(worker_id=rank, rank=rank,
                                     process=proc)

    def adopt(self, rank: int, worker_id: int | None = None):
        """Elastic JOIN: spawn a brand-new slot mid-run (a worker that
        did not exist at launch) and supervise it like the rest - the
        process half of a roster ``join``.  The new slot gets the full
        respawn budget; ``min_workers`` is unchanged (joining must
        never make an already-healthy pool collapsible)."""
        worker_id = rank if worker_id is None else int(worker_id)
        if worker_id in self.slots:
            raise ValueError(
                f"worker-id {worker_id} already supervised; a respawn "
                f"reuses its slot, only a NEW identity can be adopted"
            )
        proc = self._spawn_worker(rank, worker_id, False)
        self.slots[worker_id] = _Slot(worker_id=worker_id, rank=rank,
                                      process=proc)
        self._emit("worker_join", worker_id=worker_id, rank=rank)
        return proc

    # -- monitoring ----------------------------------------------------------

    def _live_or_completed(self) -> int:
        return sum(
            1 for s in self.slots.values()
            if s.completed or (not s.failed and s.process.is_alive())
        )

    def poll(self) -> bool:
        """One supervision pass: reap exits, respawn deaths.  Returns
        False when the pool has fallen below ``min_workers`` with no
        respawn budget left (the caller should tear down)."""
        for slot in self.slots.values():
            if slot.completed or slot.failed or slot.process.is_alive():
                continue
            code = slot.process.exitcode
            slot.history.append(code)
            if code == 0:
                # normal completion OR a SIGTERM drain: both are
                # voluntary exits the world already accounted for
                slot.completed = True
                log.info(
                    f"supervisor: worker-id {slot.worker_id} exited 0 "
                    f"(terminal)"
                )
                continue
            if slot.respawns >= self.max_respawns:
                slot.failed = True
                log.error(
                    f"supervisor: worker-id {slot.worker_id} died "
                    f"(exit {code}) with no respawn budget left "
                    f"({self.max_respawns} used)"
                )
                self._emit("worker_lost", worker_id=slot.worker_id,
                           rank=slot.rank, exit_code=code,
                           respawns_used=slot.respawns)
                continue
            slot.respawns += 1
            self.total_respawns += 1
            log.warning(
                f"supervisor: worker-id {slot.worker_id} died "
                f"(exit {code}); respawning into rank {slot.rank} "
                f"(respawn {slot.respawns}/{self.max_respawns})"
            )
            self._emit("worker_respawn", worker_id=slot.worker_id,
                       rank=slot.rank, exit_code=code,
                       respawn=slot.respawns,
                       max_respawns=self.max_respawns)
            time.sleep(self.respawn_delay_s)
            slot.process = self._spawn_worker(
                slot.rank, slot.worker_id, True
            )
        healthy = self._live_or_completed() >= self.min_workers
        if not healthy:
            self._emit("pool_collapse", min_workers=self.min_workers,
                       live_or_completed=self._live_or_completed())
        return healthy

    def supervise(self, until_exit) -> bool:
        """Supervision loop anchored on an UNSUPERVISED process: poll
        until ``until_exit()`` returns an exit code (the PS master
        finishing) or the pool collapses below the floor.  Returns True
        while healthy, False on collapse."""
        while until_exit() is None:
            if not self.poll():
                return False
            time.sleep(self.poll_s)
        return True

    def supervise_all(self) -> bool:
        """Supervision loop with NO external anchor: poll until every
        slot is terminal (completed or failed) or the pool collapses.
        Returns True iff every slot completed - the MPMD shape, where
        all processes are supervised peers."""
        while True:
            if not self.poll():
                return False
            if all(s.completed or s.failed for s in self.slots.values()):
                return all(s.completed for s in self.slots.values())
            time.sleep(self.poll_s)

    def shutdown(self, timeout_s: float = 10.0) -> None:
        """Terminate whatever is still running, reap everything, and
        settle the final per-slot verdicts - without respawning (the
        run is over)."""
        for slot in self.slots.values():
            if slot.process.is_alive():
                slot.process.terminate()
        deadline = time.monotonic() + timeout_s
        for slot in self.slots.values():
            slot.process.join(timeout=max(0.1, deadline - time.monotonic()))
            if not slot.completed and not slot.failed \
                    and not slot.process.is_alive():
                slot.history.append(slot.process.exitcode)
                slot.completed = slot.process.exitcode == 0
                slot.failed = not slot.completed

    def verdict(self) -> dict:
        """Supervision outcome for logs/telemetry."""
        return {
            "workers": len(self.slots),
            "completed": sum(1 for s in self.slots.values() if s.completed),
            "failed": sum(1 for s in self.slots.values() if s.failed),
            "respawns": self.total_respawns,
        }


def supervision_alert_hook(recorder=None, push=None):
    """The ONE ``on_event`` wiring for every supervisor flavor, so PS,
    stage and actor supervisors emit ``worker_respawn`` /
    ``worker_lost`` / ``pool_collapse`` (and elastic ``worker_join``)
    alerts uniformly instead of each runner hand-rolling the plumbing:

    - ``recorder`` (a :class:`~..obs.recorder.MetricsRecorder`): each
      event lands in the supervisor's sidecar and is flushed
      immediately - supervision events are rare and must survive a
      teardown;
    - ``push`` (the live plane's ``EventPusher.push``): the same event
      goes to the fleet aggregator as an alert.

    Returns ``None`` when there is nothing to wire (the supervisor then
    skips hook dispatch entirely)."""
    if recorder is None and push is None:
        return None

    def on_event(kind, **fields):
        if recorder is not None and recorder.enabled:
            recorder.record(kind, **fields)
            recorder.flush()
        if push is not None:
            push(kind, **fields)

    return on_event


class ElasticSupervisor(RespawnSupervisor):
    """PS flavor: supervises the WORKER processes around an
    unsupervised master (the master owns the state; its exit anchors
    :meth:`supervise`).  A respawned worker star-joins the transport on
    the same rank and REGISTERs under the same worker-id, so the
    master's push-seq watermark and data shard carry over."""


class StageSupervisor(RespawnSupervisor):
    """MPMD pipeline flavor: EVERY stage process is supervised and the
    pool floor defaults to the whole pipeline - a pipeline with a hole
    in it computes nothing, so one permanently-lost stage is a
    collapse, not a degraded world.  A respawned stage restores from
    its own per-stage checkpoint and re-dials its neighbors' fixed
    link ports; use :meth:`supervise_all` (there is no master to
    anchor on)."""

    def __init__(self, spawn_worker, *, min_workers: int | None = None,
                 max_respawns: int = 3, respawn_delay_s: float = 0.1,
                 poll_s: float = 0.05, on_event=None):
        self._floor_is_all = min_workers is None
        super().__init__(
            spawn_worker,
            min_workers=0 if min_workers is None else min_workers,
            max_respawns=max_respawns, respawn_delay_s=respawn_delay_s,
            poll_s=poll_s, on_event=on_event,
        )

    def launch(self, ranks) -> None:
        super().launch(ranks)
        if self._floor_is_all:
            self.min_workers = len(self.slots)


class ReplicaSupervisor(RespawnSupervisor):
    """Serving-fleet flavor (``serving/fleet/``): the ``pdrnn-serve``
    engine REPLICAS behind the router are supervised; the router itself
    is the unsupervised anchor (it owns no model state and dying with
    it is an outage, not a degradation).  A respawned replica rebinds
    the SAME host:port its slot was launched on, so the router's static
    pool entry stays valid and the circuit breaker re-admits it through
    half-open probing once its pings succeed - no re-registration
    protocol needed.  A SIGTERM drain (stop dispatching, finish
    in-flight, DEREGISTER via the drained digest) exits 0 and is
    terminal; the floor is the minimum replica count that keeps the
    fleet serving - losing replicas degrades capacity, never
    correctness (requests reroute)."""


class ActorSupervisor(RespawnSupervisor):
    """Streaming actor/learner flavor (``streaming/runner.py``): the
    actor FLEET is supervised around a separately-watched learner.  A
    respawned actor star-joins the learner's listener on its old rank
    and REGISTERs under its stable worker-id, so its experience-push
    watermark carries over (a retried or post-respawn push dedupes
    instead of training on the same batch twice); :meth:`adopt` covers
    the elastic-join drill (a brand-new actor entering mid-run).  The
    floor is the minimum actor count that keeps the learner fed -
    losing actors degrades throughput, never correctness."""
