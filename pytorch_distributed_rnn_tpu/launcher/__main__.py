"""Launcher CLI — the ``fab <task>`` analogue.

Tasks (mirroring ``/root/reference/fabfile.py`` Fabric tasks):

  preflight         rendezvous check (``prepare_connections`` analogue)
  prepare-data      seed a dataset directory (``copy_src`` analogue: gets the
                    workload onto the machine; synthesizes HAR-shaped data
                    when the real UCI HAR download is absent)
  run-debug         single seeded 1-epoch run (``run_debug``)
  run-all           full shuffled benchmark sweep (``run_all``)
  run-network-test  delay/loss perturbation sweep (``run_network_test``)
  show-commands     print synthesized commands without running

Example:
  python -m pytorch_distributed_rnn_tpu.launcher run-all \
      --results results.json --dataset-path data
"""

from __future__ import annotations

import argparse
import sys

from pytorch_distributed_rnn_tpu.launcher import bench
from pytorch_distributed_rnn_tpu.launcher.commands import command_string


def _add_common(parser):
    parser.add_argument("--dataset-path", default="data")
    parser.add_argument("--results", default="results.json")
    parser.add_argument("--timeout", type=float, default=None)
    parser.add_argument(
        "--backend", choices=["cpu", "native"], default="cpu",
        help="cpu: virtual-device fake cluster; native: attached accelerator",
    )


def _dataset_parameters(args):
    return {"dataset-path": args.dataset_path}


def main(argv=None):
    parser = argparse.ArgumentParser(prog="pytorch_distributed_rnn_tpu.launcher")
    sub = parser.add_subparsers(dest="task", required=True)

    p = sub.add_parser("preflight")
    p.add_argument("--world-size", type=int, default=2)

    p = sub.add_parser("prepare-data")
    p.add_argument("--dataset-path", default="data")
    # real UCI HAR split sizes; the processor's x96 truncation then yields
    # the reference's 6912 training sequences (processor.py:63-66)
    p.add_argument("--num-train", type=int, default=7352)
    p.add_argument("--num-test", type=int, default=2947)

    for task in ("run-debug", "run-all", "show-commands"):
        p = sub.add_parser(task)
        _add_common(p)

    p = sub.add_parser("run-network-test")
    _add_common(p)
    p.add_argument("--devices", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=1440)

    args = parser.parse_args(argv)

    if args.task == "preflight":
        for ident in bench.preflight(args.world_size):
            print(ident)
        print("preflight ok")
        return 0

    if args.task == "prepare-data":
        from pytorch_distributed_rnn_tpu.data import write_synthetic_har_dataset

        write_synthetic_har_dataset(
            args.dataset_path, num_train=args.num_train, num_test=args.num_test
        )
        print(f"dataset ready under {args.dataset_path}")
        return 0

    if args.task == "show-commands":
        for config in bench.expand_run_configs(
            bench.BENCHMARK_RUN, _dataset_parameters(args), args.backend
        ):
            print(command_string(config))
        return 0

    if args.task == "run-debug":
        run = bench.DEBUG_RUN
    elif args.task == "run-all":
        run = bench.BENCHMARK_RUN
    elif args.task == "run-network-test":
        executed = bench.run_network_test(
            args.results,
            devices=args.devices,
            batch_size=args.batch_size,
            extra_parameters=_dataset_parameters(args),
            backend=args.backend,
            timeout=args.timeout,
        )
        return _report(executed, args.results)

    configs = bench.expand_run_configs(
        run, _dataset_parameters(args), args.backend
    )
    executed = bench.run_benchmark(
        configs, args.results, timeout=args.timeout
    )
    return _report(executed, args.results)


def _report(executed, results_path) -> int:
    failed = [e for e in executed if e.get("returncode") != 0]
    print(f"executed {len(executed)} run(s) -> {results_path}"
          + (f" ({len(failed)} FAILED)" if failed else ""))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
