"""Launcher CLI — the ``fab <task>`` analogue.

Tasks (mirroring ``/root/reference/fabfile.py`` Fabric tasks):

  preflight         rendezvous check (``prepare_connections`` analogue)
  prepare-data      seed a dataset directory (``copy_src`` analogue: gets the
                    workload onto the machine; synthesizes HAR-shaped data
                    when the real UCI HAR download is absent)
  run-debug         single seeded 1-epoch run (``run_debug``)
  run-all           full shuffled benchmark sweep (``run_all``)
  run-chip          real-chip local rows at the three sweep batch sizes
                    (the committed results_baseline_*.json re-run analogue;
                    defaults to --backend native)
  run-slots         real multi-slot sweep (processes-per-host dimension)
  run-hosts         multi-host jax.distributed world over SSH
                    (--hosts h1:2,h2:2; the mpirun --host analogue;
                    --dry-run prints the synthesized commands)
  run-network-test  delay/loss perturbation sweep (``run_network_test``)
  run-world         stand up one N-process world: ``--transport native`` =
                    process-per-rank DDP over the TCP collectives (the
                    mpirun analogue); ``--transport jax`` = N processes
                    rendezvous through a jax.distributed coordinator into
                    one global-mesh SPMD world.  CLI flags after ``--``.
  show-commands     print synthesized commands without running

Example:
  python -m pytorch_distributed_rnn_tpu.launcher run-all \
      --results results.json --dataset-path data
"""

from __future__ import annotations

import argparse
import sys

from pytorch_distributed_rnn_tpu.launcher import bench
from pytorch_distributed_rnn_tpu.launcher.commands import command_string


def _trainer_spec(value: str) -> str:
    """A multi-controller trainer token: a bare strategy name, or a
    strategy plus its own sub-flags (e.g. ``mesh --mesh dp=1,sp=4``)."""
    import shlex

    head = shlex.split(value)[0] if value.strip() else ""
    allowed = ("distributed", "horovod", "fsdp", "mesh")
    if head not in allowed:
        raise argparse.ArgumentTypeError(
            f"trainer must start with one of {allowed}, got {value!r}"
        )
    return value


def _add_common(parser):
    parser.add_argument("--dataset-path", default="data")
    parser.add_argument("--results", default="results.json")
    parser.add_argument("--timeout", type=float, default=None)
    parser.add_argument(
        "--backend", choices=["cpu", "native"], default="cpu",
        help="cpu: virtual-device fake cluster; native: attached accelerator",
    )
    parser.add_argument(
        "--metrics-dir", default=None, metavar="DIR",
        help="per-run structured telemetry: each run writes a JSONL "
        "sidecar under DIR (--metrics plumbed into the run's CLI) and "
        "the results JSON archives its path as metrics_path - the "
        "structured channel evaluation/analysis.py prefers over the "
        "stderr perf-line regex",
    )


def _dataset_parameters(args):
    return {"dataset-path": args.dataset_path}


def main(argv=None):
    from pytorch_distributed_rnn_tpu.utils import leakcheck

    # resolve PDRNN_LEAKCHECK before the first socket/thread/file
    leakcheck.maybe_install()
    parser = argparse.ArgumentParser(prog="pytorch_distributed_rnn_tpu.launcher")
    sub = parser.add_subparsers(dest="task", required=True)

    p = sub.add_parser("preflight")
    p.add_argument("--world-size", type=int, default=2)

    p = sub.add_parser("prepare-data")
    p.add_argument("--dataset-path", default="data")
    # real UCI HAR split sizes; the processor's x96 truncation then yields
    # the reference's 6912 training sequences (processor.py:63-66)
    p.add_argument("--num-train", type=int, default=7352)
    p.add_argument("--num-test", type=int, default=2947)

    for task in ("run-debug", "run-all", "run-matrix", "show-commands"):
        p = sub.add_parser(task)
        _add_common(p)

    p = sub.add_parser("run-chip")
    _add_common(p)
    p.set_defaults(backend="native")  # real attached accelerator

    p = sub.add_parser("run-network-test")
    _add_common(p)
    p.add_argument("--devices", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=1440)
    p.add_argument(
        "--native-ranks", type=int, default=4,
        help="world size for the perturbed distributed-native rows (the "
        "ring allreduce that crosses the fault-injected TCP links)",
    )

    p = sub.add_parser("run-slots")
    _add_common(p)

    p = sub.add_parser("run-hosts")
    p.add_argument("--hosts", required=True,
                   help="host:slots list, e.g. h1:2,h2:2 (the mpirun "
                   "--host analogue); host 0 is the coordinator")
    p.add_argument("--trainer", default="distributed",
                   choices=["distributed", "horovod", "fsdp"])
    p.add_argument("--coordinator-port", type=int, default=29601)
    p.add_argument("--python", default="python3")
    p.add_argument("--repo-dir", default="~/pytorch_distributed_rnn_tpu")
    p.add_argument("--dry-run", action="store_true",
                   help="print the per-host SSH commands without running")
    p.add_argument("--timeout", type=float, default=1800)
    p.add_argument("cli", nargs=argparse.REMAINDER,
                   help="main.py flags after --")

    p = sub.add_parser("collective-report")
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--results", default="results_collectives.json")

    p = sub.add_parser("run-world")
    p.add_argument("--transport", choices=["native", "jax"], default="native")
    p.add_argument("--world-size", type=int, default=2,
                   help="native transport: process-per-rank world size")
    p.add_argument("--num-processes", type=int, default=2,
                   help="jax transport: controller process count")
    p.add_argument("--devices-per-process", type=int, default=1)
    p.add_argument("--trainer", default="distributed", type=_trainer_spec,
                   help="distributed | horovod | fsdp | a mesh spec like "
                   "'mesh --mesh dp=1,sp=4' (sub-flags ride along; sp "
                   "rings then span controllers - sequence parallelism "
                   "over DCN)")
    p.add_argument("--master-port", type=int, default=29533)
    p.add_argument("--coordinator-port", type=int, default=29601)
    p.add_argument("--timeout", type=float, default=600)
    p.add_argument(
        "--backend", choices=["cpu", "native"], default="cpu",
        help="cpu: virtual-device ranks; native: ambient accelerator",
    )
    p.add_argument("cli", nargs=argparse.REMAINDER,
                   help="main.py flags after --")

    args = parser.parse_args(argv)

    if args.task == "run-world":
        return _run_world(args)
    if args.task == "run-hosts":
        return _run_hosts(args)

    if args.task == "collective-report":
        import json

        # probe-first like bench.py (commit 8e3b014): a hung ambient
        # plugin must fall back to a virtual CPU mesh, and a plain host
        # needs the device count provisioned before first backend use
        from pytorch_distributed_rnn_tpu.utils import ensure_usable_backend

        ensure_usable_backend(min_devices=args.devices)

        from pytorch_distributed_rnn_tpu.evaluation.collectives import (
            report_programs,
        )

        rows = report_programs(args.devices)
        with open(args.results, "w") as f:
            json.dump(rows, f, indent=1)
        for row in rows:
            print(row["program"])
            for view in ("traced", "compiled"):
                for op, s in sorted(row[view].items()):
                    print(f"  {view:8s} {op:22s} x{s['count']:<4d}"
                          f" {s['bytes']:>12,d} B")
        print(f"-> {args.results}")
        return 0

    if args.task == "preflight":
        for ident in bench.preflight(args.world_size):
            print(ident)
        print("preflight ok")
        return 0

    if args.task == "prepare-data":
        from pytorch_distributed_rnn_tpu.data import write_synthetic_har_dataset

        write_synthetic_har_dataset(
            args.dataset_path, num_train=args.num_train, num_test=args.num_test
        )
        print(f"dataset ready under {args.dataset_path}")
        return 0

    if args.task == "show-commands":
        for config in bench.expand_run_configs(
            bench.BENCHMARK_RUN, _dataset_parameters(args), args.backend
        ):
            print(command_string(config))
        return 0

    if args.task == "run-debug":
        runs = [bench.DEBUG_RUN]
    elif args.task == "run-chip":
        # motion rows + the amortized 20-epoch rows (per-epoch at default
        # dropout, per-epoch at dropout 0, fused-whole-run at dropout 0 -
        # the last two isolate dispatch granularity) + the char-LM
        # companion row in one resumable sweep
        runs = [bench.CHIP_RUN, bench.CHIP_AMORTIZED_RUN,
                bench.CHIP_AMORTIZED_NODROP_RUN, bench.CHIP_FUSED_RUN,
                bench.CHIP_LM_RUN]
    elif args.task == "run-all":
        runs = [bench.BENCHMARK_RUN]
    elif args.task == "run-slots":
        runs = [bench.SLOTS_RUN]
    elif args.task == "run-network-test":
        executed = bench.run_network_test(
            args.results,
            devices=args.devices,
            batch_size=args.batch_size,
            extra_parameters=_dataset_parameters(args),
            backend=args.backend,
            timeout=args.timeout,
            native_ranks=args.native_ranks,
            metrics_dir=args.metrics_dir,
        )
        return _report(executed, args.results)

    if args.task == "run-matrix":
        # one run per strategy x family README-matrix cell
        configs = bench.matrix_configs(
            _dataset_parameters(args), args.backend
        )
    else:
        configs = [
            config
            for run in runs
            for config in bench.expand_run_configs(
                run, _dataset_parameters(args), args.backend
            )
        ]
    executed = bench.run_benchmark(
        configs, args.results, timeout=args.timeout,
        metrics_dir=args.metrics_dir,
    )
    return _report(executed, args.results)


def _run_world(args) -> int:
    """One N-process world; every rank's stderr is forwarded to ours so the
    sweep's stderr capture (and the notebooks' rank-0 perf-line regex)
    keeps working through the extra process layer."""
    cli = [a for a in args.cli if a != "--"]
    if args.transport == "native":
        from pytorch_distributed_rnn_tpu.training.native_ddp import (
            launch_world,
        )

        results = launch_world(
            args.world_size, cli, master_port=args.master_port,
            timeout=args.timeout, backend=args.backend,
        )
    else:
        results = bench.launch_jax_world(
            args.num_processes, cli,
            devices_per_process=args.devices_per_process,
            trainer=args.trainer,
            coordinator_port=args.coordinator_port,
            timeout=args.timeout, backend=args.backend,
        )
    return _emit_world_results(results, "world")


def _emit_world_results(results, label: str) -> int:
    """Forward each rank's captured output to ours (keeps the notebooks'
    rank-0 perf-line regex working through the launcher layer)."""
    for _, out, err in results:
        if out:
            sys.stdout.write(out)
        if err:
            sys.stderr.write(err)
    print(f"{label} of {len(results)} rank(s) completed")
    return 0


def _run_hosts(args) -> int:
    """Multi-host world over SSH (the ``fab run_all`` launch analogue):
    one SSH invocation per process, all rendezvousing through the
    coordinator env."""
    import os
    import shlex

    cli = [a for a in args.cli if a != "--"]
    commands = bench.host_world_commands(
        bench.parse_hosts(args.hosts), cli, trainer=args.trainer,
        coordinator_port=args.coordinator_port, python=args.python,
        repo_dir=args.repo_dir,
    )
    if args.dry_run:
        for _, cmd in commands:
            print(cmd)
        return 0

    from pytorch_distributed_rnn_tpu.utils.worlds import spawn_world

    rank_cmds = [
        (shlex.split(cmd), dict(os.environ)) for _, cmd in commands
    ]
    results = spawn_world(rank_cmds, timeout=args.timeout)
    return _emit_world_results(results, "host world")


def _report(executed, results_path) -> int:
    failed = [e for e in executed if e.get("returncode") != 0]
    print(f"executed {len(executed)} run(s) -> {results_path}"
          + (f" ({len(failed)} FAILED)" if failed else ""))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
