"""Metrics-sidecar loading, per-run summaries, diffs, stragglers.

Shared by the ``pdrnn-metrics`` CLI and the structured-first loader in
``evaluation/analysis.py`` so the two can never disagree on what a
sidecar means.  Loading is STRICT (:class:`MalformedMetricsError` on
any unparseable line, missing ``kind``, or an incompatible schema
declaration): the CI smoke step exists to catch schema drift, and a
loader that shrugs off bad lines would wave it through.
"""

from __future__ import annotations

import json
import math
import statistics
from pathlib import Path

from pytorch_distributed_rnn_tpu.obs.recorder import SCHEMA_VERSION


class MalformedMetricsError(ValueError):
    """The sidecar is unreadable, unparseable, or schema-incompatible."""


def rank_files(path) -> list[Path]:
    """All per-rank sidecars belonging to one run: the rank-0 file plus
    any ``<stem>-r<k><suffix>`` siblings (``recorder.rank_suffixed``)."""
    path = Path(path)
    files = [path] if path.exists() else []
    pattern = f"{path.stem}-r*{path.suffix}"
    if path.parent.is_dir():
        siblings = [
            p for p in path.parent.glob(pattern)
            if p.stem[len(path.stem):].lstrip("-r").isdigit()
        ]
        files.extend(sorted(siblings))
    return files


def load_events(path) -> list[dict]:
    """One run's events off one JSONL sidecar, validated."""
    path = Path(path)
    try:
        text = path.read_text()
    except OSError as exc:
        raise MalformedMetricsError(f"{path}: unreadable ({exc})") from exc
    events = []
    lines = text.splitlines()
    # a file whose last line is cut off mid-write (no trailing newline)
    # is a process killed mid-append - SIGKILL chaos faults, launcher
    # timeouts - and losing ONE torn event must not forfeit the rest:
    # partial telemetry of crashed runs is what the sidecar exists for.
    # Anything else unparseable is schema drift and stays a hard error.
    truncated_tail = bool(lines) and not text.endswith("\n")
    for lineno, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            event = json.loads(line)
        except json.JSONDecodeError as exc:
            if truncated_tail and lineno == len(lines):
                break
            raise MalformedMetricsError(
                f"{path}:{lineno}: unparseable JSONL ({exc})"
            ) from exc
        if not isinstance(event, dict) or "kind" not in event:
            raise MalformedMetricsError(
                f"{path}:{lineno}: event without a 'kind' field"
            )
        events.append(event)
    if not events:
        raise MalformedMetricsError(f"{path}: empty metrics file")
    head = events[0]
    if head.get("kind") != "meta":
        raise MalformedMetricsError(
            f"{path}: first event must be 'meta', got {head.get('kind')!r}"
        )
    schema = head.get("schema")
    if not isinstance(schema, int) or schema > SCHEMA_VERSION:
        raise MalformedMetricsError(
            f"{path}: schema {schema!r} is newer than this reader's "
            f"{SCHEMA_VERSION}"
        )
    return events


def percentile(sorted_values: list[float], q: float) -> float:
    """Nearest-rank percentile over an ascending-sorted list (NaN when
    empty).  THE percentile convention - shared by the summaries here,
    the serving engine's request-latency stats and the load generator's
    SLO report, so the three can never disagree on what a p95 means."""
    if not sorted_values:
        return float("nan")
    idx = min(len(sorted_values) - 1, int(math.ceil(q * len(sorted_values))) - 1)
    return sorted_values[max(0, idx)]


_percentile = percentile


# serving-run metrics the engine folds into its run_summary event
# (serving/engine.py): request-latency/TTFT percentiles, queue-depth
# percentiles, throughput and shedding.  Passed through verbatim when
# present so `pdrnn-metrics summarize` reads inference sidecars with
# the training analysis unchanged; absent (None) on training runs.
SERVING_SUMMARY_KEYS = (
    "requests", "requests_shed", "requests_failed", "tokens_out",
    "tokens_per_s", "latency_s_p50", "latency_s_p95", "ttft_s_p50",
    "ttft_s_p95", "queue_s_p50", "queue_s_p95", "queue_depth_p50",
    "queue_depth_p95", "queue_depth_max",
)


# streaming actor/learner metrics the learner folds into its
# run_summary event (streaming/learner.py): experience ingest rate,
# applied-update rate, and the bounded-staleness / exactly-once /
# backpressure rejection counters.  Same verbatim-passthrough contract
# as the serving keys: present on a streaming learner's sidecar, absent
# (None, not 0) on every other run.
STREAMING_SUMMARY_KEYS = (
    "experience_batches", "experience_per_s", "updates_per_s",
    "stale_rejected", "queue_sheds", "duplicates", "poisoned",
    "staleness_p50", "staleness_p95", "final_version", "rejoins",
)


# serving-fleet router metrics the router folds into its run_summary
# event (serving/fleet/router.py): dispatch/retry/hedge accounting and
# the breaker transition counters.  Same verbatim-passthrough contract
# as the serving keys: present on a pdrnn-router sidecar, absent (None,
# not 0) on every other run.
ROUTER_SUMMARY_KEYS = (
    "routed", "rerouted", "retries", "hedges", "hedge_wins",
    "router_shed", "router_errors", "stream_aborts",
    "replica_ejections", "replica_readmissions", "drain_rejected",
)


def _phase_bytes(collectives, op_kinds):
    """Per-step bytes of the named traced collective op kinds, or None
    when the run has no per-op breakdown (host-loop steps record the
    collectives event with ``ops=None``)."""
    if not collectives or not collectives.get("ops"):
        return None
    ops = collectives["ops"]
    return int(sum(ops[k]["bytes"] for k in op_kinds if k in ops))


def summarize_events(events: list[dict], path=None) -> dict:
    """One rank's summary: the numbers ``pdrnn-metrics summarize`` prints
    and ``evaluation/analysis.py`` folds into the measurement dataframe."""
    meta = events[0]
    steps = [e for e in events if e["kind"] == "step"]
    epochs = [e for e in events if e["kind"] == "epoch"]
    run = next(
        (e for e in reversed(events) if e["kind"] == "run_summary"), None
    )
    collectives = next(
        (e for e in events if e["kind"] == "collectives"), None
    )

    # warm-up exclusion for TIMING stats: the run's first step carries
    # the compile (orders of magnitude above steady state on a jit
    # framework) and would dominate every mean/percentile
    if len(steps) > 1:
        first = min(int(e.get("step", 0)) for e in steps)
        timed = [e for e in steps if int(e.get("step", 0)) != first]
    else:
        timed = steps
    dispatch = [float(e["dispatch_s"]) for e in timed if "dispatch_s" in e]
    fenced = sorted(
        float(e["fenced_s"]) for e in timed if e.get("fenced_s") is not None
    )
    data_wait = [float(e.get("data_wait_s", 0.0)) for e in steps]
    # per-step host-collective telemetry (native ring): comm_wait_s is
    # the wall the host sat blocked in collectives, overlap_frac the
    # share of wire time hidden behind compute.  None-not-0: strategies
    # without host collectives never carry the fields.  Same warm-up
    # exclusion as the timing stats - the first step's waits include
    # compile-skewed scheduling.
    comm_wait = [
        float(e["comm_wait_s"]) for e in timed
        if e.get("comm_wait_s") is not None
    ]
    overlap = [
        float(e["overlap_frac"]) for e in timed
        if e.get("overlap_frac") is not None
    ]
    losses = [float(e["loss"]) for e in steps if e.get("loss") is not None]
    if not losses:
        losses = [float(e["loss"]) for e in epochs if e.get("loss") is not None]

    epoch_wall = sum(
        float(e["wall_s"]) for e in epochs if e.get("wall_s") is not None
    )
    # data-wait fraction: input-pipeline stall share of the epochs' wall
    # time; falls back to dispatch time when no epoch event carries wall
    denom = epoch_wall or sum(dispatch) or float("nan")
    wait_total = sum(data_wait)

    # step wall time: the fenced samples are honest wall clock (dispatch
    # alone understates an async step by the device time)
    step_basis = fenced or sorted(dispatch)

    summary = {
        "path": str(path) if path is not None else None,
        "rank": int(meta.get("rank", 0)),
        "schema": meta.get("schema"),
        "steps": len(steps),
        "epochs": len(epochs),
        "fenced_samples": len(fenced),
        "loss_first": losses[0] if losses else None,
        "loss_last": losses[-1] if losses else None,
        "step_s_mean": (sum(step_basis) / len(step_basis))
        if step_basis else None,
        "step_s_p50": _percentile(step_basis, 0.50) if step_basis else None,
        "step_s_p95": _percentile(step_basis, 0.95) if step_basis else None,
        "data_wait_s": wait_total,
        "data_wait_frac": (wait_total / denom)
        if denom == denom and denom > 0 else None,
        "comm_wait_s": sum(comm_wait) if comm_wait else None,
        "comm_wait_s_mean": (sum(comm_wait) / len(comm_wait))
        if comm_wait else None,
        "overlap_frac": (sum(overlap) / len(overlap)) if overlap else None,
        "collective_bytes_per_step": (
            collectives.get("bytes_per_step") if collectives else None
        ),
        "collective_ops": collectives.get("ops") if collectives else None,
        # per-phase split of the traced collective traffic: gradient
        # phase = all-reduce; update phase = reduce-scatter + all-gather
        # (the sharded weight update's signature, 2004.13336) - so the
        # ~2x update-bytes drop is a diffable, gateable number
        "collective_grad_bytes_per_step": _phase_bytes(
            collectives, ("all-reduce",)
        ),
        "collective_update_bytes_per_step": _phase_bytes(
            collectives, ("reduce-scatter", "all-gather")
        ),
        # .get: a run_summary is not obliged to carry every field (the
        # serving engine has no memory_profiler wrap, for one); absent
        # optional metrics are None, never a loader error
        "duration_s": (
            float(run["duration_s"])
            if run and run.get("duration_s") is not None else None
        ),
        "memory_mb": (
            float(run["memory_mb"])
            if run and run.get("memory_mb") is not None else None
        ),
        "device_peak_mb": (
            max(run["device_peaks_mb"].values())
            if run and run.get("device_peaks_mb") else None
        ),
        "nan_skipped": (run or {}).get("nan_skipped", 0),
        "faults_fired": (run or {}).get("faults_fired", {}),
        "checkpoint_saves": sum(
            1 for e in events if e["kind"] == "checkpoint_save"
        ),
        # post-warm-up retraces (training/base.py emits a `compile`
        # event when a step function's trace-cache size bumps after its
        # first compile).  None-not-0: pre-ledger sidecars never carry
        # the event and must not read as "zero recompiles, verified".
        "recompiles": sum(
            1 for e in events if e["kind"] == "compile"
        ) or None,
        "ps_exchanges": sum(
            1 for e in events if e["kind"] == "ps_exchange"
        ),
        "ps_retries": sum(
            int(e.get("retries", 0)) for e in events
            if e["kind"] == "ps_exchange"
        ),
        "ps_degraded_rounds": sum(
            1 for e in events
            # schema 1 recorded degraded rounds as ps_round point
            # events; schema 2 emits a ps_round SPAN per round with a
            # degraded attribute
            if e.get("degraded") and (
                e["kind"] == "ps_round"
                or (e["kind"] == "span" and e.get("name") == "ps_round")
            )
        ),
    }
    # watchdog alerts (obs/watchdog.py): count + by-kind breakdown;
    # None (not 0) on alert-free runs so the text summary stays quiet
    alerts = [e for e in events if e["kind"] == "alert"]
    if alerts:
        by_kind: dict[str, int] = {}
        for e in alerts:
            kind = str(e.get("alert", "?"))
            by_kind[kind] = by_kind.get(kind, 0) + 1
        summary["alerts"] = len(alerts)
        summary["alerts_by_kind"] = by_kind
    else:
        summary["alerts"] = None
        summary["alerts_by_kind"] = None
    # elastic membership (resilience/membership.py): transition counts
    # off this rank's stream - the master's sidecar carries the whole
    # roster story, workers their own join/drain.  None (not 0) on
    # non-elastic runs so the text summary stays noise-free.
    member_counts = {
        "member_joins": sum(
            1 for e in events if e["kind"] == "member_join"
        ),
        "member_rejoins": sum(
            1 for e in events
            if e["kind"] == "member_join" and e.get("rejoin")
        ),
        "member_drains": sum(
            1 for e in events if e["kind"] == "member_drain"
        ),
        "member_deaths": sum(
            1 for e in events if e["kind"] == "member_dead"
        ),
    }
    if any(member_counts.values()):
        summary.update(member_counts)
    else:
        summary.update(dict.fromkeys(member_counts))
    # MPMD pipeline recovery (parallel/mpmd.py): restarts of this stage
    # plus microbatch frames its links replayed to restarted neighbors.
    # Same None-not-0 convention as the membership counts above.
    stage_counts = {
        "stage_restarts": sum(
            1 for e in events if e["kind"] == "stage_restart"
        ),
        "replayed_microbatches": sum(
            int(e.get("count", 0)) for e in events if e["kind"] == "replay"
        ),
    }
    if any(stage_counts.values()):
        summary.update(stage_counts)
    else:
        summary.update(dict.fromkeys(stage_counts))
    if run and run.get("roster") is not None:
        summary["roster"] = run["roster"]
    if run:
        for key in (SERVING_SUMMARY_KEYS + STREAMING_SUMMARY_KEYS
                    + ROUTER_SUMMARY_KEYS):
            if key in run:
                summary[key] = run[key]
    # efficiency-ledger ratios (obs/ledger.py): goodput, its inverse
    # badput_frac (the diffable direction - see REGRESSION_METRICS),
    # fault tax and the comm-wait share of wall.  None, never 0, on
    # schema-1 sidecars: the ledger needs the monotonic clock, and an
    # uninstrumented run must not read as "goodput zero".
    try:
        from pytorch_distributed_rnn_tpu.obs.ledger import ledger_events

        led = ledger_events(events)
    except MalformedMetricsError:
        led = None
    summary["goodput"] = led["goodput"] if led else None
    summary["badput_frac"] = (1.0 - led["goodput"]) if led else None
    summary["fault_tax_s"] = led["fault_tax_s"] if led else None
    summary["comm_wait_frac"] = led["comm_wait_frac"] if led else None
    summary["mfu_est"] = led["mfu_est"] if led else None
    return summary


def summarize_file(path) -> dict:
    return summarize_events(load_events(path), path=path)


def summarize_run(path) -> list[dict]:
    """Per-rank summaries for one run's sidecar family (rank-0 path plus
    ``-r<k>`` siblings), sorted by rank."""
    files = rank_files(path)
    if not files:
        raise MalformedMetricsError(f"{path}: no metrics sidecar found")
    return sorted(
        (summarize_file(p) for p in files), key=lambda s: s["rank"]
    )


# metrics where "bigger" is a regression, diffed by pdrnn-metrics diff.
# The per-phase collective bytes gate the sharded-update win: a change
# that re-inflates update-phase traffic (or gradient-phase traffic)
# trips the diff exit contract.  Replicated baselines report update
# bytes of 0, which the <= 0 guard in diff_summaries skips - turning
# sharding ON can never read as a regression against them.
REGRESSION_METRICS = (
    "step_s_mean", "step_s_p95", "duration_s", "memory_mb",
    "device_peak_mb", "data_wait_frac",
    # host-collective blocked wall (native ring): overlap regressions -
    # a schedule change that re-serializes comm behind compute - show up
    # here before they dent step_s_mean.  overlap_frac is deliberately
    # NOT listed: bigger is better, the wait metric already covers it.
    "comm_wait_s", "comm_wait_s_mean",
    "collective_grad_bytes_per_step", "collective_update_bytes_per_step",
    # efficiency-ledger ratios (obs/ledger.py).  goodput itself is
    # bigger-is-better and therefore NOT listed (the overlap_frac
    # precedent): its inverse badput_frac is the gated direction.
    # fault_tax_s is 0 on clean baselines, which the <= 0 guard skips -
    # turning chaos ON can never read as a regression against them; on
    # schema-1 sidecars all three are None (skipped the same way).
    "badput_frac", "fault_tax_s", "comm_wait_frac",
)


def diff_summaries(baseline: dict, candidate: dict,
                   threshold_pct: float = 10.0) -> list[dict]:
    """Regressions of ``candidate`` vs ``baseline``: every
    :data:`REGRESSION_METRICS` entry present in both and worse by more
    than ``threshold_pct`` percent."""
    regressions = []
    for metric in REGRESSION_METRICS:
        base, cand = baseline.get(metric), candidate.get(metric)
        if base is None or cand is None or base <= 0:
            continue
        delta_pct = 100.0 * (cand - base) / base
        if delta_pct > threshold_pct:
            regressions.append({
                "metric": metric,
                "baseline": base,
                "candidate": cand,
                "delta_pct": delta_pct,
            })
    return regressions


# events that witness forward progress (vs mere liveness): everything a
# run emits except the writer thread's own heartbeats, the meta head,
# and watchdog alerts - a STALL alert is evidence of the opposite of
# progress, and counting it would flip the stalled rank back to ok
_NON_PROGRESS_KINDS = ("meta", "heartbeat", "alert")


def rank_health(events: list[dict], now: float | None = None,
                stale_after: float = 30.0) -> dict:
    """One rank's liveness verdict from its event stream.

    Three signals: the last event of ANY kind (the writer thread's
    heartbeats keep this fresh as long as the process lives), the last
    *progress* (any non-heartbeat event, or a heartbeat whose noted
    ``progress`` step advanced), and whether the run finished (a
    ``run_summary`` landed) or the rank left voluntarily (a
    ``member_drain`` landed - the DEREGISTER half of preemption-aware
    drain).  Status:

    - ``finished`` - run_summary present (age is irrelevant);
    - ``drained``  - the rank deregistered on purpose (SIGTERM drain):
      its stream going stale afterwards is the EXPECTED shape of a
      voluntary leave, not a death - healthy, exit 0;
    - ``dead``     - nothing at all for ``stale_after`` seconds: the
      process stopped flushing (killed, wedged below Python);
    - ``recovering`` - heartbeats fresh, no progress, but the last
      thing this rank did was a ``stage_restart`` with no ``step``
      landed since: a respawned MPMD stage still restoring its
      checkpoint and retracing its programs.  The same verdict covers a
      streaming actor (``role: actor`` in its meta head) that
      registered with the learner - a ``state_sync`` span or
      ``actor_reconnect`` landed - but has not pushed a batch since:
      it is compiling its rollout or riding out backpressure, not
      wedged.  Expected recovery work, not a stall - healthy, exit 0;
    - ``stalled``  - heartbeats fresh but no progress for
      ``stale_after`` seconds: alive and stuck (the chaos harness's
      ``stall`` fault, a hung collective, a starved loader);
    - ``ok``       - otherwise.
    """
    if now is None:
        import time

        now = time.time()
    rank = int(events[0].get("rank", 0))
    finished = any(e["kind"] == "run_summary" for e in events)
    # only a drain of THIS rank counts: the master's sidecar carries
    # member_drain events for its WORKERS (rank_slot != 0) and must not
    # classify the master itself as drained mid-run
    drained = any(
        e["kind"] == "member_drain"
        and int(e.get("rank_slot", e["rank"])) == rank
        for e in events
    )
    last_t = max(float(e["t"]) for e in events)
    progress_ts = [
        float(e["t"]) for e in events
        if e["kind"] not in _NON_PROGRESS_KINDS
    ]
    noted = None
    for e in events:
        if e["kind"] == "heartbeat" and e.get("progress") is not None \
                and e["progress"] != noted:
            noted = e["progress"]
            progress_ts.append(float(e["t"]))
    last_progress_t = max(progress_ts) if progress_ts else float(
        events[0]["t"]
    )
    if finished:
        status = "finished"
    elif drained:
        status = "drained"
    elif now - last_t > stale_after:
        status = "dead"
    elif now - last_progress_t > stale_after:
        # a respawned stage restoring + retracing is working, not stuck
        # - but only until its first post-restart step lands; after
        # that, silence is an ordinary stall again.  A stage whose
        # heartbeats also stopped stays DEAD (branch above): respawn
        # grace never masks a killed process.
        restart_ts = [
            float(e["t"]) for e in events if e["kind"] == "stage_restart"
        ]
        # a streaming actor's registration witnesses play the same role
        # as a stage_restart: joined/rejoined the learner, first push
        # still pending.  Gated on the actor role so a PS/streaming
        # MASTER's sidecar (which carries state_sync spans for its
        # members' joins) can never launder its own stall as recovery.
        if events[0].get("role") == "actor":
            restart_ts += [
                float(e["t"]) for e in events
                if e["kind"] == "actor_reconnect"
                or (e["kind"] == "span" and e.get("name") == "state_sync")
            ]
            restart_ts.sort()
        stepped_since = restart_ts and any(
            e["kind"] == "step" and float(e["t"]) >= restart_ts[-1]
            for e in events
        )
        if restart_ts and not stepped_since:
            status = "recovering"
        else:
            status = "stalled"
    else:
        status = "ok"
    return {
        "rank": rank,
        "status": status,
        "last_event_age_s": now - last_t,
        "last_progress_age_s": now - last_progress_t,
        "finished": finished,
        "drained": drained,
    }


def detect_stragglers(summaries: list[dict],
                      threshold: float = 0.25) -> list[dict]:
    """Cross-rank straggler detection: ranks whose mean step time sits
    more than ``threshold`` (fraction) above the cross-rank median.
    Needs >= 2 ranks with step-time data; returns ``[{rank, step_s_mean,
    median_s, excess_frac}, ...]``."""
    timed = [
        s for s in summaries if s.get("step_s_mean") is not None
    ]
    if len(timed) < 2:
        return []
    median = statistics.median(s["step_s_mean"] for s in timed)
    if median <= 0:
        return []
    flagged = []
    for s in timed:
        excess = s["step_s_mean"] / median - 1.0
        if excess > threshold:
            flagged.append({
                "rank": s["rank"],
                "path": s.get("path"),
                "step_s_mean": s["step_s_mean"],
                "median_s": median,
                "excess_frac": excess,
            })
    return sorted(flagged, key=lambda f: -f["excess_frac"])
