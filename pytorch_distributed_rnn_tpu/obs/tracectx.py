"""Cross-process trace context: the causal spine of request tracing.

A :class:`TraceContext` names one node in a request's causal tree -
``trace_id`` identifies the whole request, ``span_id`` this node,
``parent_id`` the node that caused it - plus QoS baggage (priority
class and friends) that rides the whole tree.  It is minted ONCE at
the edge (the fleet router, or the load generator via
``--trace-sample RATE``), carried as the optional ``trace`` field on
the ``serve`` JSONL protocol, and forked with :meth:`child` at every
causal boundary: each router dispatch attempt is a distinct child span
(so sibling retry/hedge re-dispatches are distinguishable in replica
logs), and the replica engine forks again for its queue_wait / prefill
/ decode / stream_emit phases.

Spans themselves ride the existing :class:`~.recorder.MetricsRecorder`
sidecars as ordinary ``span`` events carrying the ``trace`` / ``span``
/ ``parent`` attributes (:meth:`span_fields`); ``obs/trace.py``
re-joins the per-process sidecars into one tree per trace_id.

Zero-overhead-off contract (the obs doctrine): with tracing off no
:class:`TraceContext` is ever constructed - the class-level
:attr:`TraceContext.minted` counter exists so tests can PIN that - the
wire messages carry no ``trace`` key (byte-identical requests), and
nothing here is ever reachable from jitted code, so the step jaxpr
cannot change.
"""

from __future__ import annotations

import math
import os

# wire-key vocabulary of the ``trace`` field (kept one-token short:
# the field rides every traced generate line)
_WIRE_TRACE = "id"
_WIRE_SPAN = "span"
_WIRE_PARENT = "parent"
_WIRE_KEYS = (_WIRE_TRACE, _WIRE_SPAN, _WIRE_PARENT)

# baggage values must survive a JSON round trip unchanged
_BAGGAGE_TYPES = (str, int, float, bool)


def _hex_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


class TraceContext:
    """One node of a request's causal tree (immutable by convention)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "baggage")

    #: total contexts ever constructed in this process - the
    #: tracing-off zero-overhead pin reads this (no allocation = the
    #: counter does not move)
    minted = 0

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: str | None = None,
                 baggage: dict | None = None):
        self.trace_id = str(trace_id)
        self.span_id = str(span_id)
        self.parent_id = None if parent_id is None else str(parent_id)
        self.baggage = dict(baggage or {})
        TraceContext.minted += 1

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"TraceContext(trace={self.trace_id} span={self.span_id}"
            f" parent={self.parent_id} baggage={self.baggage})"
        )

    # -- construction --------------------------------------------------------

    @classmethod
    def mint(cls, **baggage) -> "TraceContext":
        """A fresh ROOT context (no parent) - the edge of the tree.
        Keyword arguments become QoS baggage carried by every child."""
        return cls(
            _hex_id(8), _hex_id(4),
            baggage={k: v for k, v in baggage.items() if v is not None},
        )

    def child(self) -> "TraceContext":
        """Fork a child span: same trace, new span id, this node as
        parent; baggage is inherited (it describes the REQUEST)."""
        return TraceContext(
            self.trace_id, _hex_id(4), parent_id=self.span_id,
            baggage=self.baggage,
        )

    # -- wire ----------------------------------------------------------------

    def to_wire(self) -> dict:
        """The JSON-safe ``trace`` field of a protocol message."""
        wire = {_WIRE_TRACE: self.trace_id, _WIRE_SPAN: self.span_id}
        if self.parent_id is not None:
            wire[_WIRE_PARENT] = self.parent_id
        wire.update(self.baggage)
        return wire

    @classmethod
    def from_wire(cls, obj) -> "TraceContext | None":
        """Parse a peer's ``trace`` field; ``None`` on anything that is
        not a well-formed context (an observability field must never
        fail a request)."""
        if not isinstance(obj, dict):
            return None
        trace_id = obj.get(_WIRE_TRACE)
        span_id = obj.get(_WIRE_SPAN)
        if not isinstance(trace_id, str) or not trace_id \
                or not isinstance(span_id, str) or not span_id:
            return None
        parent = obj.get(_WIRE_PARENT)
        if parent is not None and not isinstance(parent, str):
            return None
        baggage = {
            k: v for k, v in obj.items()
            if k not in _WIRE_KEYS and isinstance(v, _BAGGAGE_TYPES)
        }
        return cls(trace_id, span_id, parent_id=parent, baggage=baggage)

    # -- recorder glue -------------------------------------------------------

    def span_fields(self) -> dict:
        """The attributes a ``span`` event carries so ``obs/trace.py``
        can re-join sidecars: ``trace``/``span``(/``parent``)."""
        fields = {"trace": self.trace_id, "span": self.span_id}
        if self.parent_id is not None:
            fields["parent"] = self.parent_id
        return fields


def should_sample(seq: int, rate: float) -> bool:
    """Deterministic evenly-spaced head sampling: of the first ``n``
    sequence numbers, ``ceil(n * rate)`` are sampled, spread evenly -
    no RNG, so turning sampling on cannot shift any seeded request
    plan (the load generator's determinism pin)."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    return math.floor(seq * rate) > math.floor((seq - 1) * rate)
