"""Anomaly watchdog + all-thread stack dumps for hang diagnosis.

The in-process half of live anomaly detection: a small daemon thread
(one per process, only when the live plane is on) that watches the
exporter's rolling windows and the recorder's progress note for

- **stalls** - heartbeats stay fresh (the writer thread lives) while
  ``note_progress`` freezes past ``stall_after_s``: the chaos harness's
  ``stall`` fault, a hung collective, a starved loader.  On detection
  the watchdog dumps ALL thread stacks via :mod:`faulthandler` to a
  sidecar-adjacent file (``<sidecar-stem>-stacks.txt``) - the
  post-mortem a wedged run never gets to write itself - and emits a
  structured ``alert`` event;
- **NaN streaks** - ``nan_streak`` consecutive non-finite losses;
- **loss spikes** - the newest loss above ``loss_spike_factor`` x the
  rolling window median;
- **serving SLO breaches** - windowed p95 latency above a per-QoS
  ``--slo`` objective (``qos=high:p95_ms=250:availability=99.9``; the
  router's per-class p95 when available, the block p95 otherwise).
  The legacy global ``PDRNN_WATCHDOG_SLO_P95_MS`` env is DEPRECATED -
  still honored as a default objective with a loud warning when no
  ``--slo`` is configured;
- **SLO budget burn** - when the anchor's time-series store
  (``obs/store.py``) is bound, multi-window error-budget burn rates per
  objective: episodic ``slo_burn`` alerts fire when BOTH the fast and
  slow windows burn strictly above 1.0 and ``slo_burn_cleared`` marks
  the fast window's recovery - the Google SRE fast-catch/slow-confirm
  pattern riding the same structured-alert path;
- **goodput collapse** - the exporter's windowed goodput estimate
  (``goodput_60s``: fraction of the last minute inside step compute,
  the live half of ``obs/ledger.py``) falls below the
  ``PDRNN_WATCHDOG_GOODPUT`` floor while the run is still making
  nominal progress - the "alive but mostly waiting" failure mode a
  stall detector cannot see.  Armed only when the env knob is set.

Alerts are recorded as normal sidecar events (kind ``alert``, schema in
``obs/recorder.py``) and flushed immediately, so ``pdrnn-metrics
summarize``/``timeline`` see them for free AND they are on disk while
the run is still wedged; they also ride the next live digest into the
aggregator's ``/events``.  Each detector is episodic: one alert when
the condition starts, re-armed when it clears (an ``info`` clear event
marks recovery), so a long stall cannot flood the stream.

Chaos link (``resilience/faults.py``): when a fault schedule is bound,
every alert carries a ``chaos_fired`` snapshot of the schedule's fired
counters - a drill's injected stall is distinguishable from an organic
one in the event stream.

:func:`install_stack_dump_handler` is the on-demand half (satellite):
every long-lived entrypoint registers SIGUSR2 via
``faulthandler.register`` - a C-level handler, so it dumps even when
the Python main thread is wedged below the interpreter - appending to
the same stacks file the watchdog uses.
"""

from __future__ import annotations

import faulthandler
import logging
import math
import os
import signal
import threading
import time
from pathlib import Path

log = logging.getLogger(__name__)

WATCHDOG_ENV = "PDRNN_WATCHDOG"  # "0" disables the watchdog outright
WATCHDOG_STALL_ENV = "PDRNN_WATCHDOG_STALL"  # seconds (default 10)
# DEPRECATED: the global serving SLO (ms).  Use per-QoS --slo
# objectives instead; the env is still honored as a default objective
# (with a loud warning) when no --slo is configured.
WATCHDOG_SLO_ENV = "PDRNN_WATCHDOG_SLO_P95_MS"
WATCHDOG_GOODPUT_ENV = "PDRNN_WATCHDOG_GOODPUT"  # goodput floor (0..1)

_DEFAULT_STALL_AFTER_S = 10.0
_DEFAULT_NAN_STREAK = 3
_DEFAULT_SPIKE_FACTOR = 10.0
_SPIKE_MIN_SAMPLES = 8

STACK_DUMP_SIGNAL = getattr(signal, "SIGUSR2", None)

# faulthandler.register keeps the file object alive forever; track it so
# repeated installs (tests, respawns) replace instead of leak
_signal_dump_file = None


def resolve_stall_after(env=None) -> float:
    return float(
        (env or os.environ).get(WATCHDOG_STALL_ENV, _DEFAULT_STALL_AFTER_S)
    )


def stacks_path_for(sidecar_path) -> Path:
    """The one stack-dump location per process: next to the (rank-
    suffixed) sidecar, ``<stem>-stacks.txt`` - uploaded by CI alongside
    the metrics artifact."""
    sidecar_path = Path(sidecar_path)
    return sidecar_path.with_name(f"{sidecar_path.stem}-stacks.txt")


def dump_stacks(path, reason: str = "") -> Path | None:
    """Append a headered all-thread stack dump to ``path``; returns the
    path, or None when the dump failed (diagnosis must never kill the
    patient)."""
    path = Path(path)
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "a") as f:
            f.write(
                f"\n==== pdrnn stack dump pid={os.getpid()} "
                f"reason={reason or 'unspecified'} t={time.time():.3f}\n"
            )
            f.flush()
            faulthandler.dump_traceback(file=f, all_threads=True)
        return path
    except OSError as exc:
        log.warning(f"watchdog: stack dump to {path} failed: {exc}")
        return None


def install_stack_dump_handler(sidecar_path) -> Path | None:
    """Register SIGUSR2 -> all-thread stack dump into the sidecar-
    adjacent stacks file (``kill -USR2 <pid>`` is the on-demand hang
    diagnosis every long-lived entrypoint installs).  C-level via
    ``faulthandler.register``, so it fires even when the main thread is
    wedged below Python.  Returns the dump path (None on platforms
    without SIGUSR2)."""
    global _signal_dump_file
    if STACK_DUMP_SIGNAL is None:  # pragma: no cover - non-POSIX
        return None
    path = stacks_path_for(sidecar_path)
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        f = open(path, "a")
    except OSError as exc:  # pragma: no cover - unwritable sidecar dir
        log.warning(f"stack-dump handler not installed: {exc}")
        return None
    faulthandler.register(STACK_DUMP_SIGNAL, file=f, all_threads=True,
                          chain=False)
    if _signal_dump_file is not None:
        try:
            _signal_dump_file.close()
        except OSError:  # pragma: no cover
            pass
    _signal_dump_file = f
    # the handler file deliberately lives until process exit (replaced
    # only by a re-install above) - exempt it from the leak sentinel;
    # lazy import: leakcheck's violation path imports this module
    from pytorch_distributed_rnn_tpu.utils import leakcheck
    leakcheck.adopt(f, reason="sigusr2 stack-dump sink")
    log.info(f"stack-dump handler: SIGUSR2 -> {path}")
    return path


class AnomalyWatchdog:
    """One daemon thread of in-run anomaly detection per process."""

    def __init__(self, recorder, exporter, *, faults=None,
                 stall_after_s: float = _DEFAULT_STALL_AFTER_S,
                 check_every_s: float | None = None,
                 nan_streak: int = _DEFAULT_NAN_STREAK,
                 loss_spike_factor: float = _DEFAULT_SPIKE_FACTOR,
                 slo=(), slo_p95_s: float | None = None,
                 store=None, goodput_floor: float | None = None,
                 dump_dir_hint=None):
        self.recorder = recorder
        self.exporter = exporter
        self.faults = faults
        self.stall_after_s = float(stall_after_s)
        self.check_every_s = (
            float(check_every_s) if check_every_s is not None
            else max(0.1, min(1.0, self.stall_after_s / 4))
        )
        self.nan_streak = int(nan_streak)
        self.loss_spike_factor = float(loss_spike_factor)
        # per-QoS --slo objectives; the deprecated global slo_p95_s
        # (env) stays a single class-blind default when no --slo is set
        self.slo = tuple(slo)
        self.slo_p95_s = slo_p95_s
        # the anchor's time-series store (None elsewhere): arms the
        # budget-burn detector
        self.store = store
        self.goodput_floor = goodput_floor
        self.stacks_path = stacks_path_for(
            dump_dir_hint or recorder.path or "pdrnn-metrics.jsonl"
        )
        self._seq = 0
        self._stop = threading.Event()
        self._thread = None
        # per-detector episode latches (one alert per episode)
        self._in_stall = False
        self._in_nan = False
        self._in_spike = False
        self._in_slo: dict[str, bool] = {}  # per-QoS breach episodes
        self._in_burn: dict[str, bool] = {}  # per-QoS burn episodes
        self._in_goodput = False

    @classmethod
    def resolve(cls, recorder, exporter, *, faults=None, slo=(),
                store=None, env=None) -> "AnomalyWatchdog | None":
        """Env-tuned construction (``PDRNN_WATCHDOG=0`` disables;
        ``PDRNN_WATCHDOG_STALL`` seconds; ``PDRNN_WATCHDOG_GOODPUT``
        arms the goodput-collapse detector with a 0..1 floor).  ``slo``
        objectives (the ``--slo`` flag) arm the per-QoS SLO detector
        and - with a ``store`` bound - the budget-burn detector; the
        DEPRECATED ``PDRNN_WATCHDOG_SLO_P95_MS`` env still arms a
        global default when no objectives are configured."""
        env = env or os.environ
        if env.get(WATCHDOG_ENV, "1") in ("0", "off", "false"):
            return None
        slo_ms = env.get(WATCHDOG_SLO_ENV)
        if slo_ms and not slo:
            log.warning(
                f"{WATCHDOG_SLO_ENV} is DEPRECATED: the global p95 "
                "threshold cannot distinguish QoS classes - use "
                "--slo 'qos=<class>:p95_ms=<ms>[:availability=<pct>]' "
                "(repeatable, one per class); honoring the env as a "
                "default objective for every class this run"
            )
        elif slo_ms and slo:
            log.warning(
                f"{WATCHDOG_SLO_ENV} ignored: --slo objectives are "
                "configured and take precedence"
            )
            slo_ms = None
        goodput = env.get(WATCHDOG_GOODPUT_ENV)
        return cls(
            recorder, exporter, faults=faults, slo=slo, store=store,
            stall_after_s=resolve_stall_after(env),
            slo_p95_s=float(slo_ms) / 1e3 if slo_ms else None,
            goodput_floor=float(goodput) if goodput else None,
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="pdrnn-watchdog", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.wait(self.check_every_s):
            try:
                self.check()
            except Exception:  # pragma: no cover - must never die loudly
                log.exception("watchdog: check failed")

    # -- detection -----------------------------------------------------------

    def check(self, now: float | None = None) -> None:
        """One detection pass (public for tests/drills)."""
        now = time.perf_counter() if now is None else now
        self._check_stall(now)
        self._check_loss()
        self._check_slo()
        self._check_burn(now)
        self._check_goodput(now)

    def _check_stall(self, now: float) -> None:
        age = self.exporter.progress_age_s(now)
        if age is None or self.exporter.finished:
            return
        from pytorch_distributed_rnn_tpu.obs.live import serving_idle

        if serving_idle(self.exporter.source_snapshot().get("serving")):
            # an idle serving engine has no work to progress on: frozen
            # decode-step count is idleness, not a hang
            self._in_stall = False
            return
        if age > self.stall_after_s:
            if not self._in_stall:
                self._in_stall = True
                dumped = dump_stacks(
                    self.stacks_path,
                    reason=f"stall progress_age={age:.1f}s",
                )
                self._alert(
                    "stall", progress=self.exporter.recorder.progress,
                    progress_age_s=age,
                    stall_after_s=self.stall_after_s,
                    stacks=str(dumped) if dumped else None,
                )
        elif self._in_stall:
            self._in_stall = False
            self._alert("stall_cleared", severity="info",
                        progress=self.exporter.recorder.progress)

    def _check_loss(self) -> None:
        streak = self.exporter.loss_nonfinite_streak
        if streak >= self.nan_streak:
            if not self._in_nan:
                self._in_nan = True
                self._alert("nan_streak", streak=streak)
        else:
            self._in_nan = False
        stats = self.exporter.loss.stats()
        last, p50 = stats["last"], stats["p50"]
        if (
            stats["count"] >= _SPIKE_MIN_SAMPLES
            and last is not None and p50 is not None and p50 > 0
            and math.isfinite(last)
        ):
            if last > self.loss_spike_factor * p50:
                if not self._in_spike:
                    self._in_spike = True
                    self._alert("loss_spike", loss=last, window_p50=p50,
                                factor=self.loss_spike_factor)
            else:
                self._in_spike = False

    def _check_slo(self) -> None:
        # per-QoS --slo objectives; the deprecated global env threshold
        # degrades to one class-blind check (qos None) when no --slo
        checks = [
            (obj.qos, obj.p95_ms / 1e3) for obj in self.slo
            if obj.p95_ms is not None
        ]
        if not checks and self.slo_p95_s is not None:
            checks = [(None, float(self.slo_p95_s))]
        if not checks:
            return
        snapshot = self.exporter.source_snapshot()
        serving = snapshot.get("serving") or {}
        router = snapshot.get("router") or {}
        block = router or serving
        if not block:
            return
        by_qos = router.get("latency_s_p95_by_qos") or {}
        for qos, threshold_s in checks:
            # the router carries per-class p95; the engine's block p95
            # is class-blind, so an objective without one checks the
            # block (the honest approximation until the engine splits
            # latency by QoS)
            p95 = by_qos.get(qos, block.get("latency_s_p95"))
            if p95 is None:
                continue
            key = qos or "*"
            latched = self._in_slo.get(key, False)
            if p95 > threshold_s:
                if not latched:
                    self._in_slo[key] = True
                    self._alert("slo_breach", qos=qos,
                                latency_s_p95=p95,
                                slo_p95_s=threshold_s,
                                queue_depth=serving.get("queue_depth"))
            elif latched:
                self._in_slo[key] = False
                self._alert("slo_recovered", severity="info",
                            qos=qos, latency_s_p95=p95,
                            slo_p95_s=threshold_s)

    def _check_burn(self, now: float) -> None:
        """Episodic error-budget burn alerts off the anchor's store:
        fire when BOTH windows burn strictly above 1.0 (fast catches,
        slow confirms), clear when the fast window recovers."""
        if self.store is None or not self.slo:
            return
        for qos, burn in self.store.burn_snapshot(now).items():
            latched = self._in_burn.get(qos, False)
            if burn["fire"] and not latched:
                self._in_burn[qos] = True
                self._alert(
                    "slo_burn", qos=qos,
                    burn_rate_fast=burn["fast"],
                    burn_rate_slow=burn["slow"],
                    objective=burn.get("objective"),
                    windows_s=list(self.store.burn_windows_s),
                )
            elif latched and burn["fast"] <= 1.0:
                self._in_burn[qos] = False
                self._alert(
                    "slo_burn_cleared", severity="info", qos=qos,
                    burn_rate_fast=burn["fast"],
                    burn_rate_slow=burn["slow"],
                )

    def _check_goodput(self, now: float) -> None:
        if self.goodput_floor is None or self.exporter.finished:
            return
        goodput = self.exporter.goodput_60s(now)
        # demand a populated step window: warm-up and the pre-first-step
        # gap report None / near-zero goodput without being a collapse
        stats = self.exporter.step_s.stats(now)
        if goodput is None or stats["count"] < _SPIKE_MIN_SAMPLES:
            return
        if goodput < self.goodput_floor:
            if not self._in_goodput:
                self._in_goodput = True
                self._alert(
                    "goodput_collapse", goodput_60s=goodput,
                    goodput_floor=self.goodput_floor,
                    step_s_mean=stats["mean"],
                )
        elif self._in_goodput:
            self._in_goodput = False
            self._alert("goodput_recovered", severity="info",
                        goodput_60s=goodput,
                        goodput_floor=self.goodput_floor)

    # -- emission ------------------------------------------------------------

    def _alert(self, kind: str, severity: str = "warning",
               **fields) -> None:
        self._seq += 1
        payload = {"alert": kind, "severity": severity, "seq": self._seq}
        payload.update(
            (k, v) for k, v in fields.items() if v is not None
        )
        if self.faults is not None and self.faults.fired:
            payload["chaos_fired"] = self.faults.fired_snapshot()
        log.warning(f"watchdog: {kind} {fields}")
        # the sidecar event is the system of record; flush NOW so the
        # alert is on disk while the run is still wedged (the live-drill
        # acceptance: the alert lands BEFORE the run exits).  The live
        # digest picks it up via observe_event -> _alerts.
        self.recorder.record("alert", **payload)
        self.recorder.flush()
