"""Cross-process trace assembly: sidecar span events -> causal trees.

The read side of ``obs/tracectx.py``: every process on a traced
request's path (router, replicas) records ordinary ``span`` events
carrying ``trace``/``span``/``parent`` attributes on its own
:class:`~.recorder.MetricsRecorder` sidecar.  This module re-joins any
number of those sidecars (rank families expand automatically, so
``router-metrics.jsonl`` pulls in the replicas' ``-r<k>`` siblings)
into one :class:`TraceTree` per trace_id - the ``pdrnn-metrics trace``
subcommand and the CI fleet gate sit on top.

Span wall-clock stamps come from each process's own anchor
(``recorder.py``); same-host skew is millisecond-scale, so child spans
are clamped into their parent's window with :data:`SKEW_TOL_S` slack
rather than trusted blindly.

Critical-path attribution: every node's SELF time is its duration
minus its children's (clamped) durations, and the reported fractions
are self times normalized over their total - so they sum to 1 exactly,
the same contract as the ledger's phase fractions.
"""

from __future__ import annotations

import json

from pytorch_distributed_rnn_tpu.obs.summary import (
    MalformedMetricsError,
    load_events,
    rank_files,
)

# tolerated cross-process clock skew when validating parent/child
# nesting (same-host wall clocks; the anchors are NTP-stepped wall
# time, not the monotonic clocks themselves)
SKEW_TOL_S = 0.05

# span attributes that are trace bookkeeping, not payload
_CTX_KEYS = ("trace", "span", "parent")


class MalformedTraceError(MalformedMetricsError):
    """The collected spans do not form a well-formed trace tree."""


class TraceNode:
    """One span of one process, linked into its causal tree."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t",
                 "dur_s", "rank", "role", "source", "attrs", "children",
                 "self_s")

    def __init__(self, event: dict, *, rank: int, role: str,
                 source: str):
        self.name = str(event.get("name", "?"))
        self.trace_id = str(event["trace"])
        self.span_id = str(event["span"])
        parent = event.get("parent")
        self.parent_id = None if parent is None else str(parent)
        self.t = float(event.get("t", 0.0))
        self.dur_s = max(0.0, float(event.get("dur_s") or 0.0))
        self.rank = rank
        self.role = role
        self.source = source
        self.attrs = {
            k: v for k, v in event.items()
            if k not in ("kind", "name", "t", "tm", "rank", "dur_s",
                         "cat", *_CTX_KEYS)
        }
        self.children: list[TraceNode] = []
        self.self_s = 0.0

    @property
    def end(self) -> float:
        return self.t + self.dur_s

    def walk(self):
        yield self
        for child in self.children:
            yield from child.walk()


class TraceTree:
    """One request's assembled tree: a root plus derived views."""

    def __init__(self, trace_id: str, root: TraceNode):
        self.trace_id = trace_id
        self.root = root

    @property
    def duration_s(self) -> float:
        return self.root.dur_s

    @property
    def processes(self) -> set:
        """Distinct (source, rank) pairs contributing spans - the
        cross-process gate counts these."""
        return {(n.source, n.rank) for n in self.root.walk()}

    @property
    def request(self):
        for node in self.root.walk():
            if node.attrs.get("request") is not None:
                return node.attrs["request"]
        return None

    def critical_path(self) -> dict:
        """``span name -> fraction of the root's wall time`` attributed
        to that name's SELF time, normalized to sum to 1 exactly."""
        for node in self.root.walk():
            child_s = sum(
                min(c.dur_s, max(0.0, self.root.end - c.t))
                for c in node.children
            )
            node.self_s = max(0.0, node.dur_s - child_s)
        total = sum(n.self_s for n in self.root.walk())
        if total <= 0.0:
            return {self.root.name: 1.0}
        fractions: dict[str, float] = {}
        for node in self.root.walk():
            if node.self_s > 0.0:
                fractions[node.name] = (
                    fractions.get(node.name, 0.0) + node.self_s / total
                )
        # float dust lands on the largest bin so the sum is EXACT
        largest = max(fractions, key=lambda k: fractions[k])
        fractions[largest] += 1.0 - sum(fractions.values())
        return fractions

    def to_json(self) -> dict:
        def node_json(node: TraceNode) -> dict:
            return {
                "name": node.name, "span": node.span_id,
                "parent": node.parent_id, "t": node.t,
                "dur_s": node.dur_s, "rank": node.rank,
                "role": node.role, "source": node.source,
                "attrs": node.attrs,
                "children": [node_json(c) for c in node.children],
            }

        return {
            "trace_id": self.trace_id,
            "request": self.request,
            "duration_s": self.duration_s,
            "processes": sorted(
                f"{src}:r{rank}" for src, rank in self.processes
            ),
            "critical_path": self.critical_path(),
            "root": node_json(self.root),
        }


def collect_trace_spans(paths) -> dict:
    """All trace-carrying ``span`` events off every sidecar family in
    ``paths``, grouped by trace_id.  Returns
    ``{trace_id: [TraceNode, ...]}`` (unlinked)."""
    by_trace: dict[str, list[TraceNode]] = {}
    seen_files = set()
    for path in paths:
        files = rank_files(path)
        if not files:
            raise MalformedTraceError(
                f"{path}: no metrics sidecar found"
            )
        for file in files:
            if file in seen_files:
                continue
            seen_files.add(file)
            events = load_events(file)
            meta = events[0]
            rank = int(meta.get("rank", 0))
            role = str(meta.get("role", "?"))
            for event in events:
                if event.get("kind") != "span" or "trace" not in event:
                    continue
                if "span" not in event:
                    raise MalformedTraceError(
                        f"{file}: span event carries 'trace' without "
                        f"'span'"
                    )
                node = TraceNode(event, rank=rank, role=role,
                                 source=str(file))
                by_trace.setdefault(node.trace_id, []).append(node)
    return by_trace


def build_trace_tree(trace_id: str, nodes) -> TraceTree:
    """Link one trace's spans into a tree.  A node whose parent was
    recorded nowhere (the edge lived in a process without a sidecar -
    a tracing load generator, say) roots the tree; several such
    orphans sharing ONE unrecorded parent are siblings under it, so a
    synthetic root named ``request`` is minted to hold them (the
    direct-server shape: every engine phase is a child of the client's
    root span).  Orphans under DIFFERENT unrecorded parents are
    disconnected fragments and malformed, as is any duplicate span id
    or nesting that violates wall-clock containment beyond
    :data:`SKEW_TOL_S`."""
    by_span: dict[str, TraceNode] = {}
    for node in nodes:
        if node.span_id in by_span:
            raise MalformedTraceError(
                f"trace {trace_id}: duplicate span id {node.span_id} "
                f"({by_span[node.span_id].name} vs {node.name})"
            )
        by_span[node.span_id] = node
    roots = []
    for node in by_span.values():
        parent = (
            None if node.parent_id is None
            else by_span.get(node.parent_id)
        )
        if parent is None:
            roots.append(node)
        else:
            parent.children.append(node)
    if not roots:
        raise MalformedTraceError(
            f"trace {trace_id}: no root (span/parent links form a "
            f"cycle)"
        )
    if len(roots) > 1:
        parents = {r.parent_id for r in roots}
        if len(parents) != 1 or None in parents:
            names = ", ".join(sorted(r.name for r in roots))
            raise MalformedTraceError(
                f"trace {trace_id}: {len(roots)} disconnected roots "
                f"({names})"
            )
        # every orphan hangs off the same unrecorded edge span: mint it
        t0 = min(r.t for r in roots)
        root = TraceNode(
            {
                "name": "request", "trace": trace_id,
                "span": parents.pop(), "t": t0,
                "dur_s": max(r.end for r in roots) - t0,
                "synthesized": True,
            },
            rank=-1, role="client", source="(unrecorded edge)",
        )
        root.children.extend(roots)
        roots = [root]
    root = roots[0]
    for node in root.walk():
        node.children.sort(key=lambda n: (n.t, n.span_id))
        for child in node.children:
            if child.t < node.t - SKEW_TOL_S \
                    or child.end > node.end + SKEW_TOL_S:
                raise MalformedTraceError(
                    f"trace {trace_id}: span {child.name} "
                    f"[{child.t:.6f}, {child.end:.6f}] outside its "
                    f"parent {node.name} [{node.t:.6f}, "
                    f"{node.end:.6f}] beyond {SKEW_TOL_S:g}s skew"
                )
    return TraceTree(trace_id, root)


def validate_trace_tree(tree: TraceTree) -> None:
    """The tree-shape contract ``pdrnn-metrics trace`` enforces before
    printing: one root, resolvable links, wall-clock containment
    (:func:`build_trace_tree` raises on those) plus critical-path
    fractions summing to 1."""
    fractions = tree.critical_path()
    total = sum(fractions.values())
    if abs(total - 1.0) > 1e-9:
        raise MalformedTraceError(
            f"trace {tree.trace_id}: critical-path fractions sum to "
            f"{total!r}, not 1"
        )
    for node in tree.root.walk():
        if node.trace_id != tree.trace_id:
            raise MalformedTraceError(
                f"trace {tree.trace_id}: span {node.span_id} belongs "
                f"to trace {node.trace_id}"
            )


def assemble_traces(paths, *, request=None) -> list[TraceTree]:
    """Every trace tree across the sidecar families in ``paths``,
    slowest (largest root duration) first.  ``request`` filters to
    trees whose request id matches, or whose trace_id starts with it."""
    by_trace = collect_trace_spans(paths)
    trees = [
        build_trace_tree(trace_id, nodes)
        for trace_id, nodes in by_trace.items()
    ]
    if request is not None:
        want = str(request)
        trees = [
            t for t in trees
            if str(t.request) == want or t.trace_id.startswith(want)
        ]
    trees.sort(key=lambda t: (-t.duration_s, t.trace_id))
    return trees


def format_trace_tree(tree: TraceTree) -> str:
    """Human-readable tree + critical-path attribution."""
    lines = [
        f"trace {tree.trace_id}"
        + (f"  request={tree.request}" if tree.request is not None
           else "")
        + f"  {tree.duration_s * 1e3:.1f}ms across "
        f"{len(tree.processes)} process(es)"
    ]

    def emit(node: TraceNode, depth: int):
        extras = []
        for key in ("request", "replica", "attempt", "qos", "slot",
                    "status", "outcome", "hedge", "tokens"):
            if node.attrs.get(key) is not None:
                extras.append(f"{key}={node.attrs[key]}")
        where = f"{node.role}:r{node.rank}"
        lines.append(
            "  " * (depth + 1)
            + f"{node.name}  {node.dur_s * 1e3:.1f}ms  [{where}]"
            + (f"  ({', '.join(extras)})" if extras else "")
        )
        for child in node.children:
            emit(child, depth + 1)

    emit(tree.root, 0)
    fractions = tree.critical_path()
    ordered = sorted(fractions.items(), key=lambda kv: -kv[1])
    lines.append(
        "  critical path: "
        + "  ".join(f"{name} {frac:.1%}" for name, frac in ordered)
    )
    return "\n".join(lines)


def format_traces_json(trees) -> str:
    return json.dumps([t.to_json() for t in trees], indent=2)
