"""Analytic per-step FLOP and byte accounting from abstract jaxprs.

The efficiency ledger needs a compute numerator that works on any
backend without running (or even compiling) anything.  This module
counts arithmetic straight off the traced jaxpr, the same walk
``evaluation/collectives.py`` uses for per-step collective bytes: visit
every equation, multiply enclosing ``lax.scan`` trip counts in, recurse
into sub-jaxprs (pjit bodies, custom_vjp branches), and flag ``while``
loops - whose trip counts are dynamic - as inexact.

Costing rules (standard MFU conventions):

- ``dot_general``: ``2 * output_elements * contraction_size`` (one
  multiply + one add per MAC).  This is the term that dominates every
  LSTM/GRU/dense step in the tree.
- ``conv_general_dilated``: ``2 * output_elements * kernel_fan_in``.
- data movement (reshape/transpose/slice/gather/...) and collectives:
  0 FLOPs here - collective *bytes* are already counted by
  ``evaluation/collectives.py`` and priced in its bandwidth model.
- everything else: 1 FLOP per output element (add, mul, tanh, exp, ...
  - transcendentals deliberately not weighted, which keeps the count a
  *model* FLOP count comparable across backends, not a hardware
  op count).

Because the jaxpr of a full train step contains the backward pass, the
traced total is the *executed* FLOPs (an HFU numerator); without
rematerialization - none of this repo's step programs remat - it equals
the model FLOPs (the MFU numerator), and the ledger reports both against
``utils/hw.py`` peaks.
"""

from __future__ import annotations

# Primitives that move, reshape, or select data without arithmetic, plus
# cross-device collectives (bytes counted in evaluation/collectives.py).
ZERO_FLOP_PRIMS = frozenset({
    "broadcast_in_dim", "reshape", "transpose", "squeeze", "rev",
    "slice", "dynamic_slice", "dynamic_update_slice", "concatenate",
    "pad", "gather", "iota", "copy", "copy_p", "device_put",
    "convert_element_type", "bitcast_convert_type", "stop_gradient",
    "split", "expand_dims", "real", "imag",
    "sharding_constraint", "layout_constraint",
    # collectives / mesh bookkeeping
    "psum", "pmax", "pmin", "ppermute", "all_to_all", "all_gather",
    "reduce_scatter", "axis_index", "pvary",
})

# Control/structural primitives whose cost lives in their sub-jaxprs.
_STRUCTURAL_PRIMS = frozenset({
    "pjit", "jit", "closed_call", "core_call", "xla_call", "remat",
    "remat2", "checkpoint", "custom_jvp_call", "custom_vjp_call",
    "custom_vjp_call_jaxpr", "custom_jvp_call_jaxpr", "scan", "while",
    "cond", "named_call",
})


def _elems(aval) -> int:
    shape = getattr(aval, "shape", None)
    if not shape:
        return 1
    n = 1
    for d in shape:
        n *= int(d)
    return n


def aval_bytes(aval) -> int:
    """Bytes of one abstract value (0 for tokens and friends)."""
    dtype = getattr(aval, "dtype", None)
    if dtype is None or not hasattr(aval, "shape"):
        return 0
    return _elems(aval) * dtype.itemsize


def tree_bytes(tree) -> int:
    """Total bytes across a pytree of arrays/ShapeDtypeStructs."""
    import jax

    return sum(aval_bytes(leaf) for leaf in jax.tree.leaves(tree))


def _dot_general_flops(eqn) -> int:
    (lhs_contract, _), _ = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval
    contraction = 1
    for d in lhs_contract:
        contraction *= int(lhs.shape[d])
    return 2 * _elems(eqn.outvars[0].aval) * contraction


def _conv_flops(eqn) -> int:
    rhs = eqn.invars[1].aval
    dnums = eqn.params.get("dimension_numbers")
    out_feature_dim = dnums.rhs_spec[0] if dnums is not None else 0
    fan_in = _elems(rhs) // max(int(rhs.shape[out_feature_dim]), 1)
    return 2 * _elems(eqn.outvars[0].aval) * fan_in


def closed_jaxpr_flop_stats(closed) -> dict:
    """FLOPs and boundary bytes of one traced program execution.

    Returns ``{"flops", "by_primitive", "arg_bytes", "out_bytes",
    "exact"}`` where ``exact`` flips False when a ``while`` body (whose
    trip count the trace cannot know) was counted once - same honesty
    marker as the collective walk's ``while-body(unknown-trip-count)``.
    """
    jaxpr_cls = type(closed.jaxpr)
    closed_cls = type(closed)
    by_prim: dict[str, int] = {}
    state = {"exact": True}

    def subjaxprs(params):
        found = []

        def maybe(x):
            if isinstance(x, closed_cls):
                found.append(x.jaxpr)
            elif isinstance(x, jaxpr_cls):
                found.append(x)

        for value in params.values():
            maybe(value)
            if isinstance(value, (tuple, list)):
                for item in value:
                    maybe(item)
        return found

    def visit(jaxpr, mult):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            sub_mult = mult
            if name == "scan":
                sub_mult = mult * int(eqn.params.get("length", 1))
            elif name == "while":
                state["exact"] = False
            subs = subjaxprs(eqn.params)
            for sub in subs:
                visit(sub, sub_mult)
            if name in ZERO_FLOP_PRIMS or name in _STRUCTURAL_PRIMS:
                continue
            if subs:
                # unknown higher-order primitive: its cost was counted
                # by the recursion above
                continue
            if name == "dot_general":
                flops = _dot_general_flops(eqn)
            elif name == "conv_general_dilated":
                flops = _conv_flops(eqn)
            else:
                flops = sum(_elems(v.aval) for v in eqn.outvars)
            if flops:
                by_prim[name] = by_prim.get(name, 0) + mult * flops

    visit(closed.jaxpr, 1)
    return {
        "flops": sum(by_prim.values()),
        "by_primitive": dict(sorted(
            by_prim.items(), key=lambda kv: -kv[1])),
        "arg_bytes": sum(aval_bytes(v.aval) for v in closed.jaxpr.invars),
        "out_bytes": sum(aval_bytes(v.aval) for v in closed.jaxpr.outvars),
        "exact": state["exact"],
    }


def trace_flop_stats(fn, *args) -> dict:
    """:func:`closed_jaxpr_flop_stats` via ``jax.make_jaxpr`` - abstract
    trace only, no data and no compile."""
    import jax

    return closed_jaxpr_flop_stats(jax.make_jaxpr(fn)(*args))


def entry_flop_report(entries=None, n_devices: int | None = None) -> list:
    """One FLOP/bytes row per registered abstract trace entry.

    Works over ``lint/trace_registry.py``'s provider modules under a
    virtual CPU mesh, so the whole registry (trainer families, MPMD
    stages, streaming) is costed with no data and no compile.  Entries
    whose mesh needs more devices than the session provides are reported
    with an ``error`` instead of silently dropped.
    """
    from pytorch_distributed_rnn_tpu.lint.trace_registry import (
        LINT_DEVICE_COUNT,
        PROVIDER_MODULES,
        cpu_trace_session,
        load_entries,
    )

    n = n_devices or LINT_DEVICE_COUNT
    rows = []
    with cpu_trace_session(n):
        for entry in (entries if entries is not None
                      else load_entries(PROVIDER_MODULES)):
            row = {"name": entry.name, "family": entry.family,
                   "kind": entry.kind}
            try:
                fn, args = entry.build()
                stats = trace_flop_stats(fn, *args)
            except Exception as exc:  # noqa: BLE001 - report, don't abort
                row["error"] = f"{type(exc).__name__}: {exc}"
            else:
                row.update(
                    flops_per_call=stats["flops"],
                    arg_bytes=stats["arg_bytes"],
                    out_bytes=stats["out_bytes"],
                    exact=stats["exact"],
                )
            rows.append(row)
    return rows
