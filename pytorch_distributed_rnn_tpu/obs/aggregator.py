"""Fleet aggregator: digest ingestion, live health, Prometheus export.

The rank-0/master half of the live plane (``obs/live.py``): every
process pushes periodic JSON digests here; the aggregator keeps the
latest digest per source, classifies liveness with the SAME semantics
as the post-hoc classifier (``obs/summary.rank_health``: ok / stalled /
dead / drained / finished - here on live digests instead of sidecar
event streams), detects fleet-level stragglers, and serves it all over
a tiny stdlib HTTP server:

- ``GET /metrics`` - Prometheus text exposition (version 0.0.4).
  Counters are PROCESS-cumulative values carried in the digests
  (``*_total``), so an aggregator restart reports the same counter
  values the moment digests arrive again - monotonicity survives the
  restart because the aggregator never owns a counter.  Gauges with
  NaN/Inf values are dropped from the exposition (Prometheus ingests
  NaN as a real sample that poisons aggregation).  Label values are
  escaped per the exposition spec (backslash, double-quote, newline).
- ``GET /health`` - per-source status JSON; HTTP 200 when every source
  is ok/finished/drained, 503 when any is stalled/dead (probe-able).
- ``GET /events`` - recent alerts (watchdog + fleet), newest last.
- ``GET /fleet`` - the raw digest table (what ``pdrnn-metrics watch``
  renders).
- ``GET /series?name=...&window=...`` - downsampled history from the
  bound time-series store (``obs/store.py``; 404 when none is bound).
  Optional ``agg`` (gauge ``min|mean|max|last``, counter
  ``rate|increase``, histogram ``p50|p95|p99|count``) and any other
  query key as a label filter.  Without ``name``: the series catalog.
- ``POST /push`` - digest ingestion.  When a store is bound, every
  ingested digest also feeds it (on this handler thread / the anchor's
  writer thread - the store never runs a thread of its own).

Prometheus metric names (documented next to the sidecar event schema in
``obs/recorder.py``; labels ``rank``/``role`` on all per-source series):

=============================================== ============ ==========
name                                            type         source
=============================================== ============ ==========
pdrnn_up                                        gauge        freshness
pdrnn_last_push_age_seconds                     gauge        aggregator
pdrnn_progress_age_seconds                      gauge        digest
pdrnn_steps_total                               counter      digest
pdrnn_step_seconds{quantile="0.5"|"0.95"}       gauge        window
pdrnn_step_seconds_mean                         gauge        window
pdrnn_loss                                      gauge        window
pdrnn_data_wait_seconds_mean                    gauge        window
pdrnn_queue_depth                               gauge        window
pdrnn_goodput                                   gauge        window
pdrnn_mfu                                       gauge        window
pdrnn_nan_skips_total                           counter      digest
pdrnn_faults_total{action=...}                  counter      digest
pdrnn_alerts_total                              counter      digest
pdrnn_serving_requests_total                    counter      engine
pdrnn_serving_requests_shed_total               counter      engine
pdrnn_serving_requests_failed_total             counter      engine
pdrnn_serving_tokens_total                      counter      engine
pdrnn_serving_request_rate_per_s                gauge        window
pdrnn_serving_tokens_rate_per_s                 gauge        window
pdrnn_serving_shed_rate_per_s                   gauge        window
pdrnn_serving_latency_seconds{quantile=...}     gauge        window
pdrnn_serving_ttft_seconds{quantile=...}        gauge        window
pdrnn_router_inflight                           gauge        router
pdrnn_router_replicas{state=...}                gauge        router
pdrnn_router_routed_total                       counter      router
pdrnn_router_rerouted_total                     counter      router
pdrnn_router_retries_total                      counter      router
pdrnn_router_hedges_total                       counter      router
pdrnn_router_hedge_wins_total                   counter      router
pdrnn_router_shed_total{qos=...}                counter      router
pdrnn_router_errors_total                       counter      router
pdrnn_router_request_rate_per_s                 gauge        window
pdrnn_router_latency_seconds{quantile=...}      gauge        window
pdrnn_request_latency_seconds{le=...}           histogram    histogram
pdrnn_slot_utilization{source=...}              gauge        store
pdrnn_queue_growth_per_s{source=...}            gauge        store
pdrnn_goodput_headroom{source=...}              gauge        store
pdrnn_replicas_live                             gauge        store
pdrnn_recommended_replicas                      gauge        store
pdrnn_slo_burn_rate{qos=...,window=...}         gauge        store
=============================================== ============ ==========

The ``store``-sourced series (capacity + SLO burn; present only when a
time-series store is bound, i.e. on the live-plane anchor) are derived
history, not digest pass-throughs: ``pdrnn_slot_utilization`` is
``active / num_slots`` per serving source; ``pdrnn_queue_growth_per_s``
is the gap-safe queue-depth slope (never computed across a paused
digest stream); ``pdrnn_goodput_headroom`` estimates spare tokens/s
from the peak observed rate times the free slot fraction;
``pdrnn_replicas_live`` / ``pdrnn_recommended_replicas`` are the fleet
liveness count and the advisory scale target (demand over per-replica
capacity at the target utilization); ``pdrnn_slo_burn_rate`` is the
error-budget burn per ``--slo`` objective and burn window (label
``window`` in seconds, e.g. ``"300"``/``"3600"``).  The same numbers
are queryable with history via ``GET /series`` and rendered by
``pdrnn-metrics top``.

``pdrnn_request_latency_seconds`` is the request-latency histogram
(``obs/live.LatencyHistogram``): the serving engine and the router each
carry one in their digests, exported as cumulative ``_bucket{le=...}``
series plus ``_sum``/``_count``, distinguished by the ``role`` label.
Buckets that last saw a TRACED request carry an OpenMetrics-style
exemplar suffix (``# {trace_id="..."} value timestamp``) so a latency
spike on a dashboard links straight to ``pdrnn-metrics trace --request``
on that trace id.  Prometheus's classic text parser ignores everything
after ``#``, so the suffix is backward-compatible noise to a 0.0.4
scraper and an exemplar to an OpenMetrics one.
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from pytorch_distributed_rnn_tpu.utils import threadcheck

log = logging.getLogger(__name__)

_DEFAULT_STALE_AFTER_S = 5.0
_EVENTS_MAXLEN = 512
_STRAGGLER_FRAC = 0.5
_STRAGGLER_MIN_SAMPLES = 4

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

HEALTHY_STATUSES = ("ok", "finished", "drained")


def escape_label_value(value) -> str:
    """Prometheus exposition label-value escaping: backslash first, then
    double-quote and newline (the spec's three escapes)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _render_value(value: float) -> str:
    # integers render without a fraction (counter idiom); floats use
    # repr for round-trip fidelity
    if value == int(value) and abs(value) < 2 ** 53:
        return str(int(value))
    return repr(value)


def _render_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _exemplar_suffix(exemplar: dict) -> str:
    """OpenMetrics exemplar: `` # {trace_id="..."} value timestamp``.
    A classic text-format parser stops at the ``#`` (comment), so the
    suffix degrades to nothing on scrapers that predate exemplars."""
    trace_id = exemplar.get("trace_id")
    value = exemplar.get("value")
    if trace_id is None or value is None:
        return ""
    suffix = (
        f' # {{trace_id="{escape_label_value(trace_id)}"}} '
        f"{_render_value(float(value))}"
    )
    if exemplar.get("t") is not None:
        suffix += f" {_render_value(float(exemplar['t']))}"
    return suffix


def _histogram_lines(name: str, labels: dict, snapshot: dict) -> list[str]:
    """One ``LatencyHistogram.snapshot()`` as exposition lines: the
    cumulative ``_bucket{le=...}`` series (finite buckets carry their
    exemplar when one was observed), the spec-mandated ``+Inf`` bucket,
    then ``_sum`` and ``_count``."""
    lines = []
    for bucket in snapshot.get("buckets") or ():
        line = (
            f"{name}_bucket"
            f'{_render_labels({**labels, "le": format(float(bucket["le"]), "g")})}'
            f" {int(bucket['count'])}"
        )
        exemplar = bucket.get("exemplar")
        if exemplar:
            line += _exemplar_suffix(exemplar)
        lines.append(line)
    lines.append(
        f'{name}_bucket{_render_labels({**labels, "le": "+Inf"})} '
        f"{int(snapshot['count'])}"
    )
    lines.append(
        f"{name}_sum{_render_labels(labels)} "
        f"{_render_value(float(snapshot['sum']))}"
    )
    lines.append(
        f"{name}_count{_render_labels(labels)} {int(snapshot['count'])}"
    )
    return lines


def render_prometheus(samples) -> str:
    """``[(name, labels-dict, value, type), ...]`` -> exposition text.

    Groups samples by metric name (one ``# TYPE`` line per name, first
    occurrence's type wins), escapes label values, and DROPS any sample
    whose value is not finite - a NaN gauge poisons every downstream
    ``avg()``/``sum()``, and absence is the Prometheus idiom for "no
    observation".  A sample whose type is ``"histogram"`` carries a
    ``LatencyHistogram.snapshot()`` dict as its value and expands into
    the ``_bucket``/``_sum``/``_count`` series under one ``# TYPE``
    line, with per-bucket exemplars when present."""
    by_name: dict[str, tuple[str, list[str]]] = {}
    order: list[str] = []

    def series_for(name: str, mtype: str) -> list[str]:
        if name not in by_name:
            by_name[name] = (mtype, [])
            order.append(name)
        return by_name[name][1]

    for name, labels, value, mtype in samples:
        if mtype == "histogram":
            if not isinstance(value, dict) or value.get("count") is None:
                continue
            series_for(name, mtype).extend(
                _histogram_lines(name, labels or {}, value)
            )
            continue
        try:
            value = float(value)
        except (TypeError, ValueError):
            continue
        if not math.isfinite(value):
            continue
        series_for(name, mtype).append(
            f"{name}{_render_labels(labels or {})} {_render_value(value)}"
        )
    lines = []
    for name in order:
        mtype, series = by_name[name]
        lines.append(f"# TYPE {name} {mtype}")
        lines.extend(series)
    return "\n".join(lines) + ("\n" if lines else "")


class Aggregator:
    """Latest-digest-per-source fleet state + alert ring."""

    def __init__(self, *, stale_after_s: float = _DEFAULT_STALE_AFTER_S,
                 stall_after_s: float = 10.0,
                 straggler_frac: float = _STRAGGLER_FRAC,
                 recorder=None, events_maxlen: int = _EVENTS_MAXLEN,
                 store=None):
        self.stale_after_s = float(stale_after_s)
        self.stall_after_s = float(stall_after_s)
        self.straggler_frac = float(straggler_frac)
        # the master/rank-0 recorder: fleet-level findings (stragglers)
        # are recorded as ``alert`` events into ITS sidecar, marked
        # fleet=True so the local exporter does not echo them back
        self.recorder = recorder
        # optional time-series store (obs/store.py): fed from ingest on
        # the pushing thread, queried by /series and /metrics; None
        # keeps the aggregator history-free (the pre-store behavior)
        self.store = store
        self._lock = threadcheck.lock(threading.Lock(), "aggregator.fleet")  # guards: _peers, _events, _seen_alert_seq, _peer_pids, _straggling, _fleet_seq
        self._peers: dict[str, dict] = {}  # id -> {digest, received_tm}
        self._events: deque[dict] = deque(maxlen=int(events_maxlen))
        self._seen_alert_seq: dict[str, int] = {}
        # pid per source: a RESPAWNED worker keeps its id but restarts
        # its watchdog's alert seq at 1 - the dedupe watermark must
        # reset with the incarnation or the new process's alerts are
        # silently dropped until they pass the dead one's high water
        self._peer_pids: dict[str, object] = {}
        self._straggling: set[str] = set()
        self._fleet_seq = 0

    # -- ingestion -----------------------------------------------------------

    def ingest(self, digest: dict) -> None:
        if not isinstance(digest, dict) or not digest.get("id"):
            raise ValueError("digest must be a dict with an 'id'")
        now = time.perf_counter()
        source = str(digest["id"])
        with self._lock:
            pid = digest.get("pid")
            if pid is not None and self._peer_pids.get(source, pid) != pid:
                # new incarnation under the same id: fresh seq space
                self._seen_alert_seq.pop(source, None)
            if pid is not None:
                self._peer_pids[source] = pid
            self._peers[source] = {"digest": digest, "received_tm": now}
            for alert in digest.get("alerts") or []:
                self._note_alert_locked(alert, source)
        # feed the store OUTSIDE the fleet lock (lock order: the two are
        # never held together) with the aggregator's OWN receive stamp -
        # digest-carried tm is another process's perf_counter epoch
        if self.store is not None:
            try:
                self.store.ingest(digest, now)
            except Exception:  # pragma: no cover - history must not
                log.exception("store: ingest failed")  # kill ingestion
        self._check_stragglers(now)

    def note_alert(self, alert: dict, source: str = "fleet") -> None:
        with self._lock:
            self._note_alert_locked(alert, source)

    def peek(self, source_id: str) -> dict | None:
        """Latest digest pushed by ``source_id`` (None when unseen).
        The fleet router's load-hint read path: replica digests double
        as the load signal (``serving.active + queue_depth``), so
        least-loaded dispatch needs no second telemetry channel."""
        with self._lock:
            entry = self._peers.get(str(source_id))
            return None if entry is None else entry["digest"]

    def _note_alert_locked(self, alert: dict, source: str) -> None:
        seq = alert.get("seq")
        if seq is not None:
            # (source, seq) dedupe: digests re-carry their recent-alert
            # ring on every push
            if self._seen_alert_seq.get(source, -1) >= int(seq):
                return
            self._seen_alert_seq[source] = int(seq)
        self._events.append({"source": source, **alert})

    # -- fleet-level checks --------------------------------------------------

    def _check_stragglers(self, now: float) -> None:
        """Live straggler detection across the fleet's step windows: a
        source whose window-mean step time exceeds the fleet median by
        ``straggler_frac`` is flagged once per episode (re-armed when it
        returns under), with the finding recorded as a fleet ``alert``
        event on the master recorder when one is bound."""
        import statistics

        # episode latch + fleet seq mutate UNDER the lock (concurrent
        # /push handler threads race this check; an unguarded latch can
        # double-flag or mint duplicate seqs the dedupe then drops);
        # alert emission happens outside it - note_alert re-takes the
        # lock and the master recorder does file I/O
        pending: list[dict] = []
        with self._lock:
            timed = [
                (pid, entry["digest"]["step_s"]["mean"])
                for pid, entry in self._peers.items()
                if isinstance(entry["digest"].get("step_s"), dict)
                and entry["digest"]["step_s"].get("mean") is not None
                and entry["digest"]["step_s"].get(
                    "count", 0) >= _STRAGGLER_MIN_SAMPLES
            ]
            if len(timed) < 2:
                return
            # true median (interpolated for even fleets): with 2 peers a
            # nearest-rank median would EQUAL the slow peer and no
            # straggler could ever be flagged
            median = statistics.median(m for _, m in timed)
            if median <= 0:
                return
            for pid, mean in timed:
                excess = mean / median - 1.0
                if excess > self.straggler_frac \
                        and pid not in self._straggling:
                    self._straggling.add(pid)
                    self._fleet_seq += 1
                    pending.append({
                        "alert": "straggler", "severity": "warning",
                        "seq": self._fleet_seq, "t": time.time(),
                        "peer": pid, "step_s_mean": mean,
                        "median_s": median, "excess_frac": excess,
                    })
                elif excess <= self.straggler_frac:
                    self._straggling.discard(pid)
        for alert in pending:
            self.note_alert(alert, source="fleet")
            if self.recorder is not None and self.recorder.enabled:
                self.recorder.record("alert", fleet=True, **alert)

    # -- views ---------------------------------------------------------------

    def _status(self, digest: dict, age_s: float,
                drained_slots: set[int]) -> str:
        if digest.get("drained"):
            # a voluntary leave (``LiveExporter.note_drained`` - the
            # SIGTERM drain of a serving replica) beats everything:
            # fresh while it finishes in-flight work, stale after it
            # exits - never "dead", and not "finished" either (the
            # router pool cares that it LEFT, not that it completed)
            return "drained"
        if digest.get("finished"):
            return "finished"
        if age_s > self.stale_after_s:
            rank = digest.get("rank")
            if rank is not None and int(rank) in drained_slots:
                return "drained"
            return "dead"
        progress_age = digest.get("progress_age_s")
        if progress_age is not None and progress_age > self.stall_after_s:
            # an IDLE serving engine is not stalled (the shared
            # predicate - obs/live.serving_idle - so this classifier
            # and the in-process watchdog can never disagree)
            from pytorch_distributed_rnn_tpu.obs.live import serving_idle

            if serving_idle(digest.get("serving")):
                return "ok"
            return "stalled"
        return "ok"

    def health(self, now: float | None = None) -> dict:
        """Per-source liveness with the sidecar classifier's vocabulary,
        on live digests: ``finished`` beats everything, a stale source
        whose rank the roster drained is ``drained`` (voluntary leave),
        stale otherwise is ``dead``, fresh-but-frozen ``progress_age_s``
        is ``stalled``."""
        now = time.perf_counter() if now is None else now
        with self._lock:
            peers = {
                pid: (dict(entry["digest"]), now - entry["received_tm"])
                for pid, entry in self._peers.items()
            }
        # the union of every source's drained slots: the master's digest
        # carries the roster story for workers that stopped pushing
        drained_slots: set[int] = set()
        roster = None
        for digest, _ in peers.values():
            drained_slots.update(digest.get("drained_slots") or ())
            if digest.get("roster") is not None:
                roster = digest["roster"]
        sources = []
        for pid, (digest, age_s) in sorted(peers.items()):
            if digest.get("ephemeral"):
                # event-only pushers (the supervisor): alerts and
                # metrics count, liveness does not - they push when
                # something happens, not on a cadence
                continue
            sources.append({
                "id": pid,
                "role": digest.get("role"),
                "rank": digest.get("rank"),
                "status": self._status(digest, age_s, drained_slots),
                "last_push_age_s": age_s,
                "progress": digest.get("progress"),
                "progress_age_s": digest.get("progress_age_s"),
            })
        ok = all(s["status"] in HEALTHY_STATUSES for s in sources)
        report = {"ok": ok, "sources": sources}
        if roster is not None:
            report["roster"] = roster
        return report

    def fleet(self, now: float | None = None) -> dict:
        """The digest table + statuses (the ``watch`` CLI's payload)."""
        health = {s["id"]: s for s in self.health(now)["sources"]}
        with self._lock:
            peers = {
                pid: dict(entry["digest"])
                for pid, entry in self._peers.items()
            }
        for pid, digest in peers.items():
            if digest.get("ephemeral"):
                # event-only pushers carry alerts, not liveness
                digest["status"] = "events"
                continue
            digest["status"] = health.get(pid, {}).get("status")
            digest["last_push_age_s"] = health.get(pid, {}).get(
                "last_push_age_s"
            )
        return {"sources": peers}

    def events(self, limit: int = 100) -> list[dict]:
        with self._lock:
            items = list(self._events)
        return items[-int(limit):]

    # -- Prometheus ----------------------------------------------------------

    def prometheus_text(self, now: float | None = None) -> str:
        now = time.perf_counter() if now is None else now
        health = {s["id"]: s for s in self.health(now)["sources"]}
        with self._lock:
            peers = [
                (pid, dict(entry["digest"]), now - entry["received_tm"])
                for pid, entry in sorted(self._peers.items())
            ]
        samples: list = []

        def add(name, labels, value, mtype="gauge"):
            if value is None:
                return
            samples.append((name, labels, value, mtype))

        for pid, digest, age_s in peers:
            labels = {
                "rank": digest.get("rank", ""),
                "role": digest.get("role", ""),
            }
            if digest.get("ephemeral"):
                # event-only pushers (the supervisor) have no liveness
                # story: exporting pdrnn_up 0 forever would fire every
                # min(pdrnn_up) alerting rule over nothing - only their
                # counters are real
                add("pdrnn_alerts_total", labels,
                    digest.get("alerts_total"), "counter")
                continue
            status = health.get(pid, {}).get("status")
            add("pdrnn_up", labels,
                1 if status in ("ok", "stalled") else 0)
            add("pdrnn_last_push_age_seconds", labels, age_s)
            add("pdrnn_progress_age_seconds", labels,
                digest.get("progress_age_s"))
            add("pdrnn_steps_total", labels, digest.get("steps_total"),
                "counter")
            step = digest.get("step_s") or {}
            add("pdrnn_step_seconds", {**labels, "quantile": "0.5"},
                step.get("p50"))
            add("pdrnn_step_seconds", {**labels, "quantile": "0.95"},
                step.get("p95"))
            add("pdrnn_step_seconds_mean", labels, step.get("mean"))
            loss = digest.get("loss") or {}
            add("pdrnn_loss", labels, loss.get("last"))
            add("pdrnn_data_wait_seconds_mean", labels,
                digest.get("data_wait_s_mean"))
            depth = digest.get("queue_depth") or {}
            add("pdrnn_queue_depth", labels, depth.get("last"))
            # efficiency-ledger live gauges (obs/ledger.py is the
            # post-hoc source of truth; these are windowed estimates)
            add("pdrnn_goodput", labels, digest.get("goodput_60s"))
            add("pdrnn_mfu", labels, digest.get("mfu_60s"))
            add("pdrnn_nan_skips_total", labels,
                digest.get("nan_skips_total"), "counter")
            for action, count in (digest.get("faults_total") or {}).items():
                add("pdrnn_faults_total", {**labels, "action": action},
                    count, "counter")
            add("pdrnn_alerts_total", labels, digest.get("alerts_total"),
                "counter")
            serving = digest.get("serving") or {}
            add("pdrnn_serving_requests_total", labels,
                serving.get("requests"), "counter")
            add("pdrnn_serving_requests_shed_total", labels,
                serving.get("requests_shed"), "counter")
            add("pdrnn_serving_requests_failed_total", labels,
                serving.get("requests_failed"), "counter")
            add("pdrnn_serving_tokens_total", labels,
                serving.get("tokens_out"), "counter")
            add("pdrnn_serving_request_rate_per_s", labels,
                serving.get("req_per_s_60s"))
            add("pdrnn_serving_tokens_rate_per_s", labels,
                serving.get("tokens_per_s_60s"))
            add("pdrnn_serving_shed_rate_per_s", labels,
                serving.get("shed_per_s_60s"))
            for q, key in (("0.5", "latency_s_p50"), ("0.95",
                                                     "latency_s_p95")):
                add("pdrnn_serving_latency_seconds",
                    {**labels, "quantile": q}, serving.get(key))
            for q, key in (("0.5", "ttft_s_p50"), ("0.95", "ttft_s_p95")):
                add("pdrnn_serving_ttft_seconds",
                    {**labels, "quantile": q}, serving.get(key))
            add("pdrnn_request_latency_seconds", labels,
                serving.get("latency_hist"), "histogram")
            router = digest.get("router") or {}
            add("pdrnn_router_inflight", labels, router.get("inflight"))
            for state, count in (router.get("replicas") or {}).items():
                add("pdrnn_router_replicas", {**labels, "state": state},
                    count)
            add("pdrnn_router_routed_total", labels, router.get("routed"),
                "counter")
            add("pdrnn_router_rerouted_total", labels,
                router.get("rerouted"), "counter")
            add("pdrnn_router_retries_total", labels,
                router.get("retries"), "counter")
            add("pdrnn_router_hedges_total", labels, router.get("hedges"),
                "counter")
            add("pdrnn_router_hedge_wins_total", labels,
                router.get("hedge_wins"), "counter")
            for qos, count in (router.get("shed") or {}).items():
                add("pdrnn_router_shed_total", {**labels, "qos": qos},
                    count, "counter")
            add("pdrnn_router_errors_total", labels, router.get("errors"),
                "counter")
            add("pdrnn_router_request_rate_per_s", labels,
                router.get("req_per_s_60s"))
            for q, key in (("0.5", "latency_s_p50"), ("0.95",
                                                     "latency_s_p95")):
                add("pdrnn_router_latency_seconds",
                    {**labels, "quantile": q}, router.get(key))
            add("pdrnn_request_latency_seconds", labels,
                router.get("latency_hist"), "histogram")
        if self.store is not None:
            # capacity + burn gauges (store-derived; see the registry
            # table above) join the exposition under the same render
            samples.extend(self.store.prometheus_samples(now))
        return render_prometheus(samples)

    def series(self, name: str | None = None,
               labels: dict | None = None, *, window: float = 60.0,
               agg: str | None = None) -> dict | list | None:
        """``GET /series`` body: the store's downsampled history for
        ``name`` (catalog when None); None when no store is bound."""
        if self.store is None:
            return None
        if not name:
            return self.store.series_names()
        return self.store.query(name, labels, window=window, agg=agg)


class AggregatorServer:
    """Threaded stdlib HTTP front end for one :class:`Aggregator`."""

    def __init__(self, aggregator: Aggregator, host: str = "127.0.0.1",
                 port: int = 0):
        self.aggregator = aggregator
        handler = _make_handler(aggregator)
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        # the listener deliberately outlives the server drain boundary
        # (the CLI mains close the plane AFTER shutdown so the final
        # flushed digest stays scrape-able) - exempt it from the leak
        # sentinel like the sigusr2 dump sink; lazy import: leakcheck's
        # violation path reaches back into obs
        from pytorch_distributed_rnn_tpu.utils import leakcheck
        leakcheck.adopt(self._httpd.socket,
                        reason="live-plane listener, closed post-drain")
        self._thread = threading.Thread(
            # 0.1s shutdown poll: close() returns promptly (the default
            # 0.5s poll costs half a second per server teardown)
            target=lambda: self._httpd.serve_forever(poll_interval=0.1),
            name="pdrnn-live-http", daemon=True,
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:  # pragma: no cover - already closed
            pass
        self._thread.join(timeout=5.0)


def _make_handler(aggregator: Aggregator):
    class Handler(BaseHTTPRequestHandler):
        # live telemetry must not spam stderr per scrape
        def log_message(self, fmt, *args):  # noqa: D102
            log.debug("live-http: " + fmt % args)

        def _reply(self, code: int, body: bytes, content_type: str):
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _reply_json(self, payload, code: int = 200):
            body = json.dumps(payload, default=str).encode()
            self._reply(code, body, "application/json")

        def _series(self):
            if aggregator.store is None:
                self._reply_json(
                    {"error": "no time-series store bound "
                              "(not the live-plane anchor?)"}, 404)
                return
            from urllib.parse import parse_qsl, urlsplit

            params = dict(parse_qsl(urlsplit(self.path).query))
            name = params.pop("name", None)
            try:
                window = float(params.pop("window", 60.0))
                agg = params.pop("agg", None) or None
                # every remaining query key is a label filter
                body = aggregator.series(
                    name, params or None, window=window, agg=agg)
            except ValueError as exc:
                self._reply_json({"error": str(exc)}, 400)
                return
            self._reply_json(body)

        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
            path = self.path.split("?", 1)[0].rstrip("/") or "/"
            try:
                if path == "/metrics":
                    self._reply(200, aggregator.prometheus_text().encode(),
                                PROMETHEUS_CONTENT_TYPE)
                elif path == "/health":
                    report = aggregator.health()
                    self._reply_json(report,
                                     200 if report["ok"] else 503)
                elif path == "/events":
                    self._reply_json(aggregator.events())
                elif path == "/fleet":
                    self._reply_json(aggregator.fleet())
                elif path == "/series":
                    self._series()
                else:
                    self._reply_json({"error": f"unknown path {path}"}, 404)
            except BrokenPipeError:  # scraper went away mid-reply
                pass

        def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler API
            path = self.path.split("?", 1)[0].rstrip("/")
            if path != "/push":
                self._reply_json({"error": f"unknown path {path}"}, 404)
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                digest = json.loads(self.rfile.read(length) or b"{}")
                aggregator.ingest(digest)
            except (ValueError, TypeError) as exc:
                self._reply_json({"error": str(exc)}, 400)
                return
            self._reply_json({"ok": True}, 200)

    return Handler
