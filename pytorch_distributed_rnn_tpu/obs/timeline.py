"""Cross-rank trace timelines: merge, align, export, attribute.

The metrics sidecars (``obs/recorder.py``) are per-rank JSONL streams
whose events carry dual stamps - wall ``t`` and monotonic ``tm`` - but
each rank's monotonic clock has its own epoch and each rank's wall
clock its own NTP fate.  This module turns one run's sidecar family
into a single timeline:

1. :func:`load_run` - the rank-0 file plus its ``-r<k>`` siblings,
   loaded with the strict reader;
2. :func:`estimate_clock_offsets` - per-rank corrections onto the
   reference rank's wall timeline.  The base estimate is each rank's
   meta anchor (the (t, tm) pair stamped at recorder construction);
   known-synchronous events then refine away wall-clock skew:
   collective-traced step boundaries (ranks whose step program carries
   real collective traffic finish step k together) and parameter-server
   gather edges (a worker's push reply cannot land before the master
   closed the round that consumed it);
3. :func:`build_chrome_trace` - a Chrome trace-event JSON (one ``pid``
   per rank, one ``tid`` per subsystem, µs units) that Perfetto and
   ``chrome://tracing`` load directly.  Span events export verbatim;
   events that carry a duration (``step`` dispatch/fence/data-wait,
   ``checkpoint_*`` seconds, ``ps_exchange`` seconds, ``epoch`` wall_s,
   ``run_summary`` duration_s) are synthesized into spans; the rest
   become instants.  Request-trace spans (``cat="trace"``, carrying a
   ``trace`` id from ``obs/tracectx.py``) are the exception: concurrent
   requests overlap freely on one row, so they export as ASYNC begin/end
   pairs (``ph: b/e`` keyed by trace id) on the ``trace`` lane, and every
   trace that crosses a process boundary gets a flow arrow (``ph: s/f``)
   from the pid that started it to each pid it visited;
4. :func:`validate_chrome_trace` - the strict structural validator the
   tests and the CI smoke step run on every exported trace;
5. :func:`attribute_rank` / :func:`attribute_stragglers` - per-rank
   phase attribution: sampled (fenced) step time decomposed into
   data-wait / dispatch / device / exchange fractions that sum to ~1,
   and straggler attribution naming the PHASE a slow rank lost its
   time in (upgrading the mean-step-time check of ``pdrnn-metrics
   stragglers``).

Timeline export needs schema >= 2 sidecars (the ``tm`` field);
attribution works on schema 1 too (durations only, no clock math).
"""

from __future__ import annotations

import json
import statistics
from pathlib import Path

from pytorch_distributed_rnn_tpu.obs.spans import SUBSYSTEM_TIDS
from pytorch_distributed_rnn_tpu.obs.summary import (
    MalformedMetricsError,
    load_events,
    rank_files,
)

_US = 1_000_000.0

# event kinds rendered as instants (everything not a span / synthesized
# span / skipped meta); faults and member deaths are process-scoped so
# they flash across the whole rank row in Perfetto
_INSTANT_PROCESS_SCOPE = {"fault", "ps_worker_dead", "member_dead"}


def load_run(path) -> dict[int, list[dict]]:
    """One run's events, keyed by rank (rank-0 file + ``-r<k>``
    siblings; duplicate rank declarations are a malformed family)."""
    files = rank_files(path)
    if not files:
        raise MalformedMetricsError(f"{path}: no metrics sidecar found")
    by_rank: dict[int, list[dict]] = {}
    for p in files:
        events = load_events(p)
        rank = int(events[0].get("rank", 0))
        if rank in by_rank:
            raise MalformedMetricsError(
                f"{p}: rank {rank} declared by two sidecars of one family"
            )
        by_rank[rank] = events
    return by_rank


def _meta_anchor(events: list[dict], what: str) -> float:
    """The rank's wall<->monotonic anchor (meta ``t - tm``)."""
    meta = events[0]
    if "tm" not in meta:
        raise MalformedMetricsError(
            f"{what}: schema {meta.get('schema')} sidecar carries no "
            "monotonic timestamps - timeline export needs a schema >= 2 "
            "recording (re-run with the current build)"
        )
    return float(meta["t"]) - float(meta["tm"])


def _aligned(anchor: float, offset: float, tm: float) -> float:
    return anchor + offset + float(tm)


def _collective_sync_ranks(by_rank: dict[int, list[dict]]) -> set[int]:
    """Ranks whose live step program was traced to carry real
    collective traffic: their fenced step boundaries are synchronous
    across the world (the program cannot finish step k until every
    participant reached its collectives)."""
    ranks = set()
    for rank, events in by_rank.items():
        for e in events:
            if e["kind"] == "collectives" and e.get("ops") and (
                e.get("bytes_per_step") or 0
            ) > 0:
                ranks.add(rank)
                break
    return ranks


def _fenced_step_ends(events: list[dict]) -> dict[int, float]:
    """step index -> monotonic END of the fenced (honest wall) steps."""
    ends = {}
    for e in events:
        if e["kind"] == "step" and e.get("fenced_s") is not None \
                and "tm" in e:
            ends[int(e.get("step", -1))] = float(e["tm"]) + float(
                e["fenced_s"]
            )
    return ends


def _master_rank(by_rank: dict[int, list[dict]]) -> int | None:
    for rank, events in by_rank.items():
        if events[0].get("role") == "master":
            return rank
    return None


def _ps_round_closes(events: list[dict]) -> dict:
    """Master-side round-close edges, keyed two ways: by the consumed
    push id under ``by_seq[(worker, seq)]`` (exact pairing - survives
    degraded rounds and retried pushes, whose ordinals shift), and
    positionally under ``"sync"`` / ``per_worker`` for sidecars whose
    rounds carry no seq ids."""
    sync, per_worker, by_seq = [], {}, {}
    for e in events:
        if e["kind"] == "span" and e.get("name") == "ps_round" \
                and "tm" in e:
            close = float(e["tm"]) + float(e.get("dur_s", 0.0))
            if e.get("mode") == "async":
                worker = int(e.get("worker", -1))
                per_worker.setdefault(worker, []).append(close)
                if e.get("seq") is not None:
                    by_seq[(worker, int(e["seq"]))] = close
            else:
                sync.append(close)
                for worker, seq in (e.get("seqs") or {}).items():
                    by_seq[(int(worker), int(seq))] = close
    return {"sync": sync, "per_worker": per_worker, "by_seq": by_seq}


def _push_ends(events: list[dict]) -> list[tuple[int | None, float]]:
    """Worker-side push-exchange END edges (reply landed), in order:
    ``(seq, end_tm)`` pairs (seq None on pre-seq sidecars)."""
    return [
        (int(e["seq"]) if e.get("seq") is not None else None,
         float(e["tm"]))
        for e in events
        if e["kind"] == "ps_exchange" and e.get("what") == "gradient push"
        and not e.get("failed") and "tm" in e
    ]


def estimate_clock_offsets(by_rank: dict[int, list[dict]]) -> dict[int, float]:
    """Per-rank wall-clock corrections (seconds, ADDED to the meta
    anchor) landing every rank on the reference rank's timeline.

    The meta anchors alone align perfectly when wall clocks agree (the
    single-host spawn worlds); the sync-event refinements below remove
    residual skew when they do not.  Each refinement's per-pair delta is
    reduced by the median, so one straggling sample cannot drag the
    estimate.
    """
    ranks = sorted(by_rank)
    ref = ranks[0]
    anchors = {
        r: _meta_anchor(by_rank[r], f"rank {r}") for r in ranks
    }
    offsets = {r: 0.0 for r in ranks}

    # refinement 1: collective-traced step boundaries.  For every step
    # index fenced on both the reference and rank r, the two ends are
    # the same instant; the median difference is rank r's skew.
    sync_ranks = _collective_sync_ranks(by_rank)
    if ref in sync_ranks:
        ref_ends = _fenced_step_ends(by_rank[ref])
        for r in ranks:
            if r == ref or r not in sync_ranks:
                continue
            ends = _fenced_step_ends(by_rank[r])
            deltas = [
                (anchors[r] + ends[s]) - (anchors[ref] + ref_ends[s])
                for s in ends.keys() & ref_ends.keys()
            ]
            if deltas:
                offsets[r] = -statistics.median(deltas)

    # refinement 2: parameter-server gather edges.  A worker's k-th push
    # reply lands just after the master closed the k-th round (sync
    # mode) / the k-th update for that worker (async mode); the median
    # edge-to-edge delta is the worker's skew plus the typical reply
    # latency - absorbed into the estimate, which is why the tolerance
    # contract is "within transport latency", not zero.
    master = _master_rank(by_rank)
    if master is not None:
        closes = _ps_round_closes(by_rank[master])
        for r in ranks:
            if r == master or offsets[r] != 0.0:
                continue  # collective refinement already placed it
            pushes = _push_ends(by_rank[r])
            if not pushes:
                continue
            # pair by push id where the master recorded which seq each
            # round consumed - exact even when a degraded round or a
            # retried push shifts the ordinals; fall back to positional
            # pairing for sidecars without ids
            paired = [
                (end, closes["by_seq"][(r, seq)])
                for seq, end in pushes
                if seq is not None and (r, seq) in closes["by_seq"]
            ]
            if not paired:
                edges = closes["per_worker"].get(r) or closes["sync"]
                paired = [
                    (pushes[i][1], edges[i])
                    for i in range(min(len(pushes), len(edges)))
                ]
            if not paired:
                continue
            deltas = [
                (anchors[r] + end)
                - (anchors[master] + close + offsets[master])
                for end, close in paired
            ]
            offsets[r] = -statistics.median(deltas)
    return offsets


# -- Chrome trace export -----------------------------------------------------


def _tid(cat: str) -> int:
    return SUBSYSTEM_TIDS.get(cat, SUBSYSTEM_TIDS["train"])


class _TraceBuilder:
    def __init__(self, t0_wall: float):
        self.t0 = t0_wall
        self.events: list[dict] = []
        self.threads: dict[tuple[int, int], str] = {}

    def _us(self, wall: float) -> int:
        return max(0, int(round((wall - self.t0) * _US)))

    def _thread(self, pid: int, cat: str) -> tuple[int, str]:
        """Resolve a cat to its (tid, canonical name): unknown cats
        fall back to the "train" row WHOLE - tid and thread_name
        together - so the export always passes its own validator's
        thread_name<->tid mapping check."""
        canonical = cat if cat in SUBSYSTEM_TIDS else "train"
        tid = SUBSYSTEM_TIDS[canonical]
        self.threads[(pid, tid)] = canonical
        return tid, canonical

    def span(self, pid: int, cat: str, name: str, wall_start: float,
             dur_s: float, args: dict) -> dict:
        tid, cat = self._thread(pid, cat)
        ts = self._us(wall_start)
        # the child-end clamp happens in the caller where nesting is
        # known; here dur only needs non-negativity after rounding
        event = {
            "ph": "X", "pid": pid, "tid": tid, "name": name, "cat": cat,
            "ts": ts, "dur": max(0, int(round(dur_s * _US))),
            "args": args,
        }
        self.events.append(event)
        return event

    def instant(self, pid: int, cat: str, name: str, wall: float,
                args: dict, scope: str = "t") -> None:
        tid, cat = self._thread(pid, cat)
        self.events.append({
            "ph": "i", "pid": pid, "tid": tid, "name": name, "cat": cat,
            "ts": self._us(wall), "s": scope, "args": args,
        })

    def async_span(self, pid: int, cat: str, name: str, span_id: str,
                   wall_start: float, dur_s: float, args: dict) -> int:
        """One async begin/end pair (``ph: b``/``e``): the export shape
        for request-trace spans, whose concurrent instances overlap
        arbitrarily on one lane - complete events (``X``) would trip the
        validator's nesting check.  Returns the begin ts (µs)."""
        tid, cat = self._thread(pid, cat)
        ts = self._us(wall_start)
        end = ts + max(0, int(round(dur_s * _US)))
        common = {
            "pid": pid, "tid": tid, "name": name, "cat": cat,
            "id": span_id,
        }
        self.events.append({"ph": "b", "ts": ts, "args": args, **common})
        self.events.append({"ph": "e", "ts": end, "args": {}, **common})
        return ts

    def flow(self, cat: str, name: str, flow_id: str,
             src: tuple[int, int], dst: tuple[int, int]) -> None:
        """One flow arrow: ``ph: s`` at ``src=(pid, ts)`` binding to
        ``ph: f`` at ``dst=(pid, ts)``, both on ``cat``'s lane.  The
        finish is clamped to never precede its start (cross-host clock
        skew up to the alignment tolerance)."""
        src_pid, src_ts = src
        dst_pid, dst_ts = dst
        src_tid, cat = self._thread(src_pid, cat)
        dst_tid, _ = self._thread(dst_pid, cat)
        common = {"name": name, "cat": cat, "id": flow_id}
        self.events.append({
            "ph": "s", "pid": src_pid, "tid": src_tid, "ts": src_ts,
            **common,
        })
        self.events.append({
            "ph": "f", "bp": "e", "pid": dst_pid, "tid": dst_tid,
            "ts": max(dst_ts, src_ts), **common,
        })


def _args(event: dict, *skip: str) -> dict:
    drop = {"kind", "t", "tm", "rank", *skip}
    return {
        k: v for k, v in event.items()
        if k not in drop and v is not None
    }


def build_chrome_trace(by_rank: dict[int, list[dict]],
                       offsets: dict[int, float] | None = None) -> dict:
    """The run as a Chrome trace-event JSON object (µs units): one pid
    per rank, one tid per subsystem, clock-aligned via ``offsets``
    (estimated when not given)."""
    if offsets is None:
        offsets = estimate_clock_offsets(by_rank)
    anchors = {
        r: _meta_anchor(events, f"rank {r}")
        for r, events in by_rank.items()
    }

    def wall(rank: int, event: dict) -> float:
        if "tm" in event:
            return _aligned(anchors[rank], offsets[rank], event["tm"])
        # wall-only events (the launcher's appended root span) already
        # live on the launching host's wall clock = the common timeline
        return float(event["t"])

    t0 = min(
        wall(r, e) - float(e.get("data_wait_s") or 0.0)
        for r, events in by_rank.items() for e in events
    )
    tb = _TraceBuilder(t0)
    # trace id -> [(begin ts µs, pid)]: the visits each request trace
    # paid to each process, feeding the flow-arrow synthesis below
    trace_visits: dict[str, list[tuple[int, int]]] = {}

    for rank, events in by_rank.items():
        for e in events:
            kind = e["kind"]
            w = wall(rank, e)
            if kind == "meta":
                continue
            if kind == "span":
                if e.get("cat") == "trace" and e.get("trace"):
                    ts = tb.async_span(
                        rank, "trace", str(e.get("name", "span")),
                        str(e["trace"]), w, float(e.get("dur_s", 0.0)),
                        _args(e, "name", "cat", "dur_s"),
                    )
                    trace_visits.setdefault(str(e["trace"]), []).append(
                        (ts, rank)
                    )
                    continue
                tb.span(
                    rank, e.get("cat", "train"), str(e.get("name", "span")),
                    w, float(e.get("dur_s", 0.0)),
                    _args(e, "name", "cat", "dur_s"),
                )
            elif kind == "step":
                _step_spans(tb, rank, e, w)
            elif kind == "epoch" and e.get("wall_s") is not None:
                tb.span(rank, "train", "epoch", w, float(e["wall_s"]),
                        _args(e, "wall_s"))
            elif kind in ("checkpoint_save", "checkpoint_restore"):
                # recorded at completion: tm is the END of the write
                dur = float(e.get("seconds", 0.0))
                tb.span(rank, "ckpt", kind, w - dur, dur,
                        _args(e, "seconds"))
            elif kind == "ps_exchange":
                dur = float(e.get("seconds", 0.0))
                tb.span(
                    rank, "ps",
                    str(e.get("what", "exchange")).replace(" ", "_"),
                    w - dur, dur, _args(e, "seconds", "what"),
                )
            elif kind == "run_summary":
                dur = float(e.get("duration_s") or 0.0)
                tb.span(rank, "run", "train_run", w - dur, dur,
                        _args(e, "duration_s", "device_peaks_mb"))
            else:
                # fault / nan_skip / heartbeat / collectives / profile /
                # eval / legacy ps_round points / ps_summary ...
                scope = "p" if kind in _INSTANT_PROCESS_SCOPE else "t"
                cat = {
                    "fault": "resilience", "nan_skip": "resilience",
                    # watchdog findings land on the resilience row next
                    # to the faults they often correlate with
                    "alert": "resilience",
                    "checkpoint_fallback": "ckpt",
                    "heartbeat": "sys", "collectives": "sys",
                    "profile": "sys", "eval": "eval",
                    "ps_round": "ps", "ps_summary": "ps",
                    "ps_worker_dead": "ps",
                    # the membership lane: roster transitions as instants
                    # (state_sync rides in as a span with cat=member)
                    "member_join": "member", "member_drain": "member",
                    "member_dead": "member",
                    # the MPMD pipeline lane: a stage coming back plus
                    # the frames its neighbors replayed to it
                    "stage_restart": "stage", "replay": "stage",
                    "worker_respawn": "stage", "worker_lost": "stage",
                    # the streaming actor lane: ingest verdicts and
                    # param refreshes flash next to the experience_push
                    # / learner_update spans (cat=actor); a reconnect
                    # is a membership story and lands on that row
                    "experience_reject": "actor",
                    "params_refresh": "actor",
                    "actor_reconnect": "member",
                    "learner_summary": "run",
                    # the serving-fleet router lane: breaker transitions
                    # (eject on consecutive failures, half-open probes,
                    # readmission), QoS sheds and the drain marker, next
                    # to the route dispatch spans (cat=router)
                    "replica_eject": "router",
                    "replica_probe": "router",
                    "replica_readmit": "router",
                    "replica_drain": "router",
                    "route_shed": "router",
                    "hedge": "router",
                    "router_drain": "router",
                }.get(kind, "sys")
                tb.instant(rank, cat, kind, w, _args(e), scope)

    # flow arrows: one s->f pair from the pid where a trace BEGAN to
    # each other pid it visited, so Perfetto draws the request's hop
    # across process rows (router -> replica).  The flow id is scoped
    # per destination pid - Chrome flow semantics bind exactly one s to
    # one f per (cat, id)
    for trace_id, visits in sorted(trace_visits.items()):
        visits.sort()
        src_ts, src_pid = visits[0]
        linked = {src_pid}
        for ts, pid in visits:
            if pid in linked:
                continue
            linked.add(pid)
            tb.flow("trace", trace_id, f"{trace_id}/{pid}",
                    (src_pid, src_ts), (pid, ts))

    trace_events = []
    for rank, events in sorted(by_rank.items()):
        role = events[0].get("role")
        name = f"rank {rank}" + (f" ({role})" if role else "")
        trace_events.append({
            "ph": "M", "name": "process_name", "pid": rank, "tid": 0,
            "args": {"name": name},
        })
        trace_events.append({
            "ph": "M", "name": "process_sort_index", "pid": rank, "tid": 0,
            "args": {"sort_index": rank},
        })
    for (pid, tid), cat in sorted(tb.threads.items()):
        trace_events.append({
            "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
            "args": {"name": cat},
        })
        trace_events.append({
            "ph": "M", "name": "thread_sort_index", "pid": pid, "tid": tid,
            "args": {"sort_index": tid},
        })
    trace_events.extend(sorted(tb.events, key=lambda e: e["ts"]))
    return {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {
            "ranks": sorted(by_rank),
            "clock_offsets_s": {str(r): offsets[r] for r in sorted(offsets)},
        },
    }


def _step_spans(tb: _TraceBuilder, rank: int, e: dict, w: float) -> None:
    """Synthesize the per-step sub-spans from one ``step`` event whose
    ``tm`` is the dispatch start: ``data_wait`` (before dispatch, own
    tid), ``dispatch`` and - on fenced samples - the enclosing ``step``
    plus the ``device`` tail.  Child extents are clamped to the parent
    after µs rounding so the nesting the validator enforces is exact by
    construction."""
    if "tm" not in e:
        raise MalformedMetricsError(
            f"rank {rank}: schema-1 step events carry no tm; timeline "
            "export needs a schema >= 2 recording"
        )
    args = _args(e, "dispatch_s", "data_wait_s", "fenced_s")
    data_wait = float(e.get("data_wait_s") or 0.0)
    if data_wait > 0:
        tb.span(rank, "data", "data_wait", w - data_wait, data_wait, args)
    dispatch = float(e.get("dispatch_s") or 0.0)
    fenced = e.get("fenced_s")
    if fenced is None:
        tb.span(rank, "step", "dispatch", w, dispatch, args)
        return
    parent = tb.span(rank, "step", "step", w, float(fenced), args)
    end = parent["ts"] + parent["dur"]
    child = tb.span(rank, "step", "dispatch", w, dispatch, {})
    child["dur"] = min(child["dur"], end - child["ts"])
    dev_ts = child["ts"] + child["dur"]
    tb.events.append({
        "ph": "X", "pid": rank, "tid": _tid("step"), "name": "device",
        "cat": "step", "ts": dev_ts, "dur": max(0, end - dev_ts),
        "args": {},
    })


# -- validator ---------------------------------------------------------------


_REQUIRED_BY_PH = {
    "X": ("ts", "dur", "name", "pid", "tid"),
    "B": ("ts", "name", "pid", "tid"),
    "E": ("ts", "pid", "tid"),
    # async begin/end + flow start/finish (the request-trace export):
    # both are keyed by (cat, id), so those fields are required
    "b": ("ts", "name", "pid", "tid", "cat", "id"),
    "e": ("ts", "name", "pid", "tid", "cat", "id"),
    "s": ("ts", "name", "pid", "tid", "cat", "id"),
    "f": ("ts", "name", "pid", "tid", "cat", "id"),
    "i": ("ts", "name", "pid", "tid", "s"),
    "M": ("name", "pid"),
}


def validate_chrome_trace(trace) -> None:
    """Strict structural check of a Chrome trace-event JSON object;
    raises ``ValueError`` naming the first violation.  Enforced: the
    required fields per phase type, non-negative finite µs timestamps
    and durations, pid<->rank and tid<->subsystem metadata mapping, B/E
    balance per (pid, tid), proper nesting (no partial overlap) of the
    complete-event spans sharing one thread row, async b/e balance per
    (cat, id) with begun/ended name multisets agreeing, and flow-arrow
    pairing: exactly one ``s`` and one ``f`` per (cat, id), same name,
    finish never before start - a dangling arrow is a broken trace."""
    if not isinstance(trace, dict) or not isinstance(
        trace.get("traceEvents"), list
    ) or not trace["traceEvents"]:
        raise ValueError("trace must be a dict with a non-empty traceEvents")
    process_names: dict[int, str] = {}
    thread_names: dict[tuple[int, int], str] = {}
    used_pids: set[int] = set()
    used_tids: set[tuple[int, int]] = set()
    be_stacks: dict[tuple[int, int], list[str]] = {}
    x_by_tid: dict[tuple[int, int], list[tuple[int, int]]] = {}
    async_open: dict[tuple[str, str], dict] = {}
    flows: dict[tuple[str, str], dict] = {}

    for i, e in enumerate(trace["traceEvents"]):
        where = f"traceEvents[{i}]"
        if not isinstance(e, dict):
            raise ValueError(f"{where}: not an object")
        ph = e.get("ph")
        if ph not in _REQUIRED_BY_PH:
            raise ValueError(f"{where}: unsupported ph {ph!r}")
        for field in _REQUIRED_BY_PH[ph]:
            if field not in e:
                raise ValueError(f"{where}: ph={ph} missing {field!r}")
        if "ts" in e:
            ts = e["ts"]
            if not isinstance(ts, int) or ts < 0:
                raise ValueError(
                    f"{where}: ts must be a non-negative integer µs, "
                    f"got {ts!r}"
                )
        if ph == "X":
            dur = e["dur"]
            if not isinstance(dur, int) or dur < 0:
                raise ValueError(
                    f"{where}: dur must be a non-negative integer µs, "
                    f"got {dur!r}"
                )
            x_by_tid.setdefault((e["pid"], e["tid"]), []).append(
                (e["ts"], dur)
            )
        if ph == "i" and e.get("s") not in ("t", "p", "g"):
            raise ValueError(f"{where}: instant scope {e.get('s')!r}")
        if ph == "M":
            if e["name"] == "process_name":
                process_names[e["pid"]] = e.get("args", {}).get("name", "")
            elif e["name"] == "thread_name":
                thread_names[(e["pid"], e["tid"])] = e.get(
                    "args", {}
                ).get("name", "")
            continue
        used_pids.add(e["pid"])
        used_tids.add((e["pid"], e["tid"]))
        if ph == "B":
            be_stacks.setdefault((e["pid"], e["tid"]), []).append(e["name"])
        elif ph == "E":
            stack = be_stacks.get((e["pid"], e["tid"]), [])
            if not stack:
                raise ValueError(
                    f"{where}: E without matching B on pid={e['pid']} "
                    f"tid={e['tid']}"
                )
            stack.pop()
        elif ph == "b":
            st = async_open.setdefault(
                (e["cat"], str(e["id"])), {"open": 0, "names": {}}
            )
            st["open"] += 1
            st["names"][e["name"]] = st["names"].get(e["name"], 0) + 1
        elif ph == "e":
            st = async_open.get((e["cat"], str(e["id"])))
            if st is None or st["open"] == 0:
                raise ValueError(
                    f"{where}: async e without an open b for "
                    f"cat={e['cat']!r} id={e['id']!r}"
                )
            st["open"] -= 1
            if st["names"].get(e["name"], 0) == 0:
                raise ValueError(
                    f"{where}: async e name {e['name']!r} was never begun "
                    f"on cat={e['cat']!r} id={e['id']!r}"
                )
            st["names"][e["name"]] -= 1
        elif ph in ("s", "f"):
            fl = flows.setdefault((e["cat"], str(e["id"])), {})
            if ph in fl:
                raise ValueError(
                    f"{where}: duplicate flow {ph!r} for "
                    f"cat={e['cat']!r} id={e['id']!r}"
                )
            fl[ph] = (e["ts"], e["name"])

    for (cat, async_id), st in async_open.items():
        if st["open"]:
            raise ValueError(
                f"unbalanced async b/e on cat={cat!r} id={async_id!r}: "
                f"{st['open']} unclosed"
            )
    for (cat, flow_id), fl in flows.items():
        if "s" not in fl:
            raise ValueError(
                f"flow cat={cat!r} id={flow_id!r}: f without s"
            )
        if "f" not in fl:
            raise ValueError(
                f"flow cat={cat!r} id={flow_id!r}: s without f "
                "(dangling arrow)"
            )
        if fl["f"][0] < fl["s"][0]:
            raise ValueError(
                f"flow cat={cat!r} id={flow_id!r}: finish at "
                f"ts={fl['f'][0]} precedes start at ts={fl['s'][0]}"
            )
        if fl["f"][1] != fl["s"][1]:
            raise ValueError(
                f"flow cat={cat!r} id={flow_id!r}: start name "
                f"{fl['s'][1]!r} != finish name {fl['f'][1]!r}"
            )
    for key, stack in be_stacks.items():
        if stack:
            raise ValueError(
                f"unbalanced B/E on pid={key[0]} tid={key[1]}: "
                f"{len(stack)} unclosed ({stack[-1]!r} last)"
            )
    for pid in used_pids:
        name = process_names.get(pid)
        if name is None:
            raise ValueError(f"pid {pid} has events but no process_name")
        if not name.startswith(f"rank {pid}"):
            raise ValueError(
                f"pid {pid} process_name {name!r} does not map to its rank"
            )
    for key in used_tids:
        name = thread_names.get(key)
        if name is None:
            raise ValueError(
                f"pid={key[0]} tid={key[1]} has events but no thread_name"
            )
        if SUBSYSTEM_TIDS.get(name) != key[1]:
            raise ValueError(
                f"pid={key[0]} tid={key[1]} thread_name {name!r} does not "
                "map to its subsystem tid"
            )
    for (pid, tid), spans in x_by_tid.items():
        stack: list[int] = []  # open-span end times
        for ts, dur in sorted(spans, key=lambda s: (s[0], -s[1])):
            while stack and ts >= stack[-1]:
                stack.pop()
            if stack and ts + dur > stack[-1]:
                raise ValueError(
                    f"pid={pid} tid={tid}: span at ts={ts} dur={dur} "
                    f"partially overlaps an enclosing span ending at "
                    f"{stack[-1]} (timeline nesting broken)"
                )
            stack.append(ts + dur)


def write_chrome_trace(metrics_path, out_path) -> dict:
    """Build, validate and write one run's trace; returns the trace."""
    by_rank = load_run(metrics_path)
    trace = build_chrome_trace(by_rank)
    validate_chrome_trace(trace)
    out_path = Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(trace, f, indent=1)
    return trace


# -- phase attribution -------------------------------------------------------


PHASES = ("data_wait", "dispatch", "device", "exchange")


def attribute_rank(events: list[dict]) -> dict | None:
    """One rank's step time decomposed into phase totals/fractions.

    Only the fenced (sampled) steps are attributable - on async steps
    the device tail is invisible by design - and the run's first step
    is excluded like every timing summary (it carries the compile).
    One sampled step's cycle is ``data_wait + fenced``; within it,
    ``exchange`` (the step's ps_exchange seconds, clamped into the
    dispatch window it rides) and ``device = fenced - dispatch`` leave
    ``dispatch`` as host-side dispatch work, so the four fractions sum
    to 1 exactly up to float error.  Returns ``None`` when no sampled
    steady-state step exists.
    """
    steps = [e for e in events if e["kind"] == "step"]
    if not steps:
        return None
    first = min(int(e.get("step", 0)) for e in steps)
    exchange_by_step: dict[int, float] = {}
    for e in events:
        if e["kind"] == "ps_exchange" and not e.get("failed") \
                and e.get("step") is not None:
            exchange_by_step[int(e["step"])] = (
                exchange_by_step.get(int(e["step"]), 0.0)
                + float(e.get("seconds", 0.0))
            )
    totals = dict.fromkeys(PHASES, 0.0)
    cycle_total = 0.0
    sampled = 0
    for e in steps:
        step = int(e.get("step", 0))
        fenced = e.get("fenced_s")
        if fenced is None or (step == first and len(steps) > 1):
            continue
        fenced = float(fenced)
        dispatch = min(float(e.get("dispatch_s") or 0.0), fenced)
        data_wait = float(e.get("data_wait_s") or 0.0)
        exchange = min(exchange_by_step.get(step, 0.0), dispatch)
        totals["data_wait"] += data_wait
        totals["exchange"] += exchange
        totals["dispatch"] += dispatch - exchange
        totals["device"] += fenced - dispatch
        cycle_total += data_wait + fenced
        sampled += 1
    if not sampled or cycle_total <= 0:
        return None
    return {
        "rank": int(events[0].get("rank", 0)),
        "steps_sampled": sampled,
        "step_s_mean": cycle_total / sampled,
        "seconds": {k: totals[k] / sampled for k in PHASES},
        "fractions": {k: totals[k] / cycle_total for k in PHASES},
    }


def attribute_run(path) -> list[dict]:
    """Per-rank attributions for one run's sidecar family, by rank."""
    by_rank = load_run(path)
    out = []
    for rank in sorted(by_rank):
        attr = attribute_rank(by_rank[rank])
        if attr is not None:
            attr["rank"] = rank
            out.append(attr)
    return out


def attribute_stragglers(attributions: list[dict],
                         threshold: float = 0.25) -> list[dict]:
    """Straggler attribution: ranks whose sampled step cycle sits more
    than ``threshold`` (fraction) above the cross-rank median, blamed
    on the phase with the largest per-step excess over the median
    rank's same phase."""
    timed = [a for a in attributions if a.get("step_s_mean")]
    if len(timed) < 2:
        return []
    median_cycle = statistics.median(a["step_s_mean"] for a in timed)
    if median_cycle <= 0:
        return []
    median_phase = {
        k: statistics.median(a["seconds"][k] for a in timed)
        for k in PHASES
    }
    flagged = []
    for a in timed:
        excess = a["step_s_mean"] / median_cycle - 1.0
        if excess <= threshold:
            continue
        phase_excess = {
            k: a["seconds"][k] - median_phase[k] for k in PHASES
        }
        phase = max(phase_excess, key=phase_excess.get)
        flagged.append({
            "rank": a["rank"],
            "step_s_mean": a["step_s_mean"],
            "median_s": median_cycle,
            "excess_frac": excess,
            "phase": phase,
            "phase_excess_s": phase_excess[phase],
        })
    return sorted(flagged, key=lambda f: -f["excess_frac"])
