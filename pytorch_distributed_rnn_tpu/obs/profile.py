"""Opt-in step-bounded ``jax.profiler`` trace capture.

``--profile DIR`` (training/__init__.py) already traces the WHOLE run;
that is the wrong tool past the first epochs - a 20-epoch run's xplane
dir is dominated by compile + warm-up and dwarfs the steady-state steps
the user wants to look at.  ``--profile-steps A:B`` bounds the capture
to optimizer steps ``[A, B)``: the trace starts right before step A's
dispatch and stops after step B-1's program completes (the trainer
fences on the step's outputs before stopping, so the device work is in
the trace).

Backends without profiler support (or with a broken plugin) must not
kill a training run: every profiler call is wrapped, the first failure
logs one warning and disables the capture for the rest of the run.
"""

from __future__ import annotations

import logging
import os
from pathlib import Path

log = logging.getLogger(__name__)


class StepTraceCapture:
    """Start/stop ``jax.profiler`` around a step range ``[start, stop)``."""

    def __init__(self, trace_dir, start: int, stop: int):
        if start < 0 or stop <= start:
            raise ValueError(
                f"profile step range must satisfy 0 <= A < B, got "
                f"{start}:{stop}"
            )
        self.trace_dir = Path(trace_dir)
        self.start = int(start)
        self.stop = int(stop)
        self._active = False
        self._captured = False
        self._disabled = False

    # -- construction --------------------------------------------------------

    @classmethod
    def parse_range(cls, spec: str) -> tuple[int, int]:
        """``"A:B"`` -> ``(A, B)`` with loud failure on malformed specs."""
        head, sep, tail = str(spec).partition(":")
        if not sep:
            raise ValueError(
                f"--profile-steps wants A:B (half-open step range), got "
                f"{spec!r}"
            )
        try:
            start, stop = int(head), int(tail)
        except ValueError as exc:
            raise ValueError(
                f"--profile-steps wants integer steps A:B, got {spec!r}"
            ) from exc
        if start < 0 or stop <= start:
            raise ValueError(
                f"--profile-steps needs 0 <= A < B, got {spec!r}"
            )
        return start, stop

    @classmethod
    def resolve(cls, args) -> "StepTraceCapture | None":
        """From the CLI surface: ``--profile-steps A:B`` bounds a capture
        into the ``--profile DIR`` trace directory; returns ``None`` when
        the flag is absent."""
        spec = getattr(args, "profile_steps", None)
        if not spec:
            return None
        trace_dir = getattr(args, "profile", None)
        if not trace_dir:
            raise SystemExit(
                "--profile-steps bounds a capture and needs --profile DIR "
                "for the trace directory"
            )
        start, stop = cls.parse_range(spec)
        return cls(trace_dir, start, stop)

    # -- step hooks ----------------------------------------------------------

    def on_step_start(self, step: int) -> None:
        if self._disabled or self._active or self._captured:
            return
        if step < self.start or step >= self.stop:
            return
        try:
            import jax

            os.makedirs(self.trace_dir, exist_ok=True)
            jax.profiler.start_trace(str(self.trace_dir))
        except Exception as exc:  # no profiler on this backend: skip, loudly
            self._disabled = True
            log.warning(
                f"profiler trace capture unavailable on this backend "
                f"({type(exc).__name__}: {exc}); skipping --profile-steps"
            )
            return
        self._active = True

    def on_step_end(self, step: int, fence_value=None) -> None:
        if not self._active or step < self.stop - 1:
            return
        self._stop_trace(fence_value)

    def _stop_trace(self, fence_value=None) -> None:
        try:
            import jax

            if fence_value is not None:
                # the step's device work must have landed before the
                # trace closes, or the capture ends mid-program
                jax.block_until_ready(fence_value)
            jax.profiler.stop_trace()
            self._captured = True
        except Exception as exc:  # pragma: no cover - backend-specific
            self._disabled = True
            log.warning(f"profiler stop_trace failed: {exc}")
        self._active = False

    def close(self) -> dict:
        """Stop any in-flight capture (run ended inside the range);
        returns the ``profile`` telemetry event payload."""
        if self._active:
            self._stop_trace()
        return {
            "dir": str(self.trace_dir),
            "start": self.start,
            "stop": self.stop,
            "captured": self._captured,
        }
