"""Efficiency ledger: every second of a run's wall-clock, accounted.

The fourth obs layer.  The recorder (layer 1) writes events, summaries
(layer 2) reduce them, the timeline (layer 3) draws them - this module
*prices* them: it classifies a run's wall-clock into an exhaustive phase
ledger and divides analytic FLOPs (``obs/flops.py``) by hardware peaks
(``utils/hw.py``) so chaos drills, schedulers and cross-PR diffs all
argue over the same four numbers:

- **goodput**  - fraction of wall-clock spent in steps that advanced
  the model (compute phase; nan-skipped step time excluded);
- **MFU/HFU** - analytic model FLOPs per step (counted off the traced
  jaxpr, recorded on the ``collectives`` event) against the claimed
  per-backend peak.  The two are equal when nothing rematerializes -
  true of every step program in this tree - and the CPU peak is an
  ESTIMATE, labeled as such wherever it is printed;
- **fault tax** - wall-clock attributable to injected/observed faults:
  chaos stall windows, nan-skipped step time, the tail a kill cut off,
  and restart/replay lag;
- **phase fractions** - compute / comm_wait / data_wait / compile /
  checkpoint / eval / restart / fault / idle, provably summing to 1:
  idle is the residual, and over-attribution (overlapping
  instrumentation) is scaled down proportionally before the residual
  is taken, so the invariant holds by construction.

Accounting notes, in decreasing order of certainty:

- step/epoch/span/checkpoint durations are measured wall-clock;
- per-step sums (data wait, comm wait, step time) are scaled from the
  SAMPLED step events to the full step span (``--metrics-sample-every``
  keeps hot-loop overhead down; the ledger multiplies the means back);
- a producer-side chaos stall surfaces as consumer data wait, so
  ``fault_stall`` span time is moved from the data_wait phase to the
  fault phase rather than double-counted;
- compile time is the first step's excess over the steady-state mean
  plus any ``compile`` events (retraces after warm-up);
- MPMD stage steps time the whole iteration including link waits, so a
  stage's compute phase upper-bounds its true compute and the derived
  bubble fraction is a lower bound.

Schema contract: like the timeline, the ledger needs the monotonic
``tm`` clock and therefore a schema >= 2 sidecar -
:class:`MalformedMetricsError` (CLI exit 2) on older recordings.
"""

from __future__ import annotations

import json
import statistics
from pathlib import Path

from pytorch_distributed_rnn_tpu.obs.summary import (
    MalformedMetricsError,
    load_events,
    rank_files,
)

LEDGER_PHASES = (
    "compute", "comm_wait", "data_wait", "compile", "checkpoint",
    "eval", "restart", "fault", "idle",
)

# phase fractions must sum to 1 within this tolerance (pinned by tests
# and the acceptance criteria; the residual construction guarantees it)
FRACTION_TOL = 1e-6

# fault actions that end the process: their sidecars get a lost-tail
# fault attribution (wall between the last step and the stream's end)
_FATAL_ACTIONS = ("kill", "respawn", "preempt")


def _step_time(e) -> float:
    d = e.get("fenced_s")
    if d is None:
        d = e.get("dispatch_s")
    return float(d or 0.0)


def _mono_end(e) -> float | None:
    """Monotonic end stamp of one event, or None when it carries no tm
    (the launcher's wall-clock-only root span)."""
    tm = e.get("tm")
    if tm is None:
        return None
    tm = float(tm)
    kind = e["kind"]
    # only kinds whose tm is a START stamp extend by their duration;
    # checkpoint events stamp at completion already
    if kind == "step":
        return tm + _step_time(e)
    if kind == "span":
        return tm + float(e.get("dur_s") or 0.0)
    if kind == "epoch":
        return tm + float(e.get("wall_s") or 0.0)
    return tm


def ledger_events(events: list[dict], path=None, peak: dict | None = None,
                  ) -> dict:
    """One rank's efficiency ledger off its event list.

    Raises :class:`MalformedMetricsError` on schema-1 sidecars (no
    monotonic clock - same contract as the timeline exporter).  Never
    raises on zero-step or torn runs: partial telemetry of crashed runs
    is exactly what the fault-tax column prices.
    """
    meta = events[0]
    if meta.get("tm") is None:
        raise MalformedMetricsError(
            f"{path or 'sidecar'}: the efficiency ledger needs a schema "
            ">= 2 recording (monotonic tm clock in the meta head); "
            "re-record with the current MetricsRecorder"
        )
    t0 = float(meta["tm"])
    end = t0
    for e in events:
        stamp = _mono_end(e)
        if stamp is not None:
            end = max(end, stamp)
    wall_s = max(0.0, end - t0)

    steps = sorted(
        (e for e in events if e["kind"] == "step"),
        key=lambda e: int(e.get("step", 0)),
    )
    run = next(
        (e for e in reversed(events) if e["kind"] == "run_summary"), None
    )
    collectives = next(
        (e for e in events if e["kind"] == "collectives"), None
    )
    n_sampled = len(steps)
    if steps:
        span_steps = (
            int(steps[-1].get("step", 0)) - int(steps[0].get("step", 0)) + 1
        )
    else:
        span_steps = 0

    first_time = _step_time(steps[0]) if steps else 0.0
    rest_times = [_step_time(e) for e in steps[1:]]
    mean_rest = (
        sum(rest_times) / len(rest_times) if rest_times else None
    )

    def per_step_total(field) -> float:
        """Sampled mean x full step span: the sampled-cadence rescale."""
        vals = [float(e[field]) for e in steps
                if e.get(field) is not None]
        if not vals:
            return 0.0
        return (sum(vals) / len(vals)) * span_steps

    data_wait_s = per_step_total("data_wait_s")
    comm_wait_s = per_step_total("comm_wait_s")

    compiles = [e for e in events if e["kind"] == "compile"]
    compile_warmup_s = (
        max(0.0, first_time - (mean_rest or 0.0)) if steps else 0.0
    )
    compile_s = compile_warmup_s + sum(
        float(e.get("seconds") or 0.0) for e in compiles
    )

    spans = [e for e in events if e["kind"] == "span"]
    fault_stall_s = sum(
        float(e.get("dur_s") or 0.0) for e in spans
        if e.get("name") == "fault_stall"
    )
    eval_s = sum(
        float(e.get("dur_s") or 0.0) for e in spans
        if e.get("cat") == "eval"
    )
    checkpoint_s = sum(
        float(e.get("seconds") or 0.0) for e in events
        if e["kind"] in ("checkpoint_save", "checkpoint_restore")
    )
    # a respawned MPMD stage's window from process start to its
    # stage_restart witness is restore+resync lag nothing else accounts
    restart_s = sum(
        max(0.0, float(e["tm"]) - t0)
        for e in events
        if e["kind"] == "stage_restart" and e.get("tm") is not None
    )
    replayed = sum(
        int(e.get("count", 0)) for e in events if e["kind"] == "replay"
    )

    nan_total = int((run or {}).get("nan_skipped") or 0)
    if not nan_total:
        nan_total = max(
            (int(e.get("total", 0)) for e in events
             if e["kind"] == "nan_skip"), default=0,
        )
    nan_tax_s = nan_total * (mean_rest or 0.0)

    fatal_fault = any(
        e["kind"] == "fault" and e.get("action") in _FATAL_ACTIONS
        for e in events
    )
    lost_tail_s = 0.0
    if fatal_fault and steps:
        last_step_end = max(
            float(e["tm"]) + _step_time(e) for e in steps
            if e.get("tm") is not None
        )
        lost_tail_s = max(0.0, end - last_step_end)
    fault_s = fault_stall_s + nan_tax_s + lost_tail_s

    # the injected stall blocks the producer; the consumer measures it
    # as data wait - attribute it to the fault phase, once
    data_wait_adj = max(0.0, data_wait_s - fault_stall_s)

    epoch_wall = sum(
        float(e["wall_s"]) for e in events
        if e["kind"] == "epoch" and e.get("wall_s") is not None
    )
    if epoch_wall > 0:
        # epoch windows cover the whole step loop (sampled or not);
        # carve the known non-compute residents out of them
        compute_s = (
            epoch_wall - data_wait_adj - comm_wait_s - compile_s
            - fault_stall_s - nan_tax_s
        )
    else:
        # no epoch walls (MPMD stages, fused runs, streaming): rebuild
        # from the per-step times themselves
        total_step_time = first_time + (
            (mean_rest or 0.0) * max(0, span_steps - 1)
        )
        compute_s = total_step_time - compile_s - comm_wait_s - nan_tax_s
    compute_s = max(0.0, compute_s)

    phase_s = {
        "compute": compute_s,
        "comm_wait": comm_wait_s,
        "data_wait": data_wait_adj,
        "compile": compile_s,
        "checkpoint": checkpoint_s,
        "eval": eval_s,
        "restart": restart_s,
        "fault": fault_s,
    }
    attributed = sum(phase_s.values())
    if wall_s <= 0.0:
        # degenerate (zero-duration) stream: nothing to apportion
        phase_s = dict.fromkeys(phase_s, 0.0)
        fractions = dict.fromkeys(LEDGER_PHASES, 0.0)
        fractions["idle"] = 1.0
        wall_s = 0.0
    else:
        if attributed > wall_s:
            # overlapping instrumentation over-attributed: scale down
            # proportionally so the residual construction stays valid
            factor = wall_s / attributed
            phase_s = {k: v * factor for k, v in phase_s.items()}
        fractions = {k: v / wall_s for k, v in phase_s.items()}
        fractions["idle"] = max(
            0.0, 1.0 - sum(fractions[p] for p in phase_s)
        )
    phase_s["idle"] = fractions["idle"] * wall_s

    goodput = fractions["compute"]
    fault_tax_s = phase_s["fault"] + phase_s["restart"]

    flops_per_step = None
    flops_exact = None
    if collectives is not None:
        flops_per_step = collectives.get("model_flops_per_step")
        flops_exact = collectives.get("model_flops_exact")
    run_ledger = (run or {}).get("ledger") or {}
    if flops_per_step is None:
        flops_per_step = run_ledger.get("model_flops_per_step")

    mfu_est = hfu_est = None
    peak_total = run_ledger.get("peak_flops_total")
    peak_estimated = run_ledger.get("peak_flops_estimated")
    peak_device = run_ledger.get("device_kind")
    if flops_per_step is not None and wall_s > 0 and span_steps:
        if peak_total is None:
            if peak is None:
                from pytorch_distributed_rnn_tpu.utils.hw import (
                    local_peak_flops,
                )

                peak = local_peak_flops()
            peak_total = peak["peak_flops_total"]
            peak_estimated = peak["estimated"]
            peak_device = peak.get("device")
        steps_advanced = max(0, span_steps - nan_total)
        # the traced jaxpr counts EXECUTED flops (an HFU numerator);
        # with no rematerialization in the tree it is also the model
        # flop count, so the two utilizations coincide here
        hfu_est = (
            float(flops_per_step) * steps_advanced / (wall_s * peak_total)
        )
        mfu_est = hfu_est

    return {
        "path": str(path) if path is not None else None,
        "rank": int(meta.get("rank", 0)),
        "role": meta.get("role"),
        "stage": meta.get("stage"),
        "wall_s": wall_s,
        "steps_sampled": n_sampled,
        "steps_est": span_steps,
        "phase_s": phase_s,
        "fractions": fractions,
        "goodput": goodput,
        "fault_tax_s": fault_tax_s,
        "comm_wait_frac": fractions["comm_wait"],
        "recompiles": len(compiles),
        "replayed_microbatches": replayed or None,
        "nan_skipped": nan_total,
        "flops_per_step": flops_per_step,
        "flops_exact": flops_exact,
        "mfu_est": mfu_est,
        "hfu_est": hfu_est,
        "peak_flops_total": peak_total,
        "peak_estimated": peak_estimated,
        "peak_device": peak_device,
        # streaming learner bookkeeping (None elsewhere): time the
        # learner spent ingesting batches it then rejected
        "reject_tax_s": _reject_tax(run),
    }


def _reject_tax(run) -> float | None:
    """Stale/duplicate/shed ingest tax on a streaming learner: rejected
    batches still cost one ingest each at the observed ingest rate."""
    if not run or "stale_rejected" not in run:
        return None
    rate = run.get("experience_per_s")
    if not rate:
        return None
    rejected = (
        int(run.get("stale_rejected") or 0)
        + int(run.get("duplicates") or 0)
        + int(run.get("queue_sheds") or 0)
    )
    return rejected / float(rate)


def ledger_file(path, peak: dict | None = None) -> dict:
    return ledger_events(load_events(path), path=path, peak=peak)


def ledger_run(path, peak: dict | None = None) -> dict:
    """The whole run's ledger: per-rank ledgers (rank-0 sidecar plus
    ``-r<k>`` siblings), a wall-weighted aggregate, and - when the meta
    roles say so - an MPMD per-stage view with bubble fraction or a
    streaming actor/learner split."""
    files = rank_files(path)
    if not files:
        raise MalformedMetricsError(f"{path}: no metrics sidecar found")
    ranks = [ledger_file(p, peak=peak) for p in files]
    ranks.sort(key=lambda r: r["rank"])

    wall_total = sum(r["wall_s"] for r in ranks)
    wall_max = max(r["wall_s"] for r in ranks)
    phase_s = {
        p: sum(r["phase_s"][p] for r in ranks) for p in LEDGER_PHASES
    }
    if wall_total > 0:
        fractions = {p: phase_s[p] / wall_total for p in LEDGER_PHASES}
    else:
        fractions = dict.fromkeys(LEDGER_PHASES, 0.0)
        fractions["idle"] = 1.0

    flops = [r["flops_per_step"] for r in ranks
             if r["flops_per_step"] is not None]
    peaks = [r["peak_flops_total"] for r in ranks
             if r["peak_flops_total"] is not None]
    steps_est = max(r["steps_est"] for r in ranks)
    nan_total = sum(r["nan_skipped"] for r in ranks)
    mfu_est = None
    if flops and peaks and wall_max > 0 and steps_est:
        # SPMD ranks trace the same GLOBAL program: take the flops once,
        # sum the per-process peaks
        mfu_est = (
            max(flops) * max(0, steps_est - nan_total)
            / (wall_max * sum(peaks))
        )

    aggregate = {
        "wall_s": wall_max,
        "phase_s": phase_s,
        "fractions": fractions,
        "goodput": fractions["compute"],
        "fault_tax_s": sum(r["fault_tax_s"] for r in ranks),
        "comm_wait_frac": fractions["comm_wait"],
        "recompiles": sum(r["recompiles"] for r in ranks),
        "steps_est": steps_est,
        "mfu_est": mfu_est,
        "peak_estimated": any(r["peak_estimated"] for r in ranks) or None,
    }
    out = {"path": str(path), "ranks": ranks, "aggregate": aggregate}

    stages = [r for r in ranks if r.get("stage") is not None]
    if stages:
        compute = [r["phase_s"]["compute"] for r in stages]
        peak_stage = max(compute)
        out["mpmd"] = {
            "stages": {
                int(r["stage"]): {
                    "goodput": r["goodput"],
                    "compute_s": r["phase_s"]["compute"],
                    "fault_tax_s": r["fault_tax_s"],
                } for r in stages
            },
            # classic pipeline-bubble measure over per-stage busy time;
            # stage step timing includes link waits, so this is a LOWER
            # bound on the true bubble (see module docstring)
            "bubble_frac": (
                1.0 - sum(compute) / (len(compute) * peak_stage)
                if peak_stage > 0 else None
            ),
        }

    actors = [r for r in ranks if r.get("role") == "actor"]
    learners = [r for r in ranks if r.get("role") == "learner"]
    if actors or learners:
        out["streaming"] = {
            "learner": (
                {
                    "goodput": learners[0]["goodput"],
                    "reject_tax_s": learners[0]["reject_tax_s"],
                } if learners else None
            ),
            "actors": {
                "count": len(actors),
                "goodput_mean": (
                    sum(a["goodput"] for a in actors) / len(actors)
                    if actors else None
                ),
            },
        }
    return out


# -- cross-run regression contract --------------------------------------------

# metrics the regress gate checks per config key; direction "up" means
# a rise is the regression (fault/comm fractions), "down" a drop
# (goodput).  mfu is deliberately NOT gated: on shared CI hosts the CPU
# peak is an estimate and absolute utilization is noise - the goodput
# fraction already carries the same signal relative to the run itself.
REGRESS_METRICS = (
    ("goodput", "down"),
    ("fault_tax_frac", "up"),
    ("comm_wait_frac", "up"),
)


def history_record(run_ledger: dict, key: str) -> dict:
    """One ``ledger_history.jsonl`` line for a run's aggregate ledger."""
    agg = run_ledger["aggregate"]
    wall = agg["wall_s"]
    return {
        "key": str(key),
        "goodput": agg["goodput"],
        "mfu_est": agg["mfu_est"],
        "fault_tax_s": agg["fault_tax_s"],
        "fault_tax_frac": (agg["fault_tax_s"] / wall) if wall > 0 else 0.0,
        "comm_wait_frac": agg["comm_wait_frac"],
        "wall_s": wall,
        "steps": agg["steps_est"],
    }


def append_history(history_path, record: dict) -> None:
    path = Path(history_path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with path.open("a") as f:
        f.write(json.dumps(record) + "\n")


def load_history(history_path) -> list[dict]:
    path = Path(history_path)
    try:
        text = path.read_text()
    except OSError as exc:
        raise MalformedMetricsError(
            f"{path}: unreadable history ({exc})"
        ) from exc
    records = []
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        try:
            record = json.loads(line)
        except json.JSONDecodeError as exc:
            raise MalformedMetricsError(
                f"{path}:{lineno}: unparseable history line ({exc})"
            ) from exc
        if not isinstance(record, dict) or "key" not in record:
            raise MalformedMetricsError(
                f"{path}:{lineno}: history record without a 'key'"
            )
        records.append(record)
    if not records:
        raise MalformedMetricsError(f"{path}: empty ledger history")
    return records


def check_history(records: list[dict], threshold: float = 0.2,
                  floor: float = 0.05) -> dict:
    """Latest run per key vs the median of its predecessors.

    A regression needs to clear BOTH the relative ``threshold`` and the
    absolute ``floor`` (in fraction points) - same-config reruns on
    noisy shared hosts must stay green, which is the whole point of
    gating on ratios instead of wall-clock.
    """
    by_key: dict[str, list[dict]] = {}
    for record in records:
        by_key.setdefault(record["key"], []).append(record)
    regressions = []
    compared = 0
    for key, group in sorted(by_key.items()):
        if len(group) < 2:
            continue
        compared += 1
        latest = group[-1]
        for metric, direction in REGRESS_METRICS:
            prior_vals = [
                float(r[metric]) for r in group[:-1]
                if r.get(metric) is not None
            ]
            value = latest.get(metric)
            if not prior_vals or value is None:
                continue
            prior = statistics.median(prior_vals)
            slack = max(floor, threshold * abs(prior))
            delta = float(value) - prior
            if (direction == "down" and -delta > slack) or (
                    direction == "up" and delta > slack):
                regressions.append({
                    "key": key,
                    "metric": metric,
                    "prior_median": prior,
                    "latest": value,
                    "delta": delta,
                })
    return {
        "keys": len(by_key),
        "compared": compared,
        "regressions": regressions,
    }
