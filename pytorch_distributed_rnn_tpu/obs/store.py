"""Bounded time-series store: the historical half of the live plane.

The aggregator (``obs/aggregator.py``) keeps the LATEST digest per
source - every scrape forgets the past, so nothing upstream can answer
"is queue depth growing?", "what was p95 over the last 5 minutes?", or
"are we burning the SLO error budget?".  This module retains bounded
history behind those questions, fed from the aggregator's existing
``/push`` ingest path - digests arrive on ``/push`` handler threads (or
the anchor's recorder writer thread for the in-process sink), so the
store adds NO thread of its own, and the zero-overhead contract holds:
with the live plane off no store is constructed and ``record()`` is
untouched (the store lives entirely on the aggregator side of the
digest wire).

Ladder downsampling
-------------------

Each series keeps a short raw tail plus fixed-resolution tiers
(raw -> 10 s -> 60 s), every tier a bounded deque:

- **gauges** downsample to ``{min, mean, max, last, count}`` per bucket;
- **counters** (process-cumulative ``*_total`` values carried in
  digests) downsample to per-bucket ``increase``/``rate`` - consecutive
  deltas clamped at zero, so a respawned process's counter reset can
  never produce a negative rate and monotonicity survives both replica
  and aggregator restarts;
- **latency histograms** keep the last cumulative
  ``LatencyHistogram.snapshot()`` per bucket (the quantile sketch:
  window quantiles interpolate over bucket-count deltas between two
  cumulative snapshots, on the SAME ``obs/live.LATENCY_BUCKETS_S``
  edges the engine and router observe into - like compares with like).

``query(name, labels, window, agg)`` picks the finest tier whose
horizon covers the window.  Time is the STORE's monotonic clock stamped
at ingest (never the digest's ``tm`` - each process's perf_counter has
its own epoch, and never wall time - NTP steps would corrupt windows);
wall stamps ride along for display and cold snapshots only.  The
last-ingest stamp per source is monotone by construction, so gap-aware
derivatives (``rate_of``) and staleness checks never divide across a
paused digest stream: a source mid-checkpoint that resumes pushing
contributes slopes only over post-gap samples.

SLO burn rates (Google SRE multi-window)
----------------------------------------

``--slo 'qos=high:p95_ms=250:availability=99.9'`` objectives are parsed
here (:func:`parse_slo`).  For each objective the store computes the
error-budget burn rate over a fast and a slow window (defaults 5 m /
1 h): ``burn = observed-bad-fraction / budgeted-bad-fraction``; burn 1.0
consumes the budget exactly, so alerts fire strictly ABOVE 1.0 on both
windows (fast catches the onset, slow confirms it is not a blip) and
clear when the fast window recovers.  Availability burns over
disruption events - router view: errors + sheds (per objective QoS) +
reroutes (a reroute is a client-visible hit whose root cause is an
unavailable replica); engine view: failed + shed.  Latency burns over
the fraction of requests above the objective's ``p95_ms`` (budget: 5 %
may exceed it - the p95 contract), interpolated from histogram deltas.

Capacity signals
----------------

Derived per ingest (throttled to ~1 Hz) and queryable as series:
slot utilization (``active / num_slots``), queue growth d/dt (gap-safe
slope), per-replica goodput headroom (peak observed token rate x free
slot fraction), and an advisory ``recommended_replicas`` gauge -
demand over per-replica capacity at a target utilization, so a dead
replica's redistributed load (queue growth, inflight spike) raises the
recommendation while the fleet is degraded.  All of it is published on
``/metrics`` (see the registry in ``obs/aggregator.py``), served as
JSON on ``GET /series``, and rendered by ``pdrnn-metrics top``.

Snapshots: ``maybe_snapshot`` (rides the ingest cadence, throttled)
writes the downsampled tiers as JSONL next to the sidecar
(``<sidecar-stem>-store.jsonl``) via temp-file + ``os.replace`` -
crash-tolerant cold history for ``pdrnn-plan``.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
import os
import threading
import time
from collections import deque
from pathlib import Path

from pytorch_distributed_rnn_tpu.obs.live import (
    LATENCY_BUCKETS_S,
    REQUEST_LATENCY_SERIES,
)
from pytorch_distributed_rnn_tpu.utils import threadcheck

log = logging.getLogger(__name__)

# Google SRE-style fast/slow burn windows (seconds)
DEFAULT_BURN_WINDOWS_S = (300.0, 3600.0)
# the p95 objective's implicit budget: 5% of requests may exceed the
# latency threshold (that is what "p95 <= X" tolerates)
LATENCY_BUDGET_FRAC = 0.05

# ladder tiers: (resolution_s, horizon_s); raw keeps RAW_HORIZON_S
RAW_HORIZON_S = 180.0
TIER_SPECS = ((10.0, 1800.0), (60.0, 7200.0))

_RAW_MAXLEN = 2048
_SOURCE_FORGET_S = 600.0  # known-replica horizon for capacity math
_CAPACITY_LOOKAHEAD_S = 5.0
_DERIVE_EVERY_S = 1.0
_SNAPSHOT_EVERY_S = 30.0
_GAP_S = 5.0  # a derivative never spans a larger inter-sample gap


def store_path_for(sidecar_path) -> Path:
    """The one cold-history location per aggregator: next to the
    (rank-suffixed) sidecar, ``<stem>-store.jsonl`` - the same adjacency
    convention as the watchdog's ``<stem>-stacks.txt``."""
    sidecar_path = Path(sidecar_path)
    return sidecar_path.with_name(f"{sidecar_path.stem}-store.jsonl")


# ---------------------------------------------------------------------------
# SLO objectives


@dataclasses.dataclass(frozen=True)
class SloObjective:
    """One per-QoS-class service-level objective (``--slo`` grammar:
    ``qos=high:p95_ms=250:availability=99.9``; both targets optional,
    at least one required)."""

    qos: str
    p95_ms: float | None = None
    availability: float | None = None  # percent, e.g. 99.9

    @property
    def availability_budget_frac(self) -> float | None:
        """The error budget as a fraction: 99.9% -> 0.001."""
        if self.availability is None:
            return None
        return (100.0 - self.availability) / 100.0

    def describe(self) -> str:
        parts = [f"qos={self.qos}"]
        if self.p95_ms is not None:
            parts.append(f"p95_ms={self.p95_ms:g}")
        if self.availability is not None:
            parts.append(f"availability={self.availability:g}")
        return ":".join(parts)


def parse_slo(spec: str) -> SloObjective:
    """One ``--slo`` value -> :class:`SloObjective`.  Grammar:
    colon-separated ``key=value`` fields; ``qos`` is required and must
    be a known class; at least one of ``p95_ms`` / ``availability``."""
    from pytorch_distributed_rnn_tpu.serving.fleet.router import QOS_CLASSES

    fields: dict[str, str] = {}
    for part in str(spec).split(":"):
        part = part.strip()
        if not part:
            continue
        key, eq, value = part.partition("=")
        if not eq:
            raise ValueError(
                f"bad --slo field {part!r} in {spec!r} (want key=value)"
            )
        fields[key.strip()] = value.strip()
    unknown = set(fields) - {"qos", "p95_ms", "availability"}
    if unknown:
        raise ValueError(
            f"unknown --slo field(s) {sorted(unknown)} in {spec!r}"
        )
    qos = fields.get("qos")
    if not qos:
        raise ValueError(f"--slo {spec!r} needs qos=<class>")
    if qos not in QOS_CLASSES:
        raise ValueError(
            f"--slo qos {qos!r} not one of {'|'.join(QOS_CLASSES)}"
        )
    p95_ms = availability = None
    if "p95_ms" in fields:
        p95_ms = float(fields["p95_ms"])
        if p95_ms <= 0:
            raise ValueError(f"--slo p95_ms must be > 0, got {p95_ms}")
    if "availability" in fields:
        availability = float(fields["availability"])
        if not 0.0 < availability < 100.0:
            raise ValueError(
                f"--slo availability must be in (0, 100), got {availability}"
            )
    if p95_ms is None and availability is None:
        raise ValueError(
            f"--slo {spec!r} needs p95_ms= and/or availability="
        )
    return SloObjective(qos=qos, p95_ms=p95_ms, availability=availability)


def parse_slo_args(values) -> tuple[SloObjective, ...]:
    """Repeatable ``--slo`` flag values -> objectives (one per QoS
    class; a duplicate class is a config error, not a silent merge)."""
    if values is None:
        return ()
    if isinstance(values, str):
        values = [values]
    objectives = [parse_slo(v) for v in values]
    seen: set[str] = set()
    for obj in objectives:
        if obj.qos in seen:
            raise ValueError(f"duplicate --slo for qos={obj.qos!r}")
        seen.add(obj.qos)
    return tuple(objectives)


# ---------------------------------------------------------------------------
# series plumbing


def _labels_key(labels: dict | None) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))


def _labels_match(key: tuple, want: dict | None) -> bool:
    if not want:
        return True
    have = dict(key)
    return all(have.get(str(k)) == str(v) for k, v in want.items())


def _hist_tuple(snapshot: dict) -> tuple | None:
    """Normalize a ``LatencyHistogram.snapshot()`` to
    ``(cum_counts_per_finite_le, total_count, total_sum)``."""
    try:
        counts = tuple(int(b["count"]) for b in snapshot["buckets"])
        return counts, int(snapshot["count"]), float(snapshot["sum"])
    except (KeyError, TypeError, ValueError):
        return None


def quantile_from_deltas(les, cum_counts, total, q) -> float | None:
    """Interpolated quantile over histogram bucket-count DELTAS
    (``cum_counts`` cumulative per finite ``le``; observations past the
    last edge clamp to it - the sketch cannot see further)."""
    if total <= 0:
        return None
    target = float(q) * total
    prev_le, prev_cum = 0.0, 0
    for le, cum in zip(les, cum_counts):
        if cum >= target:
            span = cum - prev_cum
            frac = 1.0 if span <= 0 else (target - prev_cum) / span
            return prev_le + frac * (float(le) - prev_le)
        prev_le, prev_cum = float(le), cum
    return float(les[-1])


def frac_above_from_deltas(les, cum_counts, total,
                           threshold_s) -> float | None:
    """Fraction of delta observations ABOVE ``threshold_s``,
    interpolating inside the straddling bucket."""
    if total <= 0:
        return None
    threshold_s = float(threshold_s)
    prev_le, prev_cum = 0.0, 0
    for le, cum in zip(les, cum_counts):
        le = float(le)
        if le >= threshold_s:
            span = le - prev_le
            frac = 1.0 if span <= 0 else (threshold_s - prev_le) / span
            below = prev_cum + frac * (cum - prev_cum)
            return max(0.0, min(1.0, 1.0 - below / total))
        prev_le, prev_cum = le, cum
    # threshold beyond the last finite edge: only overflow counts above
    return max(0.0, min(1.0, 1.0 - cum_counts[-1] / total
                        if cum_counts else 1.0))


class _Series:
    """One (name, labels) series: raw tail + downsampled tiers."""

    __slots__ = ("name", "labels", "kind", "raw", "tiers", "prev")

    def __init__(self, name: str, labels: tuple, kind: str,
                 tier_specs) -> None:
        self.name = name
        self.labels = labels
        self.kind = kind
        self.raw: deque = deque(maxlen=_RAW_MAXLEN)
        self.tiers: dict[float, deque] = {
            res: deque(maxlen=int(horizon / res) + 2)
            for res, horizon in tier_specs
        }
        self.prev = None  # last cumulative value (counter/hist resets)

    # -- append + incremental downsample ------------------------------------

    def append(self, tm: float, t: float, value) -> None:
        if self.kind == "hist":
            self._append_hist(tm, t, value)
            return
        value = float(value)
        self.raw.append((tm, t, value))
        if self.kind == "counter":
            prev = self.prev if self.prev is not None else value
            inc = max(0.0, value - prev)  # reset clamps at zero
            self.prev = value
            for res, buckets in self.tiers.items():
                idx = int(tm // res)
                if buckets and buckets[-1]["i"] == idx:
                    b = buckets[-1]
                    b["inc"] += inc
                    b["last"] = value
                    b["tm"] = tm
                    b["t"] = t
                else:
                    buckets.append({"i": idx, "tm0": tm, "tm": tm, "t": t,
                                    "inc": inc, "last": value})
        else:  # gauge
            for res, buckets in self.tiers.items():
                idx = int(tm // res)
                if buckets and buckets[-1]["i"] == idx:
                    b = buckets[-1]
                    b["min"] = min(b["min"], value)
                    b["max"] = max(b["max"], value)
                    b["sum"] += value
                    b["count"] += 1
                    b["last"] = value
                    b["tm"] = tm
                    b["t"] = t
                else:
                    buckets.append({"i": idx, "tm": tm, "t": t,
                                    "min": value, "max": value,
                                    "sum": value, "count": 1,
                                    "last": value})

    def _append_hist(self, tm: float, t: float, value: tuple) -> None:
        counts, total, total_sum = value
        self.raw.append((tm, t, counts, total, total_sum))
        for res, buckets in self.tiers.items():
            idx = int(tm // res)
            entry = {"i": idx, "tm": tm, "t": t, "counts": counts,
                     "count": total, "sum": total_sum}
            if buckets and buckets[-1]["i"] == idx:
                buckets[-1] = entry  # last cumulative snapshot wins
            else:
                buckets.append(entry)

    # -- reads (store lock held by caller) ----------------------------------

    def raw_points(self, since_tm: float) -> list:
        return [p for p in self.raw if p[0] >= since_tm]

    def tier_points(self, res: float, since_tm: float) -> list[dict]:
        return [b for b in self.tiers[res] if b["tm"] >= since_tm]

    def hist_delta(self, since_tm: float) -> tuple | None:
        """Cumulative delta across the window: last snapshot in window
        minus last snapshot before it (zeros when none - the process
        started inside the window).  Counter resets clamp at zero."""
        if self.kind != "hist" or not self.raw:
            return None
        end = base = None
        for point in self.raw:
            if point[0] < since_tm:
                base = point
            else:
                end = point
        if end is None:
            return None
        les = LATENCY_BUCKETS_S
        if base is None or base[3] > end[3]:  # none before, or a reset
            return end[2], end[3], end[4]
        counts = tuple(
            max(0, e - b) for e, b in zip(end[2], base[2])
        )
        return counts, max(0, end[3] - base[3]), max(0.0, end[4] - base[4])

    def counter_increase(self, since_tm: float) -> float:
        """Clamped increase over the window from raw points (deltas
        between consecutive in-window points, plus the step in from the
        last pre-window point)."""
        if self.kind != "counter":
            return 0.0
        prev = None
        total = 0.0
        for tm, _t, value in self.raw:
            if tm >= since_tm and prev is not None:
                total += max(0.0, value - prev)
            prev = value
        return total


class TimeSeriesStore:
    """Bounded multi-tier telemetry history + SLO burn + capacity."""

    def __init__(self, *, slo=(), burn_windows_s=DEFAULT_BURN_WINDOWS_S,
                 snapshot_path=None,
                 snapshot_every_s: float = _SNAPSHOT_EVERY_S,
                 raw_horizon_s: float = RAW_HORIZON_S,
                 tier_specs=TIER_SPECS,
                 stale_after_s: float = 5.0,
                 gap_s: float = _GAP_S,
                 slots_target_frac: float = 0.8):
        self.slo = tuple(slo)
        fast, slow = (float(w) for w in burn_windows_s)
        if not 0 < fast < slow:
            raise ValueError(
                f"burn windows must satisfy 0 < fast < slow, "
                f"got ({fast}, {slow})"
            )
        self.burn_windows_s = (fast, slow)
        self.snapshot_path = (
            None if snapshot_path is None else Path(snapshot_path)
        )
        self.snapshot_every_s = float(snapshot_every_s)
        self.raw_horizon_s = float(raw_horizon_s)
        self.tier_specs = tuple(
            (float(r), float(h)) for r, h in tier_specs
        )
        self.stale_after_s = float(stale_after_s)
        self.gap_s = float(gap_s)
        self.slots_target_frac = float(slots_target_frac)
        self._lock = threadcheck.lock(threading.Lock(), "store.series")  # guards: _series, _sources, _healthy_load, _last_derive_tm, _last_snapshot_tm
        self._series: dict[tuple, _Series] = {}
        # per-source capacity inputs; last_tm is stamped MONOTONICALLY
        # from the store's own clock at ingest (never digest-carried
        # stamps - remote perf_counter epochs differ; never wall time -
        # NTP steps), so staleness and gap checks are exact
        self._sources: dict[str, dict] = {}
        self._healthy_load = None  # EWMA demand/replica, full fleet only
        self._last_derive_tm = None
        self._last_snapshot_tm = None

    # -- ingestion (on /push handler threads - no thread of our own) --------

    def ingest(self, digest: dict, now: float | None = None) -> None:
        """Extract series from one digest; called by
        ``Aggregator.ingest`` outside the aggregator's lock (lock order:
        never both held)."""
        now = time.perf_counter() if now is None else float(now)
        t = time.time()
        source = str(digest.get("id") or "")
        if not source or digest.get("ephemeral"):
            return  # event-only pushers carry alerts, not gauges
        role = str(digest.get("role") or "")
        labels = {"source": source, "role": role}
        with self._lock:
            entry = self._sources.setdefault(source, {"last_tm": now})
            # monotone by construction: perf_counter never steps back,
            # and a re-ingest can only move the stamp forward
            entry["last_tm"] = max(entry["last_tm"], now)
            entry["role"] = role
            entry["serving"] = digest.get("serving")
            entry["router"] = digest.get("router")
            entry["drained"] = bool(digest.get("drained"))
            self._ingest_locked(digest, labels, now, t)
            self._derive_locked(now, t)
        self.maybe_snapshot(now)

    def _put(self, name: str, labels: dict, kind: str, tm: float,
             t: float, value) -> None:  # holds: _lock
        if value is None:
            return
        if kind != "hist":
            try:
                value = float(value)
            except (TypeError, ValueError):
                return
            if not math.isfinite(value):
                return
        key = (name, _labels_key(labels))
        series = self._series.get(key)
        if series is None:
            series = _Series(name, key[1], kind, self.tier_specs)
            self._series[key] = series
        series.append(tm, t, value)

    def _ingest_locked(self, digest: dict, labels: dict, tm: float,
                       t: float) -> None:  # holds: _lock
        put = self._put
        put("pdrnn_steps_total", labels, "counter", tm, t,
            digest.get("steps_total") or None)
        loss = digest.get("loss") or {}
        put("pdrnn_loss", labels, "gauge", tm, t, loss.get("last"))
        put("pdrnn_goodput", labels, "gauge", tm, t,
            digest.get("goodput_60s"))
        serving = digest.get("serving") or {}
        router = digest.get("router") or {}
        depth = digest.get("queue_depth") or {}
        if serving:
            put("pdrnn_queue_depth", labels, "gauge", tm, t,
                serving.get("queue_depth"))
        elif depth.get("last") is not None:
            put("pdrnn_queue_depth", labels, "gauge", tm, t,
                depth.get("last"))
        if serving:
            put("pdrnn_serving_requests_total", labels, "counter", tm, t,
                serving.get("requests"))
            put("pdrnn_serving_requests_shed_total", labels, "counter",
                tm, t, serving.get("requests_shed"))
            put("pdrnn_serving_requests_failed_total", labels, "counter",
                tm, t, serving.get("requests_failed"))
            put("pdrnn_serving_tokens_total", labels, "counter", tm, t,
                serving.get("tokens_out"))
            put("pdrnn_serving_active", labels, "gauge", tm, t,
                serving.get("active"))
            put("pdrnn_serving_slots", labels, "gauge", tm, t,
                serving.get("num_slots"))
            put("pdrnn_serving_request_rate_per_s", labels, "gauge",
                tm, t, serving.get("req_per_s_60s"))
            put("pdrnn_serving_tokens_rate_per_s", labels, "gauge",
                tm, t, serving.get("tokens_per_s_60s"))
            for q, key in (("0.5", "latency_s_p50"),
                           ("0.95", "latency_s_p95")):
                put("pdrnn_serving_latency_seconds",
                    {**labels, "quantile": q}, "gauge", tm, t,
                    serving.get(key))
            active = serving.get("active")
            slots = serving.get("num_slots")
            if active is not None and slots:
                put("pdrnn_slot_utilization", labels, "gauge", tm, t,
                    float(active) / float(slots))
            hist = _hist_tuple(serving.get("latency_hist") or {})
            if hist is not None:
                put(REQUEST_LATENCY_SERIES, labels, "hist", tm, t, hist)
        if router:
            put("pdrnn_router_routed_total", labels, "counter", tm, t,
                router.get("routed"))
            put("pdrnn_router_errors_total", labels, "counter", tm, t,
                router.get("errors"))
            put("pdrnn_router_rerouted_total", labels, "counter", tm, t,
                router.get("rerouted"))
            put("pdrnn_router_retries_total", labels, "counter", tm, t,
                router.get("retries"))
            for qos, count in (router.get("shed") or {}).items():
                put("pdrnn_router_shed_total", {**labels, "qos": qos},
                    "counter", tm, t, count)
            put("pdrnn_router_inflight", labels, "gauge", tm, t,
                router.get("inflight"))
            put("pdrnn_router_max_inflight", labels, "gauge", tm, t,
                router.get("max_inflight"))
            put("pdrnn_router_request_rate_per_s", labels, "gauge",
                tm, t, router.get("req_per_s_60s"))
            for q, key in (("0.5", "latency_s_p50"),
                           ("0.95", "latency_s_p95")):
                put("pdrnn_router_latency_seconds",
                    {**labels, "quantile": q}, "gauge", tm, t,
                    router.get(key))
            for qos, p95 in (router.get("latency_s_p95_by_qos")
                             or {}).items():
                put("pdrnn_router_latency_seconds",
                    {**labels, "quantile": "0.95", "qos": qos},
                    "gauge", tm, t, p95)
            for state, count in (router.get("replicas") or {}).items():
                put("pdrnn_router_replicas", {**labels, "state": state},
                    "gauge", tm, t, count)
            hist = _hist_tuple(router.get("latency_hist") or {})
            if hist is not None:
                put(REQUEST_LATENCY_SERIES, labels, "hist", tm, t, hist)

    def _derive_locked(self, tm: float, t: float) -> None:  # holds: _lock
        """Append derived capacity/burn series on the ingest cadence,
        throttled to ~1 Hz so an N-source fleet does not multiply the
        fleet-level series by its own size."""
        if self._last_derive_tm is not None \
                and tm - self._last_derive_tm < _DERIVE_EVERY_S:
            return
        self._last_derive_tm = tm
        cap = self._capacity_locked(tm)
        put = self._put
        for source, sig in cap["sources"].items():
            labels = {"source": source}
            put("pdrnn_queue_growth_per_s", labels, "gauge", tm, t,
                sig.get("queue_growth_per_s"))
            put("pdrnn_goodput_headroom", labels, "gauge", tm, t,
                sig.get("goodput_headroom_tokens_per_s"))
        put("pdrnn_replicas_live", {}, "gauge", tm, t,
            cap.get("replicas_live"))
        put("pdrnn_recommended_replicas", {}, "gauge", tm, t,
            cap.get("recommended_replicas"))
        for burn in self._burn_rates_locked(tm):
            put("pdrnn_slo_burn_rate",
                {"qos": burn["qos"],
                 "window": format(burn["window_s"], "g")},
                "gauge", tm, t, burn["burn_rate"])

    # -- queries -------------------------------------------------------------

    def series_names(self) -> list[dict]:
        with self._lock:
            return [
                {"name": name, "labels": dict(labels), "kind": s.kind}
                for (name, labels), s in sorted(self._series.items())
            ]

    def _pick_tier(self, window: float) -> float | None:
        """None = raw; otherwise the finest tier covering the window."""
        if window <= self.raw_horizon_s:
            return None
        for res, horizon in self.tier_specs:
            if window <= horizon:
                return res
        return self.tier_specs[-1][0]

    def query(self, name: str, labels: dict | None = None, *,
              window: float = 60.0, agg: str | None = None,
              now: float | None = None) -> dict:
        """Downsampled history for every series matching ``name`` (and
        the ``labels`` subset): the finest tier whose horizon covers
        ``window``.  ``agg`` reduces each series to one value - gauges:
        ``min|mean|max|last``; counters: ``rate|increase``; histograms:
        ``p50|p95|p99|count``."""
        now = time.perf_counter() if now is None else float(now)
        window = float(window)
        since = now - window
        res = self._pick_tier(window)
        out = []
        with self._lock:
            matches = [
                s for (sname, skey), s in sorted(self._series.items())
                if sname == name and _labels_match(skey, labels)
            ]
            for s in matches:
                body: dict = {
                    "labels": dict(s.labels), "kind": s.kind,
                    "resolution_s": res or 0.0,
                    "points": self._points_locked(s, res, since),
                }
                if agg:
                    body["agg"] = agg
                    body["value"] = self._agg_locked(s, res, since, agg)
                out.append(body)
        return {"name": name, "window_s": window, "series": out}

    def _points_locked(self, s: _Series, res: float | None,
                       since: float) -> list[dict]:  # holds: _lock
        if res is None:
            if s.kind == "hist":
                return [
                    {"tm": tm, "t": t, "count": c, "sum": total}
                    for tm, t, _counts, c, total in s.raw_points(since)
                ]
            return [
                {"tm": tm, "t": t, "value": v}
                for tm, t, v in s.raw_points(since)
            ]
        points = []
        for b in s.tier_points(res, since):
            if s.kind == "gauge":
                points.append({
                    "tm": b["tm"], "t": b["t"], "min": b["min"],
                    "mean": b["sum"] / b["count"], "max": b["max"],
                    "last": b["last"], "count": b["count"],
                })
            elif s.kind == "counter":
                points.append({
                    "tm": b["tm"], "t": b["t"], "increase": b["inc"],
                    "rate": b["inc"] / res,
                })
            else:
                points.append({
                    "tm": b["tm"], "t": b["t"], "count": b["count"],
                    "sum": b["sum"],
                })
        return points

    def _agg_locked(self, s: _Series, res: float | None, since: float,
                    agg: str):  # holds: _lock
        if s.kind == "hist":
            delta = s.hist_delta(since)
            if delta is None:
                return None
            counts, total, _sum = delta
            if agg == "count":
                return total
            if agg in ("p50", "p95", "p99"):
                return quantile_from_deltas(
                    LATENCY_BUCKETS_S, counts, total,
                    float(agg[1:]) / 100.0,
                )
            raise ValueError(f"bad hist agg {agg!r} (p50|p95|p99|count)")
        if s.kind == "counter":
            increase = s.counter_increase(since)
            if agg == "increase":
                return increase
            if agg == "rate":
                pts = s.raw_points(since)
                if len(pts) < 2:
                    return None
                span = pts[-1][0] - pts[0][0]
                return None if span <= 0 else increase / span
            raise ValueError(f"bad counter agg {agg!r} (rate|increase)")
        values = [v for _tm, _t, v in s.raw_points(since)]
        if res is not None:  # beyond raw: reduce over tier buckets
            buckets = s.tier_points(res, since)
            if agg == "min":
                return min((b["min"] for b in buckets), default=None)
            if agg == "max":
                return max((b["max"] for b in buckets), default=None)
            if agg == "mean":
                count = sum(b["count"] for b in buckets)
                return None if not count else (
                    sum(b["sum"] for b in buckets) / count
                )
            if agg == "last":
                return buckets[-1]["last"] if buckets else None
            raise ValueError(f"bad gauge agg {agg!r} (min|mean|max|last)")
        if not values:
            return None
        if agg == "min":
            return min(values)
        if agg == "max":
            return max(values)
        if agg == "mean":
            return sum(values) / len(values)
        if agg == "last":
            return values[-1]
        raise ValueError(f"bad gauge agg {agg!r} (min|mean|max|last)")

    def rate_of(self, name: str, labels: dict | None = None, *,
                window: float = 30.0,
                now: float | None = None) -> float | None:
        """Gap-safe d/dt of a gauge: least-squares slope over the
        CONTIGUOUS tail segment of raw points (consecutive gaps <=
        ``gap_s``) inside the window.  A paused-then-resumed source
        contributes only post-gap samples; a stale series (last point
        older than ``gap_s``) yields None rather than a slope across
        silence."""
        now = time.perf_counter() if now is None else float(now)
        with self._lock:
            matches = [
                s for (sname, skey), s in self._series.items()
                if sname == name and _labels_match(skey, labels)
                and s.kind == "gauge"
            ]
            if not matches:
                return None
            pts: list[tuple[float, float]] = []
            for s in matches:
                pts.extend(
                    (tm, v) for tm, _t, v in s.raw_points(now - window)
                )
        pts.sort()
        if not pts or now - pts[-1][0] > self.gap_s:
            return None
        tail = [pts[-1]]
        for tm, v in reversed(pts[:-1]):
            if tail[-1][0] - tm > self.gap_s:
                break
            tail.append((tm, v))
        tail.reverse()
        if len(tail) < 2 or tail[-1][0] - tail[0][0] <= 0:
            return None
        n = len(tail)
        mean_t = sum(tm for tm, _ in tail) / n
        mean_v = sum(v for _, v in tail) / n
        var = sum((tm - mean_t) ** 2 for tm, _ in tail)
        if var <= 0:
            return None
        cov = sum((tm - mean_t) * (v - mean_v) for tm, v in tail)
        return cov / var

    def last_ingest_age_s(self, source: str,
                          now: float | None = None) -> float | None:
        now = time.perf_counter() if now is None else float(now)
        with self._lock:
            entry = self._sources.get(str(source))
            return None if entry is None else now - entry["last_tm"]

    # -- SLO burn ------------------------------------------------------------

    def _window_counter_increase(self, name: str, labels: dict | None,
                                 since: float) -> float:  # holds: _lock
        total = 0.0
        for (sname, skey), s in self._series.items():
            if sname == name and _labels_match(skey, labels):
                total += s.counter_increase(since)
        return total

    def _window_hist_delta(self, role: str,
                           since: float) -> tuple:  # holds: _lock
        counts = [0] * len(LATENCY_BUCKETS_S)
        total = 0
        for (sname, skey), s in self._series.items():
            if sname != REQUEST_LATENCY_SERIES or s.kind != "hist":
                continue
            if not _labels_match(skey, {"role": role}):
                continue
            delta = s.hist_delta(since)
            if delta is None:
                continue
            for i, c in enumerate(delta[0]):
                counts[i] += c
            total += delta[1]
        return tuple(counts), total

    def _burn_rates_locked(self, now: float) -> list[dict]:  # holds: _lock
        out = []
        router_view = any(
            sname == "pdrnn_router_routed_total"
            for (sname, _), _s in self._series.items()
        )
        role = "router" if router_view else "serve"
        for obj in self.slo:
            for window in self.burn_windows_s:
                since = now - window
                entry = {
                    "qos": obj.qos, "window_s": window,
                    "objective": obj.describe(),
                }
                burns = []
                if obj.availability is not None:
                    budget = obj.availability_budget_frac
                    if router_view:
                        # disruption events: final errors, this class's
                        # sheds, and reroutes - a reroute succeeded on a
                        # sibling, but its root cause is an unavailable
                        # replica, which is exactly what the budget
                        # meters (errors/reroutes are not QoS-labelled:
                        # fleet-wide, charged to every objective)
                        bad = (
                            self._window_counter_increase(
                                "pdrnn_router_errors_total", None, since)
                            + self._window_counter_increase(
                                "pdrnn_router_shed_total",
                                {"qos": obj.qos}, since)
                            + self._window_counter_increase(
                                "pdrnn_router_rerouted_total", None,
                                since)
                        )
                        good = self._window_counter_increase(
                            "pdrnn_router_routed_total", None, since)
                    else:
                        bad = (
                            self._window_counter_increase(
                                "pdrnn_serving_requests_failed_total",
                                None, since)
                            + self._window_counter_increase(
                                "pdrnn_serving_requests_shed_total",
                                None, since)
                        )
                        good = self._window_counter_increase(
                            "pdrnn_serving_requests_total", None, since)
                    total = good + bad
                    frac = 0.0 if total <= 0 else bad / total
                    entry["availability_bad"] = bad
                    entry["availability_total"] = total
                    burns.append(0.0 if budget <= 0 else frac / budget)
                if obj.p95_ms is not None:
                    counts, total = self._window_hist_delta(role, since)
                    frac = frac_above_from_deltas(
                        LATENCY_BUCKETS_S, counts, total,
                        obj.p95_ms / 1e3,
                    )
                    entry["latency_total"] = total
                    if frac is not None:
                        entry["latency_frac_above"] = frac
                        burns.append(frac / LATENCY_BUDGET_FRAC)
                entry["burn_rate"] = max(burns) if burns else 0.0
                out.append(entry)
        return out

    def burn_rates(self, now: float | None = None) -> list[dict]:
        """One entry per (objective, window): the error-budget burn rate
        plus its inputs.  Burn 1.0 = consuming the budget exactly."""
        now = time.perf_counter() if now is None else float(now)
        with self._lock:
            return self._burn_rates_locked(now)

    def burn_snapshot(self, now: float | None = None) -> dict:
        """Per-objective alert inputs: ``{qos: {fast, slow, fire}}``.
        ``fire`` is True only when BOTH windows burn strictly above 1.0
        (fast catches the onset, slow confirms it is not a blip;
        exactly-at-budget does NOT fire - burning the whole budget and
        no more is the contract, not a breach)."""
        rates = self.burn_rates(now)
        fast_w, slow_w = self.burn_windows_s
        out: dict[str, dict] = {}
        for entry in rates:
            slot = out.setdefault(entry["qos"], {
                "fast": 0.0, "slow": 0.0,
                "objective": entry["objective"],
            })
            if entry["window_s"] == fast_w:
                slot["fast"] = entry["burn_rate"]
            elif entry["window_s"] == slow_w:
                slot["slow"] = entry["burn_rate"]
        for slot in out.values():
            slot["fire"] = slot["fast"] > 1.0 and slot["slow"] > 1.0
        return out

    # -- capacity ------------------------------------------------------------

    def _capacity_locked(self, now: float) -> dict:  # holds: _lock
        sources: dict[str, dict] = {}
        serve_live = serve_known = 0
        demand_slots = 0.0
        slot_counts: list[float] = []
        for source, entry in list(self._sources.items()):
            age = now - entry["last_tm"]
            if age > _SOURCE_FORGET_S:
                del self._sources[source]
                continue
            serving = entry.get("serving") or {}
            sig: dict = {"age_s": age, "role": entry.get("role")}
            if serving:
                serve_known += 1
                live = age <= self.stale_after_s \
                    and not entry.get("drained")
                sig["live"] = live
                active = serving.get("active")
                slots = serving.get("num_slots")
                depth = serving.get("queue_depth")
                if active is not None and slots:
                    sig["slot_utilization"] = (
                        float(active) / float(slots)
                    )
                growth = self._rate_of_locked(
                    "pdrnn_queue_depth", {"source": source}, now)
                sig["queue_growth_per_s"] = growth
                peak = self._gauge_peak_locked(
                    "pdrnn_serving_tokens_rate_per_s",
                    {"source": source}, now)
                if peak is not None and slots and active is not None:
                    # spare tokens/s estimate: the replica's peak
                    # observed rate scaled by its free slot fraction
                    free_frac = max(
                        0.0, 1.0 - float(active) / float(slots))
                    sig["goodput_headroom_tokens_per_s"] = (
                        peak * free_frac
                    )
                if live:
                    serve_live += 1
                    if slots:
                        slot_counts.append(float(slots))
                    demand_slots += float(active or 0) \
                        + float(depth or 0)
                    if growth is not None and growth > 0:
                        demand_slots += growth * _CAPACITY_LOOKAHEAD_S
            sources[source] = sig
        cap: dict = {"sources": sources}
        if serve_known:
            # engine view: demand in SLOTS vs per-replica slot capacity
            # at the target utilization - a dead replica's redistributed
            # queue shows up as survivor demand and raises the ask
            slots_per = (
                sum(slot_counts) / len(slot_counts) if slot_counts
                else None
            )
            cap["replicas_live"] = serve_live
            cap["replicas_known"] = serve_known
            cap["demand_slots"] = demand_slots
            if slots_per:
                cap["recommended_replicas"] = max(1, math.ceil(
                    demand_slots / (self.slots_target_frac * slots_per)
                ))
            return cap
        # router view: pool states carry liveness; demand is router
        # inflight (plus its growth) against the per-replica load the
        # fleet carried while FULLY healthy (EWMA baseline) - a killed
        # replica spikes inflight while the baseline holds, and the
        # live-fraction derate below covers the fast-request regime, so
        # the recommendation rises exactly over the dead-replica interval
        states: dict[str, float] = {}
        inflight = 0.0
        router_sources = []
        for source, entry in self._sources.items():
            router = entry.get("router") or {}
            if not router or now - entry["last_tm"] > self.stale_after_s:
                continue
            router_sources.append(source)
            inflight += float(router.get("inflight") or 0)
            for state, count in (router.get("replicas") or {}).items():
                states[state] = states.get(state, 0) + float(count)
        if not router_sources:
            return cap
        total = sum(states.values())
        live = states.get("healthy", 0.0) + states.get("half_open", 0.0)
        growth = self._rate_of_locked("pdrnn_router_inflight", None, now)
        demand = inflight + max(0.0, growth or 0.0) * _CAPACITY_LOOKAHEAD_S
        for source in router_sources:
            sources[source]["queue_growth_per_s"] = growth
        if total and live >= total and demand > 0:
            per_replica = demand / total
            self._healthy_load = (
                per_replica if self._healthy_load is None
                else 0.7 * self._healthy_load + 0.3 * per_replica
            )
        cap["replicas_live"] = live
        cap["replicas_known"] = total
        cap["demand_inflight"] = demand
        baseline = self._healthy_load
        recommended = max(1.0, total)
        if baseline and baseline > 0:
            recommended = max(
                recommended,
                math.ceil(demand / max(
                    baseline, 1e-9) * self.slots_target_frac),
            )
        if total and live < total and self._window_counter_increase(
                "pdrnn_router_routed_total", None, now - 30.0) > 0:
            # dead replica(s) while traffic flows: derate the ask by the
            # observed live fraction (3 configured at 2/3 live need
            # ceil(3 / (2/3)) = 5 provisioned for 3 live) so replacement
            # capacity is advised for as long as the outage lasts - the
            # inflight spike alone is invisible when requests are much
            # faster than the eject window.  Clears when the pool heals
            recommended = max(
                recommended, math.ceil(total * total / max(live, 1.0)),
            )
        cap["recommended_replicas"] = int(recommended)
        return cap

    def _rate_of_locked(self, name, labels, now):  # holds: _lock
        # rate_of re-takes the lock; inline the hot part instead
        matches = [
            s for (sname, skey), s in self._series.items()
            if sname == name and _labels_match(skey, labels)
            and s.kind == "gauge"
        ]
        pts: list[tuple[float, float]] = []
        for s in matches:
            pts.extend((tm, v) for tm, _t, v in s.raw_points(now - 30.0))
        pts.sort()
        if not pts or now - pts[-1][0] > self.gap_s:
            return None
        tail = [pts[-1]]
        for tm, v in reversed(pts[:-1]):
            if tail[-1][0] - tm > self.gap_s:
                break
            tail.append((tm, v))
        tail.reverse()
        if len(tail) < 2:
            return None
        n = len(tail)
        mean_t = sum(tm for tm, _ in tail) / n
        mean_v = sum(v for _, v in tail) / n
        var = sum((tm - mean_t) ** 2 for tm, _ in tail)
        if var <= 0:
            return None
        return sum(
            (tm - mean_t) * (v - mean_v) for tm, v in tail
        ) / var

    def _gauge_peak_locked(self, name, labels, now,
                           window=600.0):  # holds: _lock
        res = self._pick_tier(window) or self.tier_specs[0][0]
        peak = None
        for (sname, skey), s in self._series.items():
            if sname != name or not _labels_match(skey, labels) \
                    or s.kind != "gauge":
                continue
            for b in s.tier_points(res, now - window):
                peak = b["max"] if peak is None else max(peak, b["max"])
        return peak

    def capacity(self, now: float | None = None) -> dict:
        """Fleet capacity signals: per-source utilization / queue growth
        / headroom plus the advisory ``recommended_replicas``."""
        now = time.perf_counter() if now is None else float(now)
        with self._lock:
            return self._capacity_locked(now)

    # -- Prometheus ----------------------------------------------------------

    def prometheus_samples(self, now: float | None = None) -> list:
        """Capacity + burn gauges in ``render_prometheus`` sample form
        (appended to the aggregator's exposition)."""
        now = time.perf_counter() if now is None else float(now)
        samples: list = []

        def add(name, labels, value):
            if value is not None:
                samples.append((name, labels, value, "gauge"))

        cap = self.capacity(now)
        for source, sig in cap["sources"].items():
            labels = {"source": source}
            add("pdrnn_slot_utilization", labels,
                sig.get("slot_utilization"))
            add("pdrnn_queue_growth_per_s", labels,
                sig.get("queue_growth_per_s"))
            add("pdrnn_goodput_headroom", labels,
                sig.get("goodput_headroom_tokens_per_s"))
        add("pdrnn_replicas_live", {}, cap.get("replicas_live"))
        add("pdrnn_recommended_replicas", {},
            cap.get("recommended_replicas"))
        for burn in self.burn_rates(now):
            add("pdrnn_slo_burn_rate",
                {"qos": burn["qos"],
                 "window": format(burn["window_s"], "g")},
                burn["burn_rate"])
        return samples

    # -- snapshots -----------------------------------------------------------

    def maybe_snapshot(self, now: float | None = None) -> Path | None:
        """Throttled snapshot on the ingest cadence (no timer thread);
        returns the path when one was written."""
        if self.snapshot_path is None:
            return None
        now = time.perf_counter() if now is None else float(now)
        with self._lock:
            if self._last_snapshot_tm is not None \
                    and now - self._last_snapshot_tm \
                    < self.snapshot_every_s:
                return None
            self._last_snapshot_tm = now
        return self.write_snapshot()

    def write_snapshot(self, path=None) -> Path | None:
        """Write the downsampled tiers as JSONL (one meta line, one line
        per series) via temp-file + ``os.replace`` - a crash mid-write
        leaves the previous snapshot intact, never a torn file."""
        path = self.snapshot_path if path is None else Path(path)
        if path is None:
            return None
        with self._lock:
            lines = [json.dumps({
                "kind": "store_meta", "schema": 1, "t": time.time(),
                "slo": [obj.describe() for obj in self.slo],
                "burn_windows_s": list(self.burn_windows_s),
                "tiers_s": [r for r, _ in self.tier_specs],
            })]
            for (name, labels), s in sorted(self._series.items()):
                tiers = {}
                for res, _horizon in self.tier_specs:
                    tiers[format(res, "g")] = [
                        {k: v for k, v in b.items() if k != "i"}
                        for b in s.tiers[res]
                    ]
                lines.append(json.dumps({
                    "kind": "series", "name": name,
                    "labels": dict(labels), "series_kind": s.kind,
                    "tiers": tiers,
                }, default=str))
        try:
            path.parent.mkdir(parents=True, exist_ok=True)
            tmp = path.with_name(path.name + ".tmp")
            tmp.write_text("\n".join(lines) + "\n")
            os.replace(tmp, path)
            return path
        except OSError as exc:
            log.warning(f"store: snapshot to {path} failed: {exc}")
            return None


def load_snapshot(path) -> dict:
    """Read a store snapshot back (``pdrnn-plan``'s cold-history entry
    point): ``{"meta": {...}, "series": [...]}``.  Torn trailing lines
    (a crash between writes cannot produce one, but a foreign truncation
    can) are skipped, not fatal."""
    meta: dict = {}
    series: list[dict] = []
    for line in Path(path).read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            entry = json.loads(line)
        except ValueError:
            continue
        if entry.get("kind") == "store_meta":
            meta = entry
        elif entry.get("kind") == "series":
            series.append(entry)
    return {"meta": meta, "series": series}
