"""Span primitives for the trace timeline (`obs/timeline.py`).

A *span* is a duration event: ``kind="span"`` with a ``name``, a
subsystem ``cat`` (which becomes the Perfetto thread row), a monotonic
start ``tm``, a wall-clock start ``t`` and a ``dur_s``.  Two emission
styles share one wire format:

- :class:`Span` — the context-manager form
  (``with recorder.span("eval", cat="eval"): ...``) for phases whose
  extent IS a Python block;
- ``recorder.emit_span(name, tm_start, dur_s, ...)`` — the deferred
  form for phases timed inside a hot loop and emitted afterwards (the
  trainer's post-loop step flush), or whose start was captured before
  the recorder could know the outcome (a parameter-server round).

Per-step *sub*-spans (data_wait / dispatch / fenced-device) are NOT
emitted as span events at all: the ``step`` event already carries
``tm`` + the three durations, and the timeline exporter synthesizes
the nested spans from it — one JSONL line per step instead of four.
The same synthesis covers every event that carries a duration
(``checkpoint_save``/``restore`` seconds, ``ps_exchange`` seconds,
``epoch`` wall_s), so explicit span events are reserved for phases no
existing event times.

Zero-overhead contract: a disabled recorder returns :data:`NULL_SPAN`,
a shared no-op context manager — no clock reads, no allocation beyond
the method call (pinned by the guard tests next to the no-fence /
no-thread pins).
"""

from __future__ import annotations

import time

# subsystem categories -> stable Perfetto tids (one thread row per
# subsystem inside each rank's process row).  The timeline exporter and
# validator both key off this table, so an unknown cat falls back to
# "train" rather than inventing an unmapped tid.
SUBSYSTEM_TIDS = {
    "run": 0,
    "train": 1,
    "step": 2,
    "data": 3,
    "ckpt": 4,
    "ps": 5,
    "eval": 6,
    "resilience": 7,
    "sys": 8,
    "serving": 9,  # inference-server spans (prefill, serve-loop phases)
    # elastic membership lane: member_join/drain/dead instants and
    # state_sync spans (resilience/membership.py roster transitions)
    "member": 10,
    # MPMD pipeline lane: stage_restart/replay instants (parallel/mpmd.py
    # + runtime/stage.py link recovery)
    "stage": 11,
    # streaming actor/learner lane: experience pushes, params refreshes,
    # staleness rejections (streaming/actor.py + streaming/learner.py)
    "actor": 12,
    # host-collective lane: per-bucket reduce_scatter/allgather spans of
    # the overlapped native-ring step (training/native_ddp.py) - stacked
    # against the train lane they show comm riding under compute
    "comm": 13,
    # serving-fleet router lane: dispatch spans plus breaker transitions
    # (replica_eject / replica_readmit), shed and drain instants
    # (serving/fleet/router.py)
    "router": 14,
    # distributed-tracing lane: per-request route/attempt/queue_wait/
    # decode spans carrying TraceContext ids (obs/tracectx.py).  These
    # overlap freely - concurrent requests share the row - so the
    # timeline exporter renders them as ASYNC events (ph b/e keyed by
    # trace id), not complete-event spans
    "trace": 15,
}


class Span:
    """Context manager emitting one ``span`` event on exit.

    The wall start is derived from the recorder's construction-time
    wall<->monotonic anchor rather than a second ``time.time()`` call,
    so a mid-run NTP step cannot tear a span's ``t`` away from its
    ``tm`` (the alignment in ``obs/timeline.py`` depends on the two
    describing the same instant).
    """

    __slots__ = ("_recorder", "_name", "_cat", "_attrs", "_tm0")

    def __init__(self, recorder, name: str, cat: str, attrs: dict):
        self._recorder = recorder
        self._name = name
        self._cat = cat
        self._attrs = attrs
        self._tm0 = None

    def __enter__(self) -> "Span":
        self._tm0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._recorder.emit_span(
            self._name,
            self._tm0,
            time.perf_counter() - self._tm0,
            cat=self._cat,
            **self._attrs,
        )


class NullSpan:
    """The disabled-telemetry span: enter/exit do nothing."""

    __slots__ = ()

    def __enter__(self) -> "NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:  # noqa: PD105
        pass


NULL_SPAN = NullSpan()
