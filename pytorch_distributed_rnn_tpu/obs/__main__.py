import sys

from pytorch_distributed_rnn_tpu.obs.cli import main

if __name__ == "__main__":
    sys.exit(main())
