"""Structured run telemetry: rank-tagged JSONL event stream.

The reference's only machine-readable telemetry is ONE regex-parsed
stderr line per run (``training/formatter.py`` perf line), which says
nothing about *where* time goes and silently vanishes when a run
crashes.  :class:`MetricsRecorder` is the structured replacement:
every process appends per-step / per-epoch / subsystem events to a
JSONL sidecar, buffered in memory and flushed by a background thread so
nothing rides the training hot path.  The legacy perf line is untouched
- the sidecar is an addition, not a replacement (``evaluation/
analysis.py`` prefers it and falls back to the regex).

Hot-path contract:

- disabled telemetry is :data:`NULL_RECORDER` - a no-op object with NO
  flush thread and ``enabled = False``, so instrumented call sites cost
  one attribute check (the zero-overhead guard test pins this);
- ``record()`` appends a dict to an in-memory buffer under a lock and
  (past a threshold) *signals* the writer thread - it never touches the
  filesystem itself;
- device fencing (``jax.block_until_ready``) happens only on a sampled
  cadence (``sample_every``), so steady-state dispatch stays async.

Event schema (``schema = 2``; one JSON object per line, every event
carries ``kind``, ``t`` (unix seconds), ``tm`` (monotonic seconds,
``time.perf_counter`` - the clock ALL in-run deltas and the timeline
alignment use, immune to NTP steps that can reorder or negate ``t``
deltas) and ``rank``.  Schema-1 sidecars (no ``tm``) still load for
summaries; only the timeline exporter requires schema 2):

=================== =======================================================
kind                payload
=================== =======================================================
meta                schema, sample_every, argv? - always the FIRST line;
                    its (t, tm) pair is the rank's wall<->monotonic anchor
step                step, epoch, loss, dispatch_s, data_wait_s,
                    fenced_s (sampled steps only); comm_wait_s +
                    overlap_frac when the strategy runs host
                    collectives (native ring - wall blocked in
                    collectives, and the wire-time share hidden behind
                    compute); tm is the step's dispatch START
                    (overridden by the trainer), so the timeline can
                    synthesize the per-step sub-spans
epoch               epoch, steps, loss, acc, wall_s, path (scan|step|host)
eval                epoch (null = test), loss, acc
collectives         ops {hlo-op: {count, bytes}}, bytes_per_step - traced
                    once per run from the live step program; plus the
                    efficiency ledger's analytic cost of the same trace
                    (obs/flops.py): model_flops_per_step,
                    model_flops_exact, arg_bytes, out_bytes
compile             step, seconds, cache_size - a step function's trace
                    cache grew AFTER its warm-up compile (a retrace:
                    shape drift, weak types, donation mismatch);
                    seconds is that step's dispatch wall, which the
                    ledger moves from the compute to the compile phase
                    and `pdrnn-metrics summarize` counts as recompiles
checkpoint_save     epoch, best, seconds, format
checkpoint_restore  path, epoch, seconds
nan_skip            new, total, consecutive
fault               action, trigger, where
span                name, cat, dur_s (+ attrs); tm/t are the span START
                    (obs/spans.py - the trace-timeline duration event)
heartbeat           seq, progress (last step noted via note_progress) -
                    emitted by the writer thread on its wake cadence, so
                    a stalled rank keeps proving it is alive while its
                    progress freezes (pdrnn-metrics health)
ps_exchange         what (push|pull), step, seconds, retries
ps_round            updates, gathered, expected, degraded
ps_worker_dead      worker, error
ps_summary          updates, degraded_rounds, workers_lost, rejoins
member_join         worker_id, rank_slot, incarnation, via, rejoin +
                    roster counts - a member (re)entered the elastic
                    world (resilience/membership.py)
member_drain        worker_id, rank_slot, seq + roster counts -
                    voluntary leave (SIGTERM drain / DEREGISTER);
                    pdrnn-metrics health classifies the rank drained,
                    not dead
member_dead         worker_id, rank_slot, error + roster counts -
                    involuntary loss (transport death), rejoinable via
                    REGISTER
checkpoint_fallback path, reason, chosen - a corrupt checkpoint was
                    skipped during --resume auto and resume fell back
stage_restart       stage, resume_step, ckpt - a respawned MPMD stage
                    restored its per-stage checkpoint and is re-dialing
                    its neighbors (parallel/mpmd.py); pdrnn-metrics
                    health classifies the rank recovering, not stalled,
                    until its first post-restart step lands
replay              stage, link, count, from_seq, to_seq - a surviving
                    link end replayed buffered microbatch frames to a
                    restarted neighbor during the watermark handshake
                    (runtime/stage.py)
alert               alert (stall | stall_cleared | nan_streak |
                    loss_spike | slo_breach | slo_recovered | slo_burn
                    | slo_burn_cleared | straggler | worker_respawn |
                    worker_lost | pool_collapse),
                    severity (warning|info), seq (per-emitter monotone)
                    + detector fields; slo_breach/slo_recovered carry
                    the breaching ``qos`` class (absent = the
                    deprecated class-blind env threshold) and
                    slo_burn/slo_burn_cleared carry qos,
                    burn_rate_fast/_slow, objective and windows_s (the
                    store's multi-window error-budget burn,
                    obs/store.py); chaos_fired carries the fault
                    schedule's fired counters when chaos is active and
                    fleet=True marks aggregator-born findings
                    (obs/watchdog.py + obs/aggregator.py; the live
                    plane's /events and the Prometheus exposition in
                    obs/aggregator.py mirror this stream)
profile             dir, start, stop, captured
experience_reject   worker_id, seq, reason (duplicate | stale |
                    backoff | stale_at_apply | poisoned) + verdict
                    fields - one EXPERIENCE push the streaming learner
                    refused, counted never silently dropped
                    (streaming/learner.py)
params_refresh      worker_id, from_version, to_version - an actor
                    pulled fresh params (PARAMS_AT) after a STALE
                    verdict or on its proactive refresh cadence
actor_reconnect     worker_id, attempts, seq, version - an actor
                    re-registered with a (reincarnated) learner and
                    resumes pushing above its seq watermark;
                    pdrnn-metrics health treats a registered actor
                    with no push since as recovering, not stalled
learner_summary     updates, final_version, rejoins + ingest counters
                    - the streaming learner's verdict line
run_summary         memory_mb, duration_s, device_peaks_mb, steps,
                    nan_skipped, faults_fired, ledger (the trainer's
                    efficiency block: model_flops_per_step, backend,
                    device_kind/count, peak_flops_total,
                    peak_flops_estimated - see obs/ledger.py); the
                    PS master's variant
                    carries roster counts + rejoins + degraded_rounds;
                    the streaming learner's adds experience_batches,
                    experience_per_s, updates_per_s, stale_rejected,
                    queue_sheds, duplicates, poisoned,
                    staleness_p50/p95, final_version
=================== =======================================================

Span names on the ``member`` lane: ``state_sync`` (REGISTER -> params
adoption, emitted by both master and the joining worker - the
streaming actor/learner pair reuses it with the learner version in the
step slot).  Span names on the ``actor`` lane: ``experience_push``
(actor-side push exchange incl. retries/backoffs) and
``learner_update`` (one applied update with its staleness).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from pathlib import Path

from pytorch_distributed_rnn_tpu.obs.spans import NULL_SPAN, Span
from pytorch_distributed_rnn_tpu.utils import threadcheck

log = logging.getLogger(__name__)

SCHEMA_VERSION = 2

# env half of the CLI contract (the --metrics flag beats it), mirroring
# PDRNN_CHAOS: spawned worker processes inherit telemetry without CLI
# plumbing through every launcher layer
METRICS_ENV = "PDRNN_METRICS"
METRICS_SAMPLE_ENV = "PDRNN_METRICS_SAMPLE"
METRICS_HEARTBEAT_ENV = "PDRNN_METRICS_HEARTBEAT"

_DEFAULT_SAMPLE_EVERY = 16
_FLUSH_THRESHOLD = 256  # events buffered before the writer is signalled
_FLUSH_INTERVAL_S = 2.0  # writer wake cadence even below the threshold
_DEFAULT_HEARTBEAT_S = 5.0  # heartbeat cadence (0 disables)


def rank_suffixed(path, rank: int) -> Path:
    """The per-process sidecar path: rank 0 keeps ``path`` verbatim (the
    single-process case stays simple), other ranks insert ``-r<rank>``
    before the suffix so a multi-process world never interleaves writers
    in one file."""
    path = Path(path)
    if rank == 0:
        return path
    return path.with_name(f"{path.stem}-r{rank}{path.suffix}")


class NullRecorder:
    """Telemetry off: every hook is a no-op and ``enabled`` is False so
    instrumented loops skip their bookkeeping entirely - no thread, no
    fencing, no buffering."""

    enabled = False
    rank = 0
    sample_every = 0
    path = None

    def record(self, kind: str, **fields) -> None:  # noqa: PD105 - null object
        pass

    def is_sample_step(self, step: int) -> bool:
        return False

    def span(self, name: str, cat: str = "train", **attrs):
        """Disabled tracing: the shared no-op context manager - no clock
        reads, no allocation (the span half of the zero-overhead pin)."""
        return NULL_SPAN

    def emit_span(self, name, tm_start, dur_s, cat="train",  # noqa: PD105
                  **attrs) -> None:
        pass

    def note_progress(self, step: int) -> None:  # noqa: PD105 - null object
        pass

    progress = None

    def attach_live(self, live) -> None:
        raise RuntimeError(
            "live export needs an enabled recorder (--metrics / "
            "PDRNN_METRICS); the null recorder has no event stream to "
            "window"
        )

    def flush(self) -> None:  # noqa: PD105 - null object by design
        pass

    def close(self) -> None:  # noqa: PD105 - null object by design
        pass

    def __bool__(self) -> bool:
        return False


NULL_RECORDER = NullRecorder()


class MetricsRecorder:
    """Buffered JSONL event writer with a background flush thread."""

    enabled = True

    def __init__(self, path, rank: int = 0,
                 sample_every: int = _DEFAULT_SAMPLE_EVERY,
                 flush_threshold: int = _FLUSH_THRESHOLD,
                 meta: dict | None = None,
                 heartbeat_every_s: float = _DEFAULT_HEARTBEAT_S):
        if sample_every < 1:
            raise ValueError(
                f"metrics sample cadence must be >= 1, got {sample_every}"
            )
        self.rank = int(rank)
        self.sample_every = int(sample_every)
        self.path = rank_suffixed(path, self.rank)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        # lock-order: MetricsRecorder._io_lock -> MetricsRecorder._lock
        self._lock = threadcheck.lock(threading.Lock(), "recorder.buffer")  # guards: _buffer
        self._io_lock = threadcheck.lock(threading.Lock(), "recorder.io")
        self._buffer: list[dict] = []
        self._flush_threshold = int(flush_threshold)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._closed = False
        # heartbeats ride the writer thread's existing wake cadence (no
        # extra thread); 0 disables them.  The wake timeout shrinks to
        # the heartbeat interval when that is the tighter cadence.
        self._heartbeat_every = max(0.0, float(heartbeat_every_s))
        self._wake_timeout = (
            min(_FLUSH_INTERVAL_S, self._heartbeat_every)
            if self._heartbeat_every > 0 else _FLUSH_INTERVAL_S
        )
        self._hb_seq = 0
        # last step noted by the instrumented loops (note_progress): a
        # bare int store, read by the writer thread's heartbeats so a
        # stalled rank's heartbeats visibly stop advancing
        self._progress = None
        # the live plane (obs/live.py): None unless attach_live was
        # called - record() feeds it and the writer thread pushes its
        # digests, so live export adds NO thread of its own
        self._live = None
        # wall<->monotonic anchor: t and tm below describe the SAME
        # instant, so anchor + any event's tm reconstructs its wall time
        # on THIS rank's clock (obs/timeline.py aligns across ranks)
        t_wall, t_mono = time.time(), time.perf_counter()
        self._anchor = t_wall - t_mono
        # meta is the FIRST line, written synchronously: a sidecar that
        # exists always declares its schema, even if the run dies before
        # the first flush
        head = {
            "kind": "meta", "t": t_wall, "tm": t_mono, "rank": self.rank,
            "schema": SCHEMA_VERSION, "sample_every": self.sample_every,
        }
        head.update(meta or {})
        with open(self.path, "w") as f:
            f.write(json.dumps(head) + "\n")
        self._thread = threading.Thread(
            target=self._writer, name="pdrnn-metrics", daemon=True
        )
        self._thread.start()
        if threadcheck.installed():
            # the sentinel's violation alerts land in THIS sidecar, and
            # its faulthandler dumps next to it (stacks_path_for)
            threadcheck.install(recorder=self)
        from pytorch_distributed_rnn_tpu.utils import leakcheck

        if leakcheck.installed():
            # same self-register contract for the leak sentinel
            leakcheck.install(recorder=self)

    # -- construction --------------------------------------------------------

    @classmethod
    def resolve(cls, args, rank: int = 0, meta: dict | None = None):
        """The ONE CLI resolution path (``--metrics`` flag beats the
        ``PDRNN_METRICS`` env), shared by every strategy entry point so
        telemetry can never be silently dropped by one of them.  Returns
        :data:`NULL_RECORDER` when telemetry is off."""
        spec = getattr(args, "metrics", None) or os.environ.get(METRICS_ENV)
        if not spec:
            return NULL_RECORDER
        sample = getattr(args, "metrics_sample_every", None)
        if sample is None:
            sample = int(
                os.environ.get(METRICS_SAMPLE_ENV, _DEFAULT_SAMPLE_EVERY)
            )
        heartbeat = float(
            os.environ.get(METRICS_HEARTBEAT_ENV, _DEFAULT_HEARTBEAT_S)
        )
        return cls(spec, rank=rank, sample_every=int(sample), meta=meta,
                   heartbeat_every_s=heartbeat)

    # -- hot-path API --------------------------------------------------------

    def record(self, kind: str, **fields) -> None:
        # the (t, tm) stamp pair describes the record() instant; callers
        # emitting DEFERRED events (the trainer's post-loop step flush,
        # emit_span) override tm to the phase's true start - t is then
        # re-derived from the construction anchor so the two always
        # describe the SAME instant (the invariant the timeline's
        # cross-rank alignment and any t - tm anchor math rest on)
        event = {
            "kind": kind, "t": time.time(), "tm": time.perf_counter(),
            "rank": self.rank,
        }
        if "tm" in fields and "t" not in fields:
            event["t"] = self._anchor + float(fields["tm"])
        event.update(fields)
        live = self._live
        if live is not None:
            try:
                live.observe_event(event)
            except Exception:  # live telemetry must never kill the run
                log.exception("live window update failed")
        with self._lock:
            self._buffer.append(event)
            signal = len(self._buffer) >= self._flush_threshold
        if signal:
            self._wake.set()

    def span(self, name: str, cat: str = "train", **attrs) -> Span:
        """Context manager timing a ``span`` event (obs/spans.py)."""
        return Span(self, name, cat, attrs)

    def emit_span(self, name, tm_start, dur_s, cat="train",
                  **attrs) -> None:
        """Deferred span emission: ``tm_start`` is a ``perf_counter``
        value captured when the phase began; ``record`` derives the
        wall stamp from the construction-time anchor so t and tm stay
        one clock pair even across NTP steps."""
        self.record(
            "span", name=name, cat=cat, tm=float(tm_start),
            dur_s=float(dur_s), **attrs,
        )

    def note_progress(self, step: int) -> None:
        """Cheap per-step liveness note (one int store, no lock): the
        writer thread's heartbeats carry the latest value, so
        ``pdrnn-metrics health`` can tell a stalled rank (heartbeats
        fresh, progress frozen) from a dead one (heartbeats stale)."""
        self._progress = int(step)

    @property
    def progress(self) -> int | None:
        """The last ``note_progress`` value (live-plane/watchdog read)."""
        return self._progress

    def attach_live(self, live) -> None:
        """Bind a live exporter (obs/live.py): ``record`` feeds its
        rolling windows and the writer thread pushes its digests on the
        existing wake cadence - live export adds no thread here."""
        self._live = live

    def is_sample_step(self, step: int) -> bool:
        """Whether this step pays the fencing round-trip (step wall-time
        measurement): every ``sample_every``-th step, plus step 1 - the
        first STEADY-STATE step (step 0 carries the compile and is
        excluded from timing summaries), so even a short run has one
        honest fenced wall-time sample."""
        return step == 1 or step % self.sample_every == 0

    # -- writer --------------------------------------------------------------

    def _writer(self):
        next_hb = time.perf_counter() + self._heartbeat_every
        while not self._stop.is_set():
            self._wake.wait(timeout=self._wake_timeout)
            self._wake.clear()
            if self._heartbeat_every > 0:
                now = time.perf_counter()
                if now >= next_hb:
                    self._hb_seq += 1
                    self.record(
                        "heartbeat", seq=self._hb_seq,
                        progress=self._progress,
                    )
                    next_hb = now + self._heartbeat_every
            live = self._live
            if live is not None:
                try:
                    live.maybe_push()
                except Exception:  # pragma: no cover - must never kill
                    log.exception("live digest push failed")
            self._drain()
        self._drain()

    def _drain(self):
        # _io_lock serializes WHOLE drains: a caller-thread flush() (e.g.
        # the pre-kill chaos flush) racing the writer thread's timed drain
        # must not interleave its batch's buffered chunks mid-line with
        # the other's - a single torn line fails the strict loader for
        # the whole sidecar.  Holding it across the swap also keeps batch
        # order = record order.
        with self._io_lock:
            with self._lock:
                batch, self._buffer = self._buffer, []
            if not batch:
                return
            try:
                with open(self.path, "a") as f:
                    for event in batch:
                        f.write(json.dumps(event, default=_jsonable) + "\n")
            except OSError as exc:  # telemetry must never kill the run
                log.warning(f"metrics flush to {self.path} failed: {exc}")

    def flush(self) -> None:
        """Synchronous drain (tests and run teardown)."""
        self._drain()

    def close(self) -> None:
        """Stop the writer thread and flush everything; idempotent."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=5.0)
        self._drain()
        live = self._live
        if live is not None:
            # final digest AFTER the last drain: it carries the
            # run_summary-derived finished flag, so a live /health shows
            # the source finished instead of going dead
            try:
                live.push_now()
            except Exception:  # pragma: no cover - must never kill
                log.exception("final live digest push failed")

    def __del__(self):  # pragma: no cover - GC timing is interpreter-specific
        try:
            self.close()
        except Exception:
            pass


def _jsonable(value):
    """Last-resort coercion for numpy/jax scalars riding in events."""
    try:
        return float(value)
    except (TypeError, ValueError):
        return str(value)
