"""``pdrnn-metrics``: summarize / diff / stragglers / timeline / trace /
attribute / health / ledger / regress over metrics sidecars.

Exit-code contract (pinned by tests and used as a CI gate):

- ``0`` clean (summary/trace/table printed; no regression; no
  straggler; every rank healthy)
- ``1`` signal found (``diff``/``regress``: a regression past the
  threshold; ``stragglers``/``attribute``: a rank past the spread
  threshold; ``health``: a stalled or dead rank)
- ``2`` malformed input (unreadable file, bad JSONL, schema drift,
  or a sidecar too old for the requested view)

Examples::

  pdrnn-metrics summarize metrics.jsonl
  pdrnn-metrics diff baseline.jsonl candidate.jsonl --threshold 10
  pdrnn-metrics stragglers metrics.jsonl   # picks up -r<k> siblings
  pdrnn-metrics timeline metrics.jsonl -o run.trace.json  # -> Perfetto
  pdrnn-metrics trace router.jsonl replica.jsonl --slowest 3
  pdrnn-metrics trace router.jsonl replica.jsonl --request 42
  pdrnn-metrics attribute metrics.jsonl    # phase fractions + blame
  pdrnn-metrics health metrics.jsonl --stale-after 30
  pdrnn-metrics watch 127.0.0.1:9100       # live fleet table (aggregator)
  pdrnn-metrics top 127.0.0.1:9100         # + sparklines, burn, capacity
  pdrnn-metrics ledger metrics.jsonl --history ledger_history.jsonl
  pdrnn-metrics regress ledger_history.jsonl --threshold 0.2
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from pytorch_distributed_rnn_tpu.obs.summary import (
    MalformedMetricsError,
    detect_stragglers,
    diff_summaries,
    load_events,
    rank_files,
    rank_health,
    summarize_file,
)

_SUMMARY_FIELDS = (
    ("steps", "{:d}"),
    ("epochs", "{:d}"),
    ("loss_first", "{:.6f}"),
    ("loss_last", "{:.6f}"),
    ("step_s_mean", "{:.6f}"),
    ("step_s_p50", "{:.6f}"),
    ("step_s_p95", "{:.6f}"),
    ("data_wait_frac", "{:.4f}"),
    # overlapped gradient communication (None and skipped on runs whose
    # step fn publishes no comm telemetry)
    ("comm_wait_s", "{:.6f}"),
    ("comm_wait_s_mean", "{:.6f}"),
    ("overlap_frac", "{:.4f}"),
    ("collective_bytes_per_step", "{:,d}"),
    # phase split: gradient = all-reduce; update = reduce-scatter +
    # all-gather (the sharded weight update's ~2x drop shows up here)
    ("collective_grad_bytes_per_step", "{:,d}"),
    ("collective_update_bytes_per_step", "{:,d}"),
    ("duration_s", "{:.3f}"),
    ("memory_mb", "{:.1f}"),
    ("device_peak_mb", "{:.1f}"),
    ("nan_skipped", "{:d}"),
    ("alerts", "{:d}"),
    ("alerts_by_kind", "{}"),
    ("ps_exchanges", "{:d}"),
    ("ps_retries", "{:d}"),
    ("ps_degraded_rounds", "{:d}"),
    # elastic membership (None and skipped on non-elastic runs)
    ("member_joins", "{:d}"),
    ("member_rejoins", "{:d}"),
    ("member_drains", "{:d}"),
    ("member_deaths", "{:d}"),
    # MPMD pipelines (None and skipped on non-pipeline runs)
    ("stage_restarts", "{:d}"),
    ("replayed_microbatches", "{:d}"),
    ("roster", "{}"),
    ("checkpoint_saves", "{:d}"),
    # efficiency ledger (None and skipped on schema-1 sidecars; the
    # full phase table lives under `pdrnn-metrics ledger`)
    ("recompiles", "{:d}"),
    ("goodput", "{:.4f}"),
    ("badput_frac", "{:.4f}"),
    ("fault_tax_s", "{:.6f}"),
    ("comm_wait_frac", "{:.4f}"),
    ("mfu_est", "{:.3e}"),
    # serving runs (absent on training sidecars - skipped when None)
    ("requests", "{:d}"),
    ("requests_shed", "{:d}"),
    ("requests_failed", "{:d}"),
    ("tokens_out", "{:d}"),
    ("tokens_per_s", "{:.1f}"),
    ("latency_s_p50", "{:.6f}"),
    ("latency_s_p95", "{:.6f}"),
    ("ttft_s_p50", "{:.6f}"),
    ("ttft_s_p95", "{:.6f}"),
    ("queue_s_p50", "{:.6f}"),
    ("queue_s_p95", "{:.6f}"),
    ("queue_depth_p50", "{:.0f}"),
    ("queue_depth_p95", "{:.0f}"),
    ("queue_depth_max", "{:.0f}"),
    # streaming actor/learner runs (absent on everything else - the
    # summary only carries these keys off a streaming learner's
    # run_summary, so None-means-skip keeps other runs noise-free)
    ("experience_batches", "{:d}"),
    ("experience_per_s", "{:.1f}"),
    ("updates_per_s", "{:.1f}"),
    ("stale_rejected", "{:d}"),
    ("queue_sheds", "{:d}"),
    ("duplicates", "{:d}"),
    ("poisoned", "{:d}"),
    ("staleness_p50", "{:.0f}"),
    ("staleness_p95", "{:.0f}"),
    ("final_version", "{:d}"),
    ("rejoins", "{:d}"),
)


def _print_summary(summary: dict, out=print):
    out(f"{summary['path']} (rank {summary['rank']})")
    for field, fmt in _SUMMARY_FIELDS:
        value = summary.get(field)
        if value is None or value == {}:
            continue
        try:
            rendered = fmt.format(value)
        except (TypeError, ValueError):
            rendered = str(value)
        out(f"  {field:26s} {rendered}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="pdrnn-metrics", description=(
        "Summarize, diff and straggler-scan pdrnn metrics JSONL sidecars"
    ))
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("summarize", help="per-rank run summary")
    p.add_argument("files", nargs="+", help="metrics JSONL sidecar(s)")
    p.add_argument("--json", action="store_true", help="machine output")

    p = sub.add_parser("diff", help="regression check candidate vs baseline")
    p.add_argument("baseline")
    p.add_argument("candidate")
    p.add_argument("--threshold", type=float, default=10.0, metavar="PCT",
                   help="regression tolerance in percent (default 10)")
    p.add_argument("--json", action="store_true")

    p = sub.add_parser(
        "stragglers",
        help="cross-rank step-time spread (rank-suffixed siblings "
        "of each file are included automatically)",
    )
    p.add_argument("files", nargs="+")
    p.add_argument("--threshold", type=float, default=0.25, metavar="FRAC",
                   help="flag ranks this fraction above the median step "
                   "time (default 0.25)")
    p.add_argument("--json", action="store_true")

    p = sub.add_parser(
        "timeline",
        help="export the run (rank-0 sidecar + -r<k> siblings) as a "
        "clock-aligned Chrome trace-event JSON for Perfetto",
    )
    p.add_argument("file", help="the run's rank-0 metrics sidecar")
    p.add_argument("-o", "--output", default=None, metavar="PATH",
                   help="trace output path (default: <file>.trace.json)")
    p.add_argument("--json", action="store_true",
                   help="print a machine summary of the export")

    p = sub.add_parser(
        "trace",
        help="assemble distributed request traces (obs/tracectx.py "
        "span contexts recorded across router + replica sidecars) into "
        "span trees with critical-path attribution",
    )
    p.add_argument("files", nargs="+",
                   help="sidecar path(s) - pass the router's AND the "
                   "replicas' families; -r<k> siblings are picked up "
                   "automatically")
    p.add_argument("--request", default=None, metavar="ID",
                   help="only traces whose request id matches, or whose "
                   "trace id starts with ID")
    p.add_argument("--slowest", type=int, default=None, metavar="N",
                   help="only the N slowest traces (default: all, "
                   "slowest first)")
    p.add_argument("--json", action="store_true", help="machine output")

    p = sub.add_parser(
        "attribute",
        help="per-rank phase attribution: sampled step time decomposed "
        "into data-wait / dispatch / device / exchange fractions, plus "
        "phase-blamed straggler detection",
    )
    p.add_argument("files", nargs="+")
    p.add_argument("--threshold", type=float, default=0.25, metavar="FRAC",
                   help="flag ranks this fraction above the median step "
                   "time (default 0.25)")
    p.add_argument("--json", action="store_true")

    p = sub.add_parser(
        "health",
        help="liveness check: flag ranks whose telemetry went stale "
        "(dead) or whose heartbeats continue without progress (stalled); "
        "a rank that DEREGISTERed (member_drain - the SIGTERM drain "
        "path) is 'drained' and healthy, not dead, and a respawned MPMD "
        "stage still restoring/retracing after a stage_restart - or a "
        "streaming actor registered with the learner but not yet "
        "pushing - is 'recovering', not stalled",
    )
    p.add_argument("files", nargs="+")
    p.add_argument("--stale-after", type=float, default=30.0, metavar="S",
                   help="seconds without progress/events before a rank "
                   "is flagged (default 30)")
    p.add_argument("--now", type=float, default=None, metavar="EPOCH",
                   help="reference wall time (default: the current time; "
                   "pass a run-contemporary stamp for post-hoc checks)")
    p.add_argument("--json", action="store_true")

    p = sub.add_parser(
        "watch",
        help="poll a live aggregator (obs/aggregator.py - the --live "
        "flag / PDRNN_LIVE run-side) and render the fleet table: one "
        "row per source with status, step-time window, loss, queue "
        "depth and recent alerts",
    )
    p.add_argument("target", help="aggregator address (HOST:PORT or "
                   "http://HOST:PORT)")
    p.add_argument("--interval", type=float, default=2.0, metavar="S",
                   help="poll cadence in seconds (default 2)")
    p.add_argument("--once", action="store_true",
                   help="print one snapshot and exit (0 healthy, 1 if "
                   "any source is stalled/dead - the health exit "
                   "contract)")
    p.add_argument("--json", action="store_true",
                   help="print the raw fleet+events JSON instead of the "
                   "table (implies --once)")

    p = sub.add_parser(
        "top",
        help="live fleet view over an aggregator that hosts the "
        "time-series store (the --live anchor): one row per source "
        "with load gauges and 60s sparklines, the store's capacity "
        "signals (live vs recommended replicas), and the active SLO "
        "error-budget burn alerts (a slo_burn with no later "
        "slo_burn_cleared for that qos)",
    )
    p.add_argument("target", help="aggregator address (HOST:PORT or "
                   "http://HOST:PORT)")
    p.add_argument("--interval", type=float, default=2.0, metavar="S",
                   help="poll cadence in seconds (default 2)")
    p.add_argument("--window", type=float, default=60.0, metavar="S",
                   help="sparkline window in seconds (default 60)")
    p.add_argument("--once", action="store_true",
                   help="print one snapshot and exit (0 healthy, 1 if "
                   "any source is stalled/dead or a burn alert is "
                   "active)")
    p.add_argument("--json", action="store_true",
                   help="print the snapshot as JSON instead of the "
                   "table (implies --once)")

    p = sub.add_parser(
        "ledger",
        help="efficiency ledger: classify the run's wall-clock into "
        "phase fractions (summing to 1), goodput, MFU/HFU vs the "
        "per-backend peak table (CPU peak is an estimate), and fault "
        "tax; per-stage ledgers + bubble fraction on MPMD runs, "
        "actor/learner split on streaming runs",
    )
    p.add_argument("files", nargs="+", help="rank-0 sidecar(s); -r<k> "
                   "siblings are picked up automatically")
    p.add_argument("--json", action="store_true", help="machine output")
    p.add_argument("--history", default=None, metavar="PATH",
                   help="append each run's aggregate to this "
                   "ledger_history.jsonl (the `regress` gate's input)")
    p.add_argument("--key", default=None, metavar="KEY",
                   help="config key for the history record (default: "
                   "the sidecar's stem)")

    p = sub.add_parser(
        "regress",
        help="cross-run regression gate over a ledger_history.jsonl: "
        "latest run per key vs the median of its predecessors "
        "(goodput drop, fault-tax / comm-wait fraction rise)",
    )
    p.add_argument("history", help="ledger_history.jsonl path")
    p.add_argument("--threshold", type=float, default=0.2, metavar="FRAC",
                   help="relative tolerance (default 0.2)")
    p.add_argument("--floor", type=float, default=0.05, metavar="FRAC",
                   help="absolute tolerance in fraction points a "
                   "regression must also clear (default 0.05)")
    p.add_argument("--json", action="store_true")

    args = parser.parse_args(argv)
    try:
        return _dispatch(args)
    except MalformedMetricsError as exc:
        print(f"pdrnn-metrics: {exc}", file=sys.stderr)
        return 2
    except BrokenPipeError:  # `pdrnn-metrics ... | head` is fine
        return 0


def _dispatch(args) -> int:
    if args.cmd == "summarize":
        summaries = [summarize_file(path) for path in args.files]
        if args.json:
            print(json.dumps(summaries, indent=1))
        else:
            for summary in summaries:
                _print_summary(summary)
        return 0

    if args.cmd == "diff":
        base = summarize_file(args.baseline)
        cand = summarize_file(args.candidate)
        regressions = diff_summaries(base, cand, args.threshold)
        if args.json:
            print(json.dumps(regressions, indent=1))
        else:
            if not regressions:
                print(
                    f"no regression past {args.threshold:g}% "
                    f"({args.candidate} vs {args.baseline})"
                )
            for r in regressions:
                print(
                    f"REGRESSION {r['metric']}: {r['baseline']:.6g} -> "
                    f"{r['candidate']:.6g} (+{r['delta_pct']:.1f}%)"
                )
        return 1 if regressions else 0

    if args.cmd == "timeline":
        return _timeline(args)
    if args.cmd == "trace":
        return _trace(args)
    if args.cmd == "attribute":
        return _attribute(args)
    if args.cmd == "health":
        return _health(args)
    if args.cmd == "watch":
        return _watch(args)
    if args.cmd == "top":
        return _top(args)
    if args.cmd == "ledger":
        return _ledger(args)
    if args.cmd == "regress":
        return _regress(args)

    # stragglers
    summaries = [summarize_file(p) for p in _expand_families(args.files)]
    summaries.sort(key=lambda s: s["rank"])
    flagged = detect_stragglers(summaries, args.threshold)
    if args.json:
        print(json.dumps(flagged, indent=1))
    else:
        if not flagged:
            print(
                f"no straggler past {args.threshold:g}x-over-median "
                f"across {len(summaries)} rank(s)"
            )
        for f in flagged:
            print(
                f"STRAGGLER rank {f['rank']}: mean step "
                f"{f['step_s_mean']:.6f}s vs median {f['median_s']:.6f}s "
                f"(+{100 * f['excess_frac']:.0f}%)"
            )
    return 1 if flagged else 0


def _expand_families(paths) -> list[Path]:
    """Every given path expanded to its rank family so the common case
    (pass the rank-0 sidecar) sees the whole world.  Dedup by resolved
    path: a shell glob passes the -r<k> siblings explicitly TOO, and a
    double-counted rank shifts medians onto the outlier, masking it."""
    members, seen = [], set()
    for path in paths:
        family = rank_files(path)
        if not family:
            raise MalformedMetricsError(f"{path}: no metrics sidecar found")
        for member in family:
            resolved = Path(member).resolve()
            if resolved in seen:
                continue
            seen.add(resolved)
            members.append(member)
    return members


def _timeline(args) -> int:
    from pytorch_distributed_rnn_tpu.obs.timeline import write_chrome_trace

    out = args.output or str(
        Path(args.file).with_suffix("")
    ) + ".trace.json"
    try:
        trace = write_chrome_trace(args.file, out)
    except ValueError as exc:
        # a validator rejection of our own export is still bad INPUT
        # from the caller's perspective (a sidecar the exporter cannot
        # render consistently) - same exit as malformed JSONL
        raise MalformedMetricsError(str(exc)) from exc
    summary = {
        "trace": str(out),
        "ranks": trace["otherData"]["ranks"],
        "events": len(trace["traceEvents"]),
        "clock_offsets_s": trace["otherData"]["clock_offsets_s"],
    }
    if args.json:
        print(json.dumps(summary, indent=1))
    else:
        print(
            f"wrote {out}: {summary['events']} trace events across "
            f"{len(summary['ranks'])} rank(s) - open in "
            "https://ui.perfetto.dev or chrome://tracing"
        )
    return 0


def _trace(args) -> int:
    from pytorch_distributed_rnn_tpu.obs.trace import (
        assemble_traces,
        format_trace_tree,
        format_traces_json,
        validate_trace_tree,
    )

    trees = assemble_traces(args.files, request=args.request)
    if args.slowest is not None:
        trees = trees[:max(0, args.slowest)]
    for tree in trees:
        # self-check the assembly before presenting it: a tree that
        # fails its own invariants is malformed input, not a finding
        validate_trace_tree(tree)
    if args.json:
        print(format_traces_json(trees))
        return 0
    if not trees:
        what = f" matching {args.request!r}" if args.request else ""
        print(
            f"no request trace{what} in the given sidecars (record "
            "with tracing on: pdrnn-router --trace-sample / "
            "pdrnn-loadgen --trace-sample, plus --metrics everywhere)"
        )
        return 0
    for tree in trees:
        print(format_trace_tree(tree))
    return 0


def _attribute(args) -> int:
    from pytorch_distributed_rnn_tpu.obs.timeline import (
        PHASES,
        attribute_rank,
        attribute_stragglers,
    )

    attributions = []
    for member in _expand_families(args.files):
        events = load_events(member)
        attr = attribute_rank(events)
        if attr is not None:
            attr["path"] = str(member)
            attributions.append(attr)
    attributions.sort(key=lambda a: a["rank"])
    flagged = attribute_stragglers(attributions, args.threshold)
    if args.json:
        print(json.dumps(
            {"ranks": attributions, "stragglers": flagged}, indent=1
        ))
        return 1 if flagged else 0
    if not attributions:
        print("no attributable rank (no fenced step samples - raise the "
              "--metrics-sample-every cadence)")
        return 0
    header = f"{'rank':>4} {'steps':>5} {'step_s':>10} " + " ".join(
        f"{p:>9}" for p in PHASES
    )
    print(header)
    for a in attributions:
        fr = a["fractions"]
        print(
            f"{a['rank']:>4} {a['steps_sampled']:>5} "
            f"{a['step_s_mean']:>10.6f} "
            + " ".join(f"{100 * fr[p]:>8.1f}%" for p in PHASES)
        )
    for f in flagged:
        print(
            f"STRAGGLER rank {f['rank']}: mean step "
            f"{f['step_s_mean']:.6f}s vs median {f['median_s']:.6f}s "
            f"(+{100 * f['excess_frac']:.0f}%), dominated by "
            f"{f['phase']} (+{f['phase_excess_s']:.6f}s/step vs median)"
        )
    return 1 if flagged else 0


def _watch_fetch(base: str, path: str):
    import urllib.request

    try:
        with urllib.request.urlopen(base + path, timeout=5.0) as resp:
            return json.loads(resp.read())
    except OSError as exc:
        # /health replies 503 when a source is stalled/dead - that is a
        # VALID payload for the watch table, not a fetch failure
        body = getattr(exc, "read", lambda: None)()
        if body:
            try:
                return json.loads(body)
            except ValueError:
                pass
        raise MalformedMetricsError(
            f"{base}{path}: aggregator unreachable ({exc})"
        ) from exc


def _watch_row(source_id: str, digest: dict) -> str:
    step = digest.get("step_s") or {}
    loss = digest.get("loss") or {}
    depth = digest.get("queue_depth") or {}
    serving = digest.get("serving") or {}

    def num(value, fmt="{:.4f}"):
        return fmt.format(value) if value is not None else "-"

    return (
        f"{source_id:>14} {str(digest.get('status', '?')):>9} "
        f"{num(digest.get('progress'), '{:d}'):>8} "
        f"{num(step.get('p50')):>10} {num(step.get('p95')):>10} "
        f"{num(loss.get('last')):>10} "
        f"{num(depth.get('last'), '{:.0f}'):>6} "
        f"{num(serving.get('req_per_s_60s'), '{:.1f}'):>7} "
        f"{num(digest.get('alerts_total'), '{:d}'):>7}"
    )


def _watch(args) -> int:
    base = args.target
    if "://" not in base:
        base = f"http://{base}"
    base = base.rstrip("/")
    header = (
        f"{'source':>14} {'status':>9} {'step':>8} {'p50_s':>10} "
        f"{'p95_s':>10} {'loss':>10} {'queue':>6} {'req/s':>7} "
        f"{'alerts':>7}"
    )
    while True:
        fleet = _watch_fetch(base, "/fleet")
        events = _watch_fetch(base, "/events")
        sources = fleet.get("sources") or {}
        flagged = any(
            d.get("status") in ("stalled", "dead")
            for d in sources.values()
        )
        if args.json:
            print(json.dumps({"fleet": fleet, "events": events}, indent=1))
            return 1 if flagged else 0
        print(f"== {base} @ {time.strftime('%H:%M:%S')} "
              f"({len(sources)} source(s))")
        print(header)
        for source_id in sorted(sources):
            line = _watch_row(source_id, sources[source_id])
            if sources[source_id].get("status") in ("stalled", "dead"):
                line = line.upper()
            print(line)
        for event in events[-5:]:
            print(
                f"  ALERT {event.get('source', '?')}: "
                f"{event.get('alert', '?')} "
                f"[{event.get('severity', '?')}] seq={event.get('seq')}"
            )
        if args.once:
            return 1 if flagged else 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:  # pragma: no cover - interactive
            return 0


_SPARK_GLYPHS = "▁▂▃▄▅▆▇█"


def _spark(values, width: int = 24) -> str:
    """Resample ``values`` into ``width`` buckets and render a unicode
    sparkline scaled to the window's own max (flat-zero stays flat)."""
    values = [v for v in values if v is not None]
    if not values:
        return "-"
    if len(values) > width:
        # bucket-mean resample so a 60s window fits the column
        step = len(values) / width
        values = [
            sum(chunk) / len(chunk)
            for chunk in (
                values[int(i * step):max(int(i * step) + 1,
                                         int((i + 1) * step))]
                for i in range(width)
            )
        ]
    top = max(values)
    if top <= 0:
        return _SPARK_GLYPHS[0] * len(values)
    return "".join(
        _SPARK_GLYPHS[min(len(_SPARK_GLYPHS) - 1,
                          int(max(0.0, v) / top * (len(_SPARK_GLYPHS) - 1)))]
        for v in values
    )


def _series_values(points, kind: str) -> list:
    """Plottable value per point: gauges use value/mean, counters use
    the per-bucket rate (raw cumulative points are differenced)."""
    if kind == "counter":
        vals, prev = [], None
        for p in points:
            if "rate" in p:
                vals.append(p["rate"])
                continue
            v = p.get("value")
            if prev is not None and v is not None:
                vals.append(max(0.0, v - prev))
            prev = v
        return vals
    return [
        p.get("mean", p.get("value"))
        for p in points
        if p.get("mean", p.get("value")) is not None
    ]


def _top_series(base: str, name: str, window: float, agg=None):
    """GET /series, or None when the anchor hosts no store (404 /
    pre-store aggregator)."""
    from urllib.parse import urlencode

    query = {"name": name, "window": f"{window:g}"}
    if agg:
        query["agg"] = agg
    try:
        payload = _watch_fetch(base, "/series?" + urlencode(query))
    except MalformedMetricsError:
        return None
    if not isinstance(payload, dict) or "series" not in payload:
        return None
    return payload


def _active_burns(events) -> list[dict]:
    """The slo_burn alerts with no later slo_burn_cleared for the same
    (source, qos) - the fleet's currently-burning error budgets."""
    active: dict = {}
    for event in events:
        kind = event.get("alert")
        key = (event.get("source"), event.get("qos"))
        if kind == "slo_burn":
            active[key] = event
        elif kind == "slo_burn_cleared":
            active.pop(key, None)
    return list(active.values())


def _top_row(source_id: str, digest: dict, queue_spark: str,
             rate_spark: str) -> str:
    serving = digest.get("serving") or {}
    router = digest.get("router") or {}
    depth = digest.get("queue_depth") or {}

    def num(value, fmt="{:.1f}"):
        return fmt.format(value) if value is not None else "-"

    active = serving.get("active")
    if active is None:
        active = router.get("inflight")
    rate = serving.get("req_per_s_60s")
    if rate is None:
        rate = router.get("req_per_s_60s")
    return (
        f"{source_id:>14} {str(digest.get('status', '?')):>9} "
        f"{num(active, '{:.0f}'):>6} "
        f"{num(depth.get('last'), '{:.0f}'):>6} "
        f"{num(rate):>7} "
        f"{queue_spark:<24} {rate_spark:<24}"
    )


def _top(args) -> int:
    base = args.target
    if "://" not in base:
        base = f"http://{base}"
    base = base.rstrip("/")
    window = args.window
    header = (
        f"{'source':>14} {'status':>9} {'active':>6} {'queue':>6} "
        f"{'req/s':>7} {'queue ' + format(window, 'g') + 's':<24} "
        f"{'req/s ' + format(window, 'g') + 's':<24}"
    )
    while True:
        fleet = _watch_fetch(base, "/fleet")
        events = _watch_fetch(base, "/events")
        sources = fleet.get("sources") or {}
        burns = _active_burns(events)
        flagged = any(
            d.get("status") in ("stalled", "dead")
            for d in sources.values()
        ) or bool(burns)

        sparks: dict = {}  # name -> {source -> values}
        fetched: dict = {}
        for name in ("pdrnn_queue_depth",
                     "pdrnn_serving_request_rate_per_s",
                     "pdrnn_router_request_rate_per_s"):
            resp = _top_series(base, name, window)
            fetched[name] = resp
            per_source: dict = {}
            for s in (resp or {}).get("series") or []:
                source = (s.get("labels") or {}).get("source")
                if source is not None:
                    per_source[source] = _series_values(
                        s["points"], s.get("kind", "gauge"))
            sparks[name] = per_source
        capacity = {}
        for name in ("pdrnn_replicas_live", "pdrnn_recommended_replicas"):
            resp = _top_series(base, name, window, agg="last")
            series = (resp or {}).get("series") or []
            capacity[name] = series[0].get("value") if series else None

        if args.json:
            print(json.dumps({
                "fleet": fleet, "events": events,
                "capacity": capacity, "active_burns": burns,
                "series": fetched,
            }, indent=1))
            return 1 if flagged else 0
        live = capacity.get("pdrnn_replicas_live")
        want = capacity.get("pdrnn_recommended_replicas")
        cap_txt = ""
        if live is not None or want is not None:
            cap_txt = (
                f"  replicas live "
                f"{'-' if live is None else format(live, '.0f')}"
                f" / recommended "
                f"{'-' if want is None else format(want, '.0f')}"
            )
        print(f"== {base} @ {time.strftime('%H:%M:%S')} "
              f"({len(sources)} source(s)){cap_txt}")
        print(header)
        rate_by_source = dict(
            sparks["pdrnn_serving_request_rate_per_s"])
        rate_by_source.update(sparks["pdrnn_router_request_rate_per_s"])
        for source_id in sorted(sources):
            line = _top_row(
                source_id, sources[source_id],
                _spark(sparks["pdrnn_queue_depth"].get(source_id, [])),
                _spark(rate_by_source.get(source_id, [])),
            )
            if sources[source_id].get("status") in ("stalled", "dead"):
                line = line.upper()
            print(line)
        for burn in burns:
            fast = burn.get("burn_rate_fast")
            slow = burn.get("burn_rate_slow")
            print(
                f"  BURN {burn.get('source', '?')} "
                f"qos={burn.get('qos', '?')}: fast "
                f"{'-' if fast is None else format(fast, '.1f')}x / slow "
                f"{'-' if slow is None else format(slow, '.1f')}x budget "
                f"({burn.get('objective', '?')})"
            )
        if not burns:
            print("  no active burn alert")
        if args.once:
            return 1 if flagged else 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:  # pragma: no cover - interactive
            return 0


def _ledger(args) -> int:
    from pytorch_distributed_rnn_tpu.obs.ledger import (
        LEDGER_PHASES,
        append_history,
        history_record,
        ledger_run,
    )

    runs = [ledger_run(path) for path in args.files]
    if args.history:
        for path, run in zip(args.files, runs):
            key = args.key or Path(path).stem
            append_history(args.history, history_record(run, key))
    if args.json:
        print(json.dumps(runs, indent=1))
        return 0
    header = f"{'rank':>5} {'wall_s':>9} " + " ".join(
        f"{p:>9}" for p in LEDGER_PHASES
    )
    for run in runs:
        print(f"{run['path']}")
        print(header)
        for r in run["ranks"]:
            fr = r["fractions"]
            label = (f"s{r['stage']}" if r.get("stage") is not None
                     else str(r["rank"]))
            print(
                f"{label:>5} {r['wall_s']:>9.3f} "
                + " ".join(f"{100 * fr[p]:>8.1f}%" for p in LEDGER_PHASES)
            )
        agg = run["aggregate"]
        mfu = agg["mfu_est"]
        mfu_txt = "-" if mfu is None else "{:.2e}{}".format(
            mfu, " (peak estimated)" if agg.get("peak_estimated") else ""
        )
        print(
            f"  goodput {agg['goodput']:.4f}  mfu {mfu_txt}  "
            f"fault_tax_s {agg['fault_tax_s']:.3f}  "
            f"comm_wait_frac {agg['comm_wait_frac']:.4f}  "
            f"recompiles {agg['recompiles']}"
        )
        if "mpmd" in run:
            bubble = run["mpmd"]["bubble_frac"]
            print(
                "  pipeline bubble_frac "
                + ("-" if bubble is None else f"{bubble:.4f}")
                + " (lower bound; stage steps time link waits too)"
            )
        if "streaming" in run:
            learner = run["streaming"]["learner"] or {}
            actors = run["streaming"]["actors"]
            tax = learner.get("reject_tax_s")
            print(
                f"  streaming: {actors['count']} actor(s), learner "
                "reject_tax_s "
                + ("-" if tax is None else f"{tax:.3f}")
            )
    return 0


def _regress(args) -> int:
    from pytorch_distributed_rnn_tpu.obs.ledger import (
        check_history,
        load_history,
    )

    verdict = check_history(
        load_history(args.history), threshold=args.threshold,
        floor=args.floor,
    )
    if args.json:
        print(json.dumps(verdict, indent=1))
    else:
        if not verdict["regressions"]:
            print(
                f"no ledger regression across {verdict['compared']} "
                f"comparable key(s) of {verdict['keys']} "
                f"(threshold {args.threshold:g}, floor {args.floor:g})"
            )
        for r in verdict["regressions"]:
            print(
                f"REGRESSION {r['key']}: {r['metric']} "
                f"{r['prior_median']:.4f} -> {r['latest']:.4f} "
                f"({r['delta']:+.4f})"
            )
    return 1 if verdict["regressions"] else 0


def _health(args) -> int:
    reports = [
        {**rank_health(load_events(m), now=args.now,
                       stale_after=args.stale_after), "path": str(m)}
        for m in _expand_families(args.files)
    ]
    reports.sort(key=lambda r: r["rank"])
    flagged = [r for r in reports if r["status"] in ("stalled", "dead")]
    if args.json:
        print(json.dumps(reports, indent=1))
        return 1 if flagged else 0
    for r in reports:
        line = (
            f"rank {r['rank']}: {r['status']} (last event "
            f"{r['last_event_age_s']:.1f}s ago, last progress "
            f"{r['last_progress_age_s']:.1f}s ago)"
        )
        if r["status"] in ("stalled", "dead"):
            line = line.upper()
        print(line)
    return 1 if flagged else 0


if __name__ == "__main__":
    sys.exit(main())
