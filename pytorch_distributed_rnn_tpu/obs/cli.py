"""``pdrnn-metrics``: summarize / diff / stragglers over metrics sidecars.

Exit-code contract (pinned by tests and used as a CI gate):

- ``0`` clean (summary printed; no regression; no straggler)
- ``1`` signal found (``diff``: a regression past the threshold;
  ``stragglers``: a rank past the spread threshold)
- ``2`` malformed input (unreadable file, bad JSONL, schema drift)

Examples::

  pdrnn-metrics summarize metrics.jsonl
  pdrnn-metrics diff baseline.jsonl candidate.jsonl --threshold 10
  pdrnn-metrics stragglers metrics.jsonl   # picks up -r<k> siblings
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from pytorch_distributed_rnn_tpu.obs.summary import (
    MalformedMetricsError,
    detect_stragglers,
    diff_summaries,
    rank_files,
    summarize_file,
)

_SUMMARY_FIELDS = (
    ("steps", "{:d}"),
    ("epochs", "{:d}"),
    ("loss_first", "{:.6f}"),
    ("loss_last", "{:.6f}"),
    ("step_s_mean", "{:.6f}"),
    ("step_s_p50", "{:.6f}"),
    ("step_s_p95", "{:.6f}"),
    ("data_wait_frac", "{:.4f}"),
    ("collective_bytes_per_step", "{:,d}"),
    ("duration_s", "{:.3f}"),
    ("memory_mb", "{:.1f}"),
    ("device_peak_mb", "{:.1f}"),
    ("nan_skipped", "{:d}"),
    ("ps_exchanges", "{:d}"),
    ("ps_retries", "{:d}"),
    ("ps_degraded_rounds", "{:d}"),
    ("checkpoint_saves", "{:d}"),
)


def _print_summary(summary: dict, out=print):
    out(f"{summary['path']} (rank {summary['rank']})")
    for field, fmt in _SUMMARY_FIELDS:
        value = summary.get(field)
        if value is None or value == {}:
            continue
        try:
            rendered = fmt.format(value)
        except (TypeError, ValueError):
            rendered = str(value)
        out(f"  {field:26s} {rendered}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="pdrnn-metrics", description=(
        "Summarize, diff and straggler-scan pdrnn metrics JSONL sidecars"
    ))
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("summarize", help="per-rank run summary")
    p.add_argument("files", nargs="+", help="metrics JSONL sidecar(s)")
    p.add_argument("--json", action="store_true", help="machine output")

    p = sub.add_parser("diff", help="regression check candidate vs baseline")
    p.add_argument("baseline")
    p.add_argument("candidate")
    p.add_argument("--threshold", type=float, default=10.0, metavar="PCT",
                   help="regression tolerance in percent (default 10)")
    p.add_argument("--json", action="store_true")

    p = sub.add_parser(
        "stragglers",
        help="cross-rank step-time spread (rank-suffixed siblings "
        "of each file are included automatically)",
    )
    p.add_argument("files", nargs="+")
    p.add_argument("--threshold", type=float, default=0.25, metavar="FRAC",
                   help="flag ranks this fraction above the median step "
                   "time (default 0.25)")
    p.add_argument("--json", action="store_true")

    args = parser.parse_args(argv)
    try:
        return _dispatch(args)
    except MalformedMetricsError as exc:
        print(f"pdrnn-metrics: {exc}", file=sys.stderr)
        return 2
    except BrokenPipeError:  # `pdrnn-metrics ... | head` is fine
        return 0


def _dispatch(args) -> int:
    if args.cmd == "summarize":
        summaries = [summarize_file(path) for path in args.files]
        if args.json:
            print(json.dumps(summaries, indent=1))
        else:
            for summary in summaries:
                _print_summary(summary)
        return 0

    if args.cmd == "diff":
        base = summarize_file(args.baseline)
        cand = summarize_file(args.candidate)
        regressions = diff_summaries(base, cand, args.threshold)
        if args.json:
            print(json.dumps(regressions, indent=1))
        else:
            if not regressions:
                print(
                    f"no regression past {args.threshold:g}% "
                    f"({args.candidate} vs {args.baseline})"
                )
            for r in regressions:
                print(
                    f"REGRESSION {r['metric']}: {r['baseline']:.6g} -> "
                    f"{r['candidate']:.6g} (+{r['delta_pct']:.1f}%)"
                )
        return 1 if regressions else 0

    # stragglers: expand every given path to its rank family so the
    # common case (pass the rank-0 sidecar) sees the whole world.
    # Dedup by resolved path: a shell glob passes the -r<k> siblings
    # explicitly TOO, and a double-counted rank shifts the median onto
    # the straggler, masking it.
    summaries, seen = [], set()
    for path in args.files:
        family = rank_files(path)
        if not family:
            raise MalformedMetricsError(f"{path}: no metrics sidecar found")
        for member in family:
            resolved = Path(member).resolve()
            if resolved in seen:
                continue
            seen.add(resolved)
            summaries.append(summarize_file(member))
    summaries.sort(key=lambda s: s["rank"])
    flagged = detect_stragglers(summaries, args.threshold)
    if args.json:
        print(json.dumps(flagged, indent=1))
    else:
        if not flagged:
            print(
                f"no straggler past {args.threshold:g}x-over-median "
                f"across {len(summaries)} rank(s)"
            )
        for f in flagged:
            print(
                f"STRAGGLER rank {f['rank']}: mean step "
                f"{f['step_s_mean']:.6f}s vs median {f['median_s']:.6f}s "
                f"(+{100 * f['excess_frac']:.0f}%)"
            )
    return 1 if flagged else 0


if __name__ == "__main__":
    sys.exit(main())
