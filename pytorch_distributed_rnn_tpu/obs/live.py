"""Live observability plane: rolling windows, digests, and the exporter.

Everything ``obs/`` built so far is post-hoc - the sidecar is read after
the run exits.  This module is the in-run half: each process keeps a
BOUNDED rolling window of its recent telemetry (step times, loss,
data-wait, queue depth) fed from the same ``MetricsRecorder.record``
stream the sidecar gets, and a :class:`LiveExporter` that rides the
recorder's existing writer thread (no new thread) to push periodic
JSON digests to the rank-0/master aggregator (``obs/aggregator.py``),
which serves them over ``GET /metrics`` (Prometheus), ``/health``,
``/events`` and ``/fleet``.

Hot-path contract (the zero-overhead pin extends here):

- live export OFF (no ``--live`` flag / ``PDRNN_LIVE`` env, or telemetry
  off entirely) = nothing exists: no window, no exporter, no watchdog,
  no HTTP server, NO new threads, and the step program is untouched;
- live export ON adds one ``observe_event`` call inside ``record()``
  (which is already off the hot path - trainers emit step events in a
  deferred post-loop batch) and digest pushes on the writer thread's
  wake cadence.

:class:`RollingWindow` is THE windowing implementation - the serving
engine's ``stats`` op computes its req/s / tokens/s / shed/s rates from
the same class (one implementation, not two).

Wire contract: a digest is one JSON object POSTed to the aggregator's
``/push``; its ``id`` (``<role>-<rank>``) keys the fleet table.  Fields
(all optional beyond ``id``/``role``/``rank``/``t``):

=================== =======================================================
field               meaning
=================== =======================================================
id, role, rank, pid digest source identity (role: trainer | master |
                    worker | serve | supervisor)
t, tm               wall / monotonic stamp of the digest build
seq                 per-process digest counter (monotone)
progress            last step noted via ``note_progress``
progress_age_s      seconds since progress last ADVANCED (exporter-side
                    tracking - the live analogue of the sidecar
                    heartbeat-vs-progress health split)
finished            a ``run_summary`` landed (the run is over)
steps_total         step events observed since process start (counter)
step_s              {count, mean, p50, p95, last} over the window
loss                {last, mean, nonfinite_streak} over the window
data_wait_s_mean    window mean input-pipeline wait
queue_depth         {last, p95} over the window (serving / PS)
goodput_60s         fraction of the last minute spent in step compute
                    (windowed ``sum_rate`` of step durations, clamped
                    to 1) - the live analogue of the post-hoc ledger's
                    goodput
mfu_60s             windowed model-FLOPs utilisation: the analytic
                    per-step FLOPs the trainer recorded in its
                    ``collectives`` event x step rate / local peak
                    (absent until that event arrives)
nan_skips_total     non-finite guard skips (counter)
faults_total        {action: count} chaos faults fired (counter)
alerts_total        alert events observed (counter)
alerts              recent watchdog alerts (seq-tagged; the aggregator
                    dedupes by (id, seq) so re-pushed digests are safe)
roster              latest elastic-roster counts (master digests)
drained_slots       rank slots that DEREGISTERed voluntarily - the
                    aggregator classifies their silence as drained,
                    not dead
drained             this source itself is draining / drained
                    (``note_drained`` - a SIGTERMed serving replica
                    finishing in-flight work); the aggregator
                    classifies its silence as drained, not dead
serving             serving-engine gauge block (queue depth, windowed
                    req/s / tokens/s / shed/s, latency/TTFT p50/p95)
=================== =======================================================
"""

from __future__ import annotations

import bisect
import json
import logging
import math
import os
import threading
import time
from collections import deque

from pytorch_distributed_rnn_tpu.obs.summary import percentile
from pytorch_distributed_rnn_tpu.utils import threadcheck

log = logging.getLogger(__name__)

# env half of the CLI contract (the --live flag beats it), mirroring
# PDRNN_METRICS: spawned worker processes inherit the aggregator address
# without CLI plumbing
LIVE_ENV = "PDRNN_LIVE"
LIVE_PORT_FILE_ENV = "PDRNN_LIVE_PORT_FILE"
LIVE_PUSH_EVERY_ENV = "PDRNN_LIVE_PUSH_EVERY"

# the shared rate horizon: serving stats-op rates and live digests both
# answer "over the last minute"
RATE_HORIZON_S = 60.0

_DEFAULT_PUSH_EVERY_S = 1.0
_PUSH_TIMEOUT_S = 1.0
_ALERT_RING = 64  # recent alerts carried per digest


class RollingWindow:
    """Bounded (monotonic-time, value) observation window.

    Two bounds compose: observations older than ``horizon_s`` are
    evicted, and ``maxlen`` caps memory however fast observations
    arrive.  Rates divide by the EFFECTIVE window - ``min(horizon,
    age-of-window)`` - so a server 10 s into its life reports an honest
    10 s rate instead of a 60 s-diluted one.  Thread-safe."""

    def __init__(self, horizon_s: float = RATE_HORIZON_S,
                 maxlen: int = 4096):
        self.horizon_s = float(horizon_s)
        self._items: deque[tuple[float, float]] = deque(maxlen=int(maxlen))
        self._lock = threadcheck.lock(threading.Lock(), "live.window")  # guards: _items
        self._created = time.perf_counter()

    def observe(self, value: float, tm: float | None = None) -> None:
        now = time.perf_counter() if tm is None else float(tm)
        with self._lock:
            self._items.append((now, float(value)))
            self._evict(now)

    def _evict(self, now: float) -> None:  # holds: _lock
        cutoff = now - self.horizon_s
        items = self._items
        while items and items[0][0] < cutoff:
            items.popleft()

    def values(self, now: float | None = None) -> list[float]:
        now = time.perf_counter() if now is None else now
        with self._lock:
            self._evict(now)
            return [v for _, v in self._items]

    def last(self) -> float | None:
        with self._lock:
            return self._items[-1][1] if self._items else None

    def _window_s(self, now: float) -> float:
        return max(1e-9, min(self.horizon_s, now - self._created))

    def count_rate(self, now: float | None = None) -> float:
        """Observations per second over the effective window."""
        now = time.perf_counter() if now is None else now
        return len(self.values(now)) / self._window_s(now)

    def sum_rate(self, now: float | None = None) -> float:
        """Sum of observed values per second over the effective window
        (tokens/s when each observation is a request's token count)."""
        now = time.perf_counter() if now is None else now
        return sum(self.values(now)) / self._window_s(now)

    def stats(self, now: float | None = None) -> dict:
        """``{count, mean, p50, p95, last}`` over the live window (the
        percentile convention is ``obs/summary.percentile`` - shared
        with every post-hoc summary)."""
        values = self.values(now)
        if not values:
            return {"count": 0, "mean": None, "p50": None, "p95": None,
                    "last": None}
        ordered = sorted(values)
        return {
            "count": len(values),
            "mean": sum(values) / len(values),
            "p50": percentile(ordered, 0.50),
            "p95": percentile(ordered, 0.95),
            "last": values[-1],
        }


# THE request-latency histogram spec, shared by every layer that
# observes or interprets request latency: the serving engine and the
# fleet router construct their histograms via
# ``request_latency_histogram()`` below, and the time-series store
# (``obs/store.py``) interpolates window quantiles and SLO burn
# fractions over the SAME edges - cross-layer burn-rate math compares
# like with like by construction.  Prometheus' conventional buckets;
# the +Inf bucket is implicit (it equals ``count``).  The aggregator
# renders these as the series named by ``REQUEST_LATENCY_SERIES``.
LATENCY_BUCKETS_S = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

REQUEST_LATENCY_SERIES = "pdrnn_request_latency_seconds"


def request_latency_histogram() -> "LatencyHistogram":
    """The one constructor for the request-latency histogram behind
    ``REQUEST_LATENCY_SERIES`` - engine and router both build theirs
    here, so the bucket edges can never drift apart."""
    return LatencyHistogram(LATENCY_BUCKETS_S)


class LatencyHistogram:
    """Fixed-bucket latency histogram with OpenMetrics exemplars.

    Cumulative counts over :data:`LATENCY_BUCKETS_S` (``le`` inclusive,
    the Prometheus convention); each finite bucket remembers the LAST
    traced observation that landed in it (trace_id + value + wall
    stamp), so a slow-tail bucket on ``/metrics`` links straight to a
    trace pullable with ``pdrnn-metrics trace``.  Untraced observations
    still count - they just carry no exemplar.  Thread-safe."""

    def __init__(self, buckets=LATENCY_BUCKETS_S):
        self.buckets = tuple(float(b) for b in buckets)
        self._counts = [0] * (len(self.buckets) + 1)  # last = overflow
        self._sum = 0.0
        self._count = 0
        self._exemplars: list[dict | None] = [None] * len(self.buckets)
        self._lock = threadcheck.lock(threading.Lock(), "live.histogram")  # guards: _counts, _sum, _count, _exemplars

    def observe(self, seconds: float,
                trace_id: str | None = None) -> None:
        seconds = float(seconds)
        index = bisect.bisect_left(self.buckets, seconds)
        with self._lock:
            self._counts[index] += 1
            self._sum += seconds
            self._count += 1
            if trace_id is not None and index < len(self.buckets):
                self._exemplars[index] = {
                    "trace_id": str(trace_id), "value": seconds,
                    "t": time.time(),
                }

    def snapshot(self) -> dict | None:
        """Digest form: cumulative ``buckets`` (le/count/exemplar?),
        ``sum``, ``count``; None while empty (an idle source should not
        export an all-zero histogram)."""
        with self._lock:
            if self._count == 0:
                return None
            counts = list(self._counts)
            exemplars = [
                None if e is None else dict(e) for e in self._exemplars
            ]
            total, count = self._sum, self._count
        buckets, running = [], 0
        for i, le in enumerate(self.buckets):
            running += counts[i]
            entry: dict = {"le": le, "count": running}
            if exemplars[i] is not None:
                entry["exemplar"] = exemplars[i]
            buckets.append(entry)
        return {"buckets": buckets, "sum": total, "count": count}


def parse_live_spec(spec: str) -> tuple[str, int]:
    """``PORT`` or ``HOST:PORT`` -> (host, port).  The bare-port form
    binds/targets localhost - the single-machine spawn-world default."""
    spec = str(spec).strip()
    host, _, port_s = spec.rpartition(":")
    if not host:
        host, port_s = "127.0.0.1", spec
    try:
        port = int(port_s)
    except ValueError as exc:
        raise ValueError(
            f"bad live spec {spec!r} (want PORT or HOST:PORT)"
        ) from exc
    return host, port


def _finite_or_none(value):
    try:
        value = float(value)
    except (TypeError, ValueError):
        return None
    return value if math.isfinite(value) else None


def serving_idle(serving: dict | None) -> bool:
    """THE idleness predicate for a serving gauge block: no active
    slots and an empty queue means there is no work to progress on, so
    frozen decode-step progress is idleness, not a stall.  Shared by
    the in-process watchdog and the aggregator's health classifier so
    the two can never disagree about the same process."""
    return (
        serving is not None
        and not serving.get("active")
        and not serving.get("queue_depth")
    )


class LiveExporter:
    """Per-process live state + digest push.

    Fed by ``MetricsRecorder.record`` (``observe_event``); drained by
    the recorder's writer thread (``maybe_push`` on its wake cadence -
    no thread of its own).  ``sink`` is either a local
    :class:`~pytorch_distributed_rnn_tpu.obs.aggregator.Aggregator`
    (rank 0 exports in-process, no HTTP to self) or an aggregator base
    URL (``http://host:port``) for remote ranks.  Push failures are
    swallowed - live telemetry must never kill the run."""

    def __init__(self, recorder, sink, *, role: str = "trainer",
                 push_every_s: float | None = None):
        self.recorder = recorder
        self.sink = sink
        self.role = str(role)
        self.rank = int(getattr(recorder, "rank", 0))
        self.id = f"{self.role}-{self.rank}"
        if push_every_s is None:
            push_every_s = float(
                os.environ.get(LIVE_PUSH_EVERY_ENV, _DEFAULT_PUSH_EVERY_S)
            )
        self.push_every_s = max(0.05, float(push_every_s))

        self.step_s = RollingWindow()
        self.loss = RollingWindow()
        self.data_wait_s = RollingWindow()
        self.queue_depth = RollingWindow()

        self._lock = threadcheck.lock(threading.Lock(), "live.exporter")  # guards: _steps_total, _nan_skips, _faults, _alerts_total, _alerts
        self._steps_total = 0
        self._nan_skips = 0
        self._faults: dict[str, int] = {}
        self._alerts_total = 0
        self._alerts: deque[dict] = deque(maxlen=_ALERT_RING)
        self._roster = None
        self._drained_slots: set[int] = set()
        self.finished = False
        self.drained = False
        self.loss_nonfinite_streak = 0

        # efficiency-ledger live inputs: the trainer's collectives event
        # carries the analytic per-step model FLOPs; peak FLOPS is
        # resolved lazily (jax is already up in-process when training)
        self._model_flops_per_step = None
        self._peak_flops_total = None

        self._sources: list = []  # callables returning digest sub-dicts
        self._digest_seq = 0
        self._last_push = 0.0
        self._push_errors = 0
        # exporter-side progress tracking: progress_age_s in the digest
        # is the live analogue of the sidecar's heartbeat-vs-progress
        # split, computed here so the aggregator needs no clock deals
        self._progress_seen = None
        self._progress_tm = time.perf_counter()

    # -- feeding (any thread, via recorder.record) ---------------------------

    def observe_event(self, event: dict) -> None:
        kind = event.get("kind")
        if kind == "step":
            step_s = event.get("fenced_s")
            if step_s is None:
                step_s = event.get("dispatch_s")
            tm = time.perf_counter()  # windows use ARRIVAL time: the
            # trainer's deferred post-loop batch carries past dispatch
            # stamps, but window residency should reflect recency
            if step_s is not None:
                self.step_s.observe(step_s, tm)
            if event.get("data_wait_s") is not None:
                self.data_wait_s.observe(event["data_wait_s"], tm)
            if event.get("queue_depth") is not None:
                self.queue_depth.observe(event["queue_depth"], tm)
            loss = event.get("loss")
            with self._lock:
                self._steps_total += 1
                if loss is not None:
                    if _finite_or_none(loss) is None:
                        self.loss_nonfinite_streak += 1
                    else:
                        self.loss_nonfinite_streak = 0
                        self.loss.observe(loss, tm)
        elif kind == "nan_skip":
            with self._lock:
                self._nan_skips = int(event.get("total", self._nan_skips + 1))
        elif kind == "fault":
            action = str(event.get("action"))
            with self._lock:
                self._faults[action] = self._faults.get(action, 0) + 1
        elif kind == "alert":
            with self._lock:
                self._alerts_total += 1
                # fleet-born alerts (aggregator straggler findings the
                # master records) must not ride BACK in the digest - the
                # aggregator already has them
                if event.get("seq") is not None and not event.get("fleet"):
                    self._alerts.append({
                        k: v for k, v in event.items()
                        if k not in ("kind", "tm")
                    })
        elif kind == "collectives":
            flops = _finite_or_none(event.get("model_flops_per_step"))
            if flops is not None and flops > 0:
                with self._lock:
                    self._model_flops_per_step = flops
        elif kind == "run_summary":
            self.finished = True
        elif kind in ("member_join", "member_drain", "member_dead"):
            with self._lock:
                roster = {
                    k: event[k] for k in
                    ("joined", "drained", "dead", "done")
                    if k in event
                }
                if roster:
                    self._roster = roster
                slot = event.get("rank_slot")
                if slot is not None:
                    if kind == "member_drain":
                        self._drained_slots.add(int(slot))
                    else:
                        self._drained_slots.discard(int(slot))

    def note_drained(self) -> None:
        """Mark this source as voluntarily draining (a SIGTERMed serving
        replica finishing in-flight work before exit): every subsequent
        digest carries ``drained`` so the aggregator classifies the
        source's eventual silence as ``drained``, never ``dead``."""
        self.drained = True

    def note_alert(self, alert: dict) -> None:
        """Watchdog-side entry: queue an alert for the next digest (the
        sidecar ``alert`` event is recorded separately and feeds
        ``observe_event`` - this direct path exists for callers without
        a recorder, e.g. the supervisor pusher)."""
        with self._lock:
            self._alerts_total += 1
            self._alerts.append(dict(alert))

    def add_source(self, source) -> None:
        """Register a callable returning a dict merged into every digest
        under its own key (the serving engine contributes its gauge
        block this way)."""
        self._sources.append(source)

    # -- progress ------------------------------------------------------------

    def progress_age_s(self, now: float | None = None) -> float | None:
        """Seconds since ``note_progress`` last ADVANCED; None before
        the first noted step.  Refreshes the change stamp as a side
        effect (shared by the digest build and the watchdog)."""
        now = time.perf_counter() if now is None else now
        progress = getattr(self.recorder, "progress", None)
        with self._lock:
            if progress is None:
                return None
            if progress != self._progress_seen:
                self._progress_seen = progress
                self._progress_tm = now
            return now - self._progress_tm

    def source_snapshot(self) -> dict:
        """Merged extra-source dicts (watchdog SLO checks read serving
        gauges here without waiting for a digest)."""
        merged: dict = {}
        for source in self._sources:
            try:
                merged.update(source() or {})
            except Exception:  # pragma: no cover - sources must not kill
                log.exception("live: digest source failed")
        return merged

    # -- digest build + push -------------------------------------------------

    def digest(self, now: float | None = None) -> dict:
        now = time.perf_counter() if now is None else now
        age = self.progress_age_s(now)
        with self._lock:
            self._digest_seq += 1
            body = {
                "id": self.id, "role": self.role, "rank": self.rank,
                "pid": os.getpid(), "seq": self._digest_seq,
                "t": time.time(), "tm": now,
                "push_every_s": self.push_every_s,
                "progress": self._progress_seen,
                "progress_age_s": age,
                "finished": self.finished,
                "steps_total": self._steps_total,
                "nan_skips_total": self._nan_skips,
                "faults_total": dict(self._faults),
                "alerts_total": self._alerts_total,
                "alerts": list(self._alerts),
                "loss_nonfinite_streak": self.loss_nonfinite_streak,
            }
            if self._roster is not None:
                body["roster"] = dict(self._roster)
            if self._drained_slots:
                body["drained_slots"] = sorted(self._drained_slots)
            if self.drained:
                body["drained"] = True
        body["step_s"] = self.step_s.stats(now)
        loss_stats = self.loss.stats(now)
        body["loss"] = {
            "last": loss_stats["last"], "mean": loss_stats["mean"],
            "nonfinite_streak": body.pop("loss_nonfinite_streak"),
        }
        body["data_wait_s_mean"] = self.data_wait_s.stats(now)["mean"]
        depth = self.queue_depth.stats(now)
        body["queue_depth"] = {"last": depth["last"], "p95": depth["p95"]}
        body["goodput_60s"] = self.goodput_60s(now)
        body["mfu_60s"] = self.mfu_60s(now)
        body.update(self.source_snapshot())
        return body

    # -- live efficiency (the in-run half of obs/ledger.py) ------------------

    def goodput_60s(self, now: float | None = None) -> float | None:
        """Fraction of the effective window spent inside step compute
        (sum of step durations / window seconds, clamped to 1 - deferred
        batch arrival can momentarily stack more step-seconds than
        wall-seconds).  None before the first step lands."""
        now = time.perf_counter() if now is None else now
        if not self.step_s.values(now):
            return None
        return min(1.0, self.step_s.sum_rate(now))

    def mfu_60s(self, now: float | None = None) -> float | None:
        """Windowed MFU: analytic per-step model FLOPs (learned from the
        trainer's ``collectives`` event) x windowed step rate / local
        peak FLOPS.  None until the flops figure arrives or when no
        peak is resolvable."""
        now = time.perf_counter() if now is None else now
        with self._lock:
            flops = self._model_flops_per_step
        if flops is None or not self.step_s.values(now):
            return None
        peak = self._resolve_peak()
        if not peak:
            return None
        return flops * self.step_s.count_rate(now) / peak

    def _resolve_peak(self) -> float | None:
        if self._peak_flops_total is None:
            try:
                from pytorch_distributed_rnn_tpu.utils.hw import (
                    local_peak_flops,
                )

                self._peak_flops_total = float(
                    local_peak_flops().get("peak_flops_total") or 0.0
                )
            except Exception:  # pragma: no cover - peak must not kill
                self._peak_flops_total = 0.0
        return self._peak_flops_total or None

    def maybe_push(self) -> bool:
        """Writer-thread hook: push a digest when the cadence elapsed."""
        now = time.perf_counter()
        if now - self._last_push < self.push_every_s:
            return False
        self.push_now(now)
        return True

    def push_now(self, now: float | None = None) -> None:
        self._last_push = time.perf_counter() if now is None else now
        digest = self.digest(self._last_push)
        push_digest(self.sink, digest)


def push_digest(sink, digest: dict) -> bool:
    """Deliver one digest to ``sink``: a local Aggregator object (direct
    call) or an aggregator base URL (HTTP POST ``/push``).  Returns
    delivery success; failures are logged at debug (a dead aggregator
    must not spam or kill the run)."""
    if sink is None:
        return False
    if not isinstance(sink, str):
        try:
            sink.ingest(digest)
            return True
        except Exception:  # pragma: no cover - defensive
            log.exception("live: local aggregator ingest failed")
            return False
    import urllib.request

    req = urllib.request.Request(
        sink.rstrip("/") + "/push",
        data=json.dumps(digest, default=_jsonable).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=_PUSH_TIMEOUT_S):
            return True
    except (OSError, ValueError) as exc:
        log.debug(f"live: digest push to {sink} failed: {exc}")
        return False


def _jsonable(value):
    try:
        return float(value)
    except (TypeError, ValueError):
        return str(value)


def resolve_push_url(args, host: str, port: int,
                     wait_s: float = 10.0) -> str | None:
    """Push-target resolution for non-anchor processes.  An explicit
    port is used as-is.  Port 0 (ephemeral) is only knowable through
    the anchor's ``--live-port-file`` / ``PDRNN_LIVE_PORT_FILE``, so
    wait for it to appear (spawn worlds share a filesystem and the
    anchor binds before its rendezvous).  Unresolvable = a LOUD warning
    and no sink - pushing to the literal port 0 would silently drop
    every digest."""
    if port != 0:
        return f"http://{host}:{port}"
    port_file = (
        getattr(args, "live_port_file", None)
        or os.environ.get(LIVE_PORT_FILE_ENV)
    )
    if port_file:
        from pathlib import Path

        path = Path(port_file)
        deadline = time.monotonic() + wait_s
        while time.monotonic() < deadline:
            try:
                fields = path.read_text().split()
                if len(fields) == 2:
                    return f"http://{fields[0]}:{int(fields[1])}"
            except (OSError, ValueError):
                pass
            time.sleep(0.1)
    log.warning(
        "live: --live port 0 but no readable --live-port-file; this "
        "process cannot locate the aggregator - digest push disabled "
        "(give multi-process worlds an explicit port, or share a "
        "port file)"
    )
    return None


class EventPusher:
    """Minimal alert-only pusher for processes WITHOUT a recorder (the
    elastic supervisor parent): wraps each event as a digest carrying
    one alert, so supervisor respawn/collapse events land in the
    aggregator's ``/events`` and ``/metrics`` next to the fleet's.

    ``sink`` may also be a zero-arg callable resolved per push - the
    supervisor constructs its pusher BEFORE the master child binds an
    ephemeral --live 0 port, so the port-file lookup must be lazy."""

    def __init__(self, sink, *, role: str = "supervisor", rank: int = 0):
        self.sink = sink
        self.role, self.rank = str(role), int(rank)
        self.id = f"{self.role}-{self.rank}"
        self._seq = 0
        self._alerts_total = 0

    def push(self, kind: str, severity: str = "warning", **fields) -> None:
        self._seq += 1
        self._alerts_total += 1
        alert = {"alert": kind, "severity": severity, "seq": self._seq,
                 "t": time.time(), **fields}
        sink = self.sink() if callable(self.sink) else self.sink
        push_digest(sink, {
            "id": self.id, "role": self.role, "rank": self.rank,
            "pid": os.getpid(), "seq": self._seq,
            "t": time.time(), "tm": time.perf_counter(),
            # event-only source: it pushes when something HAPPENS, not
            # on a cadence - /health must not classify its silence as a
            # death
            "ephemeral": True,
            "alerts_total": self._alerts_total, "alerts": [alert],
        })


def resolve_event_push(args, *, role: str = "supervisor",
                       wait_s: float = 2.0):
    """The supervisor-parent push wiring, shared by every supervised
    runner (elastic PS, MPMD stages, streaming actors): an
    :class:`EventPusher` ``push`` bound to the run's aggregator, or
    ``None`` when the live plane is off.  Gated on BOTH ``--live`` and
    ``--metrics`` (matching LivePlane.resolve: live rides the metrics
    writer thread, so live-without-metrics is rejected there too).  The
    sink is lazy: with ``--live 0`` the anchor child binds its
    ephemeral port after the supervisor constructs the pusher, so the
    port file is only readable at push time."""
    from pytorch_distributed_rnn_tpu.obs.recorder import METRICS_ENV

    live_spec = getattr(args, "live", None) or os.environ.get(LIVE_ENV)
    if not live_spec:
        return None
    if not (getattr(args, "metrics", None) or os.environ.get(METRICS_ENV)):
        return None
    host, port = parse_live_spec(live_spec)
    return EventPusher(
        lambda: resolve_push_url(args, host, port, wait_s=wait_s),
        role=role,
    ).push


class LivePlane:
    """The wired-together live plane of ONE process: exporter (+local
    aggregator HTTP server when this process is the rank-0/master
    anchor) + anomaly watchdog.  ``resolve`` is the one construction
    path every entry point shares (``--live`` flag beats the
    ``PDRNN_LIVE`` env), so live export can never be silently dropped
    by one of them; returns None when live export is off or telemetry
    is off entirely (the zero-overhead contract: nothing constructed,
    no threads)."""

    def __init__(self, exporter, aggregator=None, server=None,
                 watchdog=None, store=None):
        self.exporter = exporter
        self.aggregator = aggregator
        self.server = server
        self.watchdog = watchdog
        self.store = store

    @classmethod
    def resolve(cls, args, recorder, *, rank: int = 0,
                role: str = "trainer", faults=None,
                serve_here: bool | None = None):
        spec = getattr(args, "live", None) or os.environ.get(LIVE_ENV)
        if not spec or not getattr(recorder, "enabled", False):
            return None
        host, port = parse_live_spec(spec)
        if serve_here is None:
            serve_here = rank == 0
        # --slo objectives parse once here (the one construction path):
        # they arm the per-QoS watchdog SLO detector on EVERY live
        # process, and the anchor's store burns budgets against them
        from pytorch_distributed_rnn_tpu.obs.store import parse_slo_args

        slo = parse_slo_args(getattr(args, "slo", None))
        aggregator = server = store = None
        if serve_here:
            from pytorch_distributed_rnn_tpu.obs.aggregator import (
                Aggregator,
                AggregatorServer,
            )
            from pytorch_distributed_rnn_tpu.obs.store import (
                DEFAULT_BURN_WINDOWS_S,
                TimeSeriesStore,
                store_path_for,
            )
            from pytorch_distributed_rnn_tpu.obs.watchdog import (
                resolve_stall_after,
            )

            # the anchor owns the history: the store rides the
            # aggregator's ingest path (push handler threads / this
            # process's writer-thread pushes - no thread of its own),
            # snapshotting next to the sidecar for cold reads
            windows = getattr(args, "slo_windows", None)
            if windows:
                fast_s, _, slow_s = str(windows).partition(",")
                windows = (float(fast_s), float(slow_s))
            store = TimeSeriesStore(
                slo=slo,
                burn_windows_s=windows or DEFAULT_BURN_WINDOWS_S,
                snapshot_path=(
                    store_path_for(recorder.path)
                    if getattr(recorder, "path", None) else None
                ),
            )
            aggregator = Aggregator(
                stall_after_s=resolve_stall_after(), recorder=recorder,
                store=store,
            )
            server = AggregatorServer(aggregator, host=host, port=port)
            port_file = (
                getattr(args, "live_port_file", None)
                or os.environ.get(LIVE_PORT_FILE_ENV)
            )
            if port_file:
                from pathlib import Path

                port_file = Path(port_file)
                port_file.parent.mkdir(parents=True, exist_ok=True)
                port_file.write_text(f"{server.host} {server.port}\n")
            sink = aggregator
        else:
            sink = resolve_push_url(args, host, port)
        exporter = LiveExporter(recorder, sink, role=role)
        recorder.attach_live(exporter)

        from pytorch_distributed_rnn_tpu.obs.watchdog import (
            AnomalyWatchdog,
        )

        watchdog = AnomalyWatchdog.resolve(
            recorder, exporter, faults=faults, slo=slo, store=store
        )
        if watchdog is not None:
            watchdog.start()
        log.info(
            f"live plane up: role={role} rank={rank} "
            + (f"serving http://{server.host}:{server.port}" if server
               else f"pushing to {sink}")
        )
        return cls(exporter, aggregator, server, watchdog, store)

    def close(self) -> None:
        """Stop the watchdog and the HTTP server; idempotent.  Call
        AFTER ``recorder.close()`` so the final digest push (finished
        state) lands before the server goes away."""
        if self.watchdog is not None:
            self.watchdog.close()
        if self.server is not None:
            self.server.close()
        if self.store is not None:
            # final snapshot regardless of the periodic throttle: a run
            # shorter than the cadence still leaves its history on disk
            try:
                self.store.write_snapshot()
            except OSError as exc:  # pragma: no cover - disk trouble
                log.warning(f"store snapshot on close failed: {exc}")
