"""Structured run telemetry (the observability spine).

- :mod:`.recorder`: rank-tagged JSONL event stream, buffered off the
  training hot path (:class:`MetricsRecorder` / :data:`NULL_RECORDER`).
- :mod:`.spans`: the span primitives (``recorder.span`` / ``emit_span``
  context-manager and deferred duration events).
- :mod:`.profile`: step-bounded ``jax.profiler`` capture
  (``--profile-steps A:B``).
- :mod:`.summary`: sidecar loading, summaries, diffs, stragglers,
  per-rank liveness (``rank_health``).
- :mod:`.timeline`: cross-rank clock alignment, Chrome-trace/Perfetto
  export + validator, phase attribution.
- :mod:`.tracectx`: the distributed request-trace context
  (:class:`TraceContext`) minted at the serving edge and carried on the
  serve wire protocol; :mod:`.trace` assembles the recorded spans from
  router + replica sidecars into trees with critical-path attribution
  (``pdrnn-metrics trace``).
- :mod:`.flops`: analytic per-step FLOP/byte counts off abstract
  jaxprs (no data, no compile) - the efficiency ledger's MFU numerator.
- :mod:`.ledger`: the efficiency ledger - exhaustive wall-clock phase
  accounting (fractions sum to 1), goodput, MFU/HFU vs the
  ``utils/hw.py`` peak table, fault tax, and the
  ``ledger_history.jsonl`` + ``pdrnn-metrics regress`` cross-run gate.
- :mod:`.live`: the live plane - rolling windows, digest exporter (no
  thread of its own: rides the recorder's writer thread), and the
  per-process ``LivePlane`` wiring (``--live`` / ``PDRNN_LIVE``).
- :mod:`.aggregator`: rank-0/master digest aggregation + the stdlib
  HTTP server behind ``GET /metrics`` (Prometheus), ``/health``,
  ``/events`` and ``/fleet``.
- :mod:`.watchdog`: in-run anomaly detection (stall / NaN streak / loss
  spike / serving SLO) with all-thread stack dumps, plus the SIGUSR2
  on-demand dump hook every long-lived entrypoint installs.
- :mod:`.cli`: the ``pdrnn-metrics`` CLI over all of the above
  (including ``watch``, the live fleet table).

This package imports neither jax nor the training stack at module
import time, so CLI startup and jax-free tooling stay cheap.
"""

from pytorch_distributed_rnn_tpu.obs.aggregator import (
    Aggregator,
    AggregatorServer,
    render_prometheus,
)
from pytorch_distributed_rnn_tpu.obs.live import (
    LIVE_ENV,
    LatencyHistogram,
    LiveExporter,
    LivePlane,
    RollingWindow,
)
from pytorch_distributed_rnn_tpu.obs.flops import (
    closed_jaxpr_flop_stats,
    entry_flop_report,
    trace_flop_stats,
)
from pytorch_distributed_rnn_tpu.obs.ledger import (
    FRACTION_TOL,
    LEDGER_PHASES,
    append_history,
    check_history,
    history_record,
    ledger_events,
    ledger_file,
    ledger_run,
    load_history,
)
from pytorch_distributed_rnn_tpu.obs.profile import StepTraceCapture
from pytorch_distributed_rnn_tpu.obs.recorder import (
    METRICS_ENV,
    METRICS_HEARTBEAT_ENV,
    METRICS_SAMPLE_ENV,
    NULL_RECORDER,
    SCHEMA_VERSION,
    MetricsRecorder,
    NullRecorder,
    rank_suffixed,
)
from pytorch_distributed_rnn_tpu.obs.summary import (
    MalformedMetricsError,
    detect_stragglers,
    diff_summaries,
    load_events,
    rank_files,
    rank_health,
    summarize_events,
    summarize_file,
    summarize_run,
)
from pytorch_distributed_rnn_tpu.obs.watchdog import (
    AnomalyWatchdog,
    dump_stacks,
    install_stack_dump_handler,
)
from pytorch_distributed_rnn_tpu.obs.trace import (
    MalformedTraceError,
    TraceTree,
    assemble_traces,
    build_trace_tree,
    collect_trace_spans,
    format_trace_tree,
    validate_trace_tree,
)
from pytorch_distributed_rnn_tpu.obs.tracectx import (
    TraceContext,
    should_sample,
)
from pytorch_distributed_rnn_tpu.obs.timeline import (
    attribute_rank,
    attribute_run,
    attribute_stragglers,
    build_chrome_trace,
    estimate_clock_offsets,
    load_run,
    validate_chrome_trace,
    write_chrome_trace,
)

__all__ = [
    "Aggregator",
    "AggregatorServer",
    "AnomalyWatchdog",
    "LIVE_ENV",
    "LiveExporter",
    "LivePlane",
    "METRICS_ENV",
    "METRICS_HEARTBEAT_ENV",
    "METRICS_SAMPLE_ENV",
    "NULL_RECORDER",
    "RollingWindow",
    "SCHEMA_VERSION",
    "LatencyHistogram",
    "MalformedMetricsError",
    "MalformedTraceError",
    "MetricsRecorder",
    "NullRecorder",
    "StepTraceCapture",
    "TraceContext",
    "TraceTree",
    "dump_stacks",
    "install_stack_dump_handler",
    "render_prometheus",
    "FRACTION_TOL",
    "LEDGER_PHASES",
    "append_history",
    "assemble_traces",
    "attribute_rank",
    "attribute_run",
    "attribute_stragglers",
    "build_chrome_trace",
    "build_trace_tree",
    "check_history",
    "closed_jaxpr_flop_stats",
    "collect_trace_spans",
    "detect_stragglers",
    "diff_summaries",
    "entry_flop_report",
    "estimate_clock_offsets",
    "format_trace_tree",
    "history_record",
    "ledger_events",
    "ledger_file",
    "ledger_run",
    "load_events",
    "load_history",
    "load_run",
    "trace_flop_stats",
    "rank_files",
    "rank_health",
    "rank_suffixed",
    "should_sample",
    "summarize_events",
    "summarize_file",
    "summarize_run",
    "validate_chrome_trace",
    "write_chrome_trace",
]
