"""Structured run telemetry (the observability spine).

- :mod:`.recorder`: rank-tagged JSONL event stream, buffered off the
  training hot path (:class:`MetricsRecorder` / :data:`NULL_RECORDER`).
- :mod:`.spans`: the span primitives (``recorder.span`` / ``emit_span``
  context-manager and deferred duration events).
- :mod:`.profile`: step-bounded ``jax.profiler`` capture
  (``--profile-steps A:B``).
- :mod:`.summary`: sidecar loading, summaries, diffs, stragglers,
  per-rank liveness (``rank_health``).
- :mod:`.timeline`: cross-rank clock alignment, Chrome-trace/Perfetto
  export + validator, phase attribution.
- :mod:`.cli`: the ``pdrnn-metrics`` CLI over all of the above.

This package imports neither jax nor the training stack at module
import time, so CLI startup and jax-free tooling stay cheap.
"""

from pytorch_distributed_rnn_tpu.obs.profile import StepTraceCapture
from pytorch_distributed_rnn_tpu.obs.recorder import (
    METRICS_ENV,
    METRICS_HEARTBEAT_ENV,
    METRICS_SAMPLE_ENV,
    NULL_RECORDER,
    SCHEMA_VERSION,
    MetricsRecorder,
    NullRecorder,
    rank_suffixed,
)
from pytorch_distributed_rnn_tpu.obs.summary import (
    MalformedMetricsError,
    detect_stragglers,
    diff_summaries,
    load_events,
    rank_files,
    rank_health,
    summarize_events,
    summarize_file,
    summarize_run,
)
from pytorch_distributed_rnn_tpu.obs.timeline import (
    attribute_rank,
    attribute_run,
    attribute_stragglers,
    build_chrome_trace,
    estimate_clock_offsets,
    load_run,
    validate_chrome_trace,
    write_chrome_trace,
)

__all__ = [
    "METRICS_ENV",
    "METRICS_HEARTBEAT_ENV",
    "METRICS_SAMPLE_ENV",
    "NULL_RECORDER",
    "SCHEMA_VERSION",
    "MalformedMetricsError",
    "MetricsRecorder",
    "NullRecorder",
    "StepTraceCapture",
    "attribute_rank",
    "attribute_run",
    "attribute_stragglers",
    "build_chrome_trace",
    "detect_stragglers",
    "diff_summaries",
    "estimate_clock_offsets",
    "load_events",
    "load_run",
    "rank_files",
    "rank_health",
    "rank_suffixed",
    "summarize_events",
    "summarize_file",
    "summarize_run",
    "validate_chrome_trace",
    "write_chrome_trace",
]
