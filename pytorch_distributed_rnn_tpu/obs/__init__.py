"""Structured run telemetry (the observability spine).

- :mod:`.recorder`: rank-tagged JSONL event stream, buffered off the
  training hot path (:class:`MetricsRecorder` / :data:`NULL_RECORDER`).
- :mod:`.profile`: step-bounded ``jax.profiler`` capture
  (``--profile-steps A:B``).
- :mod:`.summary`: sidecar loading, summaries, diffs, stragglers.
- :mod:`.cli`: the ``pdrnn-metrics`` CLI over those summaries.

This package imports neither jax nor the training stack at module
import time, so CLI startup and jax-free tooling stay cheap.
"""

from pytorch_distributed_rnn_tpu.obs.profile import StepTraceCapture
from pytorch_distributed_rnn_tpu.obs.recorder import (
    METRICS_ENV,
    METRICS_SAMPLE_ENV,
    NULL_RECORDER,
    SCHEMA_VERSION,
    MetricsRecorder,
    NullRecorder,
    rank_suffixed,
)
from pytorch_distributed_rnn_tpu.obs.summary import (
    MalformedMetricsError,
    detect_stragglers,
    diff_summaries,
    load_events,
    rank_files,
    summarize_events,
    summarize_file,
    summarize_run,
)

__all__ = [
    "METRICS_ENV",
    "METRICS_SAMPLE_ENV",
    "NULL_RECORDER",
    "SCHEMA_VERSION",
    "MalformedMetricsError",
    "MetricsRecorder",
    "NullRecorder",
    "StepTraceCapture",
    "detect_stragglers",
    "diff_summaries",
    "load_events",
    "rank_files",
    "rank_suffixed",
    "summarize_events",
    "summarize_file",
    "summarize_run",
]
