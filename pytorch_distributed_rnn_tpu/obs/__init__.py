"""Structured run telemetry (the observability spine).

- :mod:`.recorder`: rank-tagged JSONL event stream, buffered off the
  training hot path (:class:`MetricsRecorder` / :data:`NULL_RECORDER`).
- :mod:`.spans`: the span primitives (``recorder.span`` / ``emit_span``
  context-manager and deferred duration events).
- :mod:`.profile`: step-bounded ``jax.profiler`` capture
  (``--profile-steps A:B``).
- :mod:`.summary`: sidecar loading, summaries, diffs, stragglers,
  per-rank liveness (``rank_health``).
- :mod:`.timeline`: cross-rank clock alignment, Chrome-trace/Perfetto
  export + validator, phase attribution.
- :mod:`.live`: the live plane - rolling windows, digest exporter (no
  thread of its own: rides the recorder's writer thread), and the
  per-process ``LivePlane`` wiring (``--live`` / ``PDRNN_LIVE``).
- :mod:`.aggregator`: rank-0/master digest aggregation + the stdlib
  HTTP server behind ``GET /metrics`` (Prometheus), ``/health``,
  ``/events`` and ``/fleet``.
- :mod:`.watchdog`: in-run anomaly detection (stall / NaN streak / loss
  spike / serving SLO) with all-thread stack dumps, plus the SIGUSR2
  on-demand dump hook every long-lived entrypoint installs.
- :mod:`.cli`: the ``pdrnn-metrics`` CLI over all of the above
  (including ``watch``, the live fleet table).

This package imports neither jax nor the training stack at module
import time, so CLI startup and jax-free tooling stay cheap.
"""

from pytorch_distributed_rnn_tpu.obs.aggregator import (
    Aggregator,
    AggregatorServer,
    render_prometheus,
)
from pytorch_distributed_rnn_tpu.obs.live import (
    LIVE_ENV,
    LiveExporter,
    LivePlane,
    RollingWindow,
)
from pytorch_distributed_rnn_tpu.obs.profile import StepTraceCapture
from pytorch_distributed_rnn_tpu.obs.recorder import (
    METRICS_ENV,
    METRICS_HEARTBEAT_ENV,
    METRICS_SAMPLE_ENV,
    NULL_RECORDER,
    SCHEMA_VERSION,
    MetricsRecorder,
    NullRecorder,
    rank_suffixed,
)
from pytorch_distributed_rnn_tpu.obs.summary import (
    MalformedMetricsError,
    detect_stragglers,
    diff_summaries,
    load_events,
    rank_files,
    rank_health,
    summarize_events,
    summarize_file,
    summarize_run,
)
from pytorch_distributed_rnn_tpu.obs.watchdog import (
    AnomalyWatchdog,
    dump_stacks,
    install_stack_dump_handler,
)
from pytorch_distributed_rnn_tpu.obs.timeline import (
    attribute_rank,
    attribute_run,
    attribute_stragglers,
    build_chrome_trace,
    estimate_clock_offsets,
    load_run,
    validate_chrome_trace,
    write_chrome_trace,
)

__all__ = [
    "Aggregator",
    "AggregatorServer",
    "AnomalyWatchdog",
    "LIVE_ENV",
    "LiveExporter",
    "LivePlane",
    "METRICS_ENV",
    "METRICS_HEARTBEAT_ENV",
    "METRICS_SAMPLE_ENV",
    "NULL_RECORDER",
    "RollingWindow",
    "SCHEMA_VERSION",
    "MalformedMetricsError",
    "MetricsRecorder",
    "NullRecorder",
    "StepTraceCapture",
    "dump_stacks",
    "install_stack_dump_handler",
    "render_prometheus",
    "attribute_rank",
    "attribute_run",
    "attribute_stragglers",
    "build_chrome_trace",
    "detect_stragglers",
    "diff_summaries",
    "estimate_clock_offsets",
    "load_events",
    "load_run",
    "rank_files",
    "rank_health",
    "rank_suffixed",
    "summarize_events",
    "summarize_file",
    "summarize_run",
    "validate_chrome_trace",
    "write_chrome_trace",
]
