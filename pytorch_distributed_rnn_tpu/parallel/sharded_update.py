"""Cross-replica sharded weight update (PAPERS.md 2004.13336).

Every pure data-parallel path used to allreduce the full gradient and
then apply the FULL optimizer update redundantly on every replica.  This
module is the shared fix - reduce-scatter the gradient, apply a
1/world-sharded ``optax`` update, allgather the fresh parameters - for
both trainer stacks:

- the SPMD ``shard_map`` step factories (``parallel/dp.py``):
  :meth:`ShardedUpdate.apply` is the per-shard body
  (``lax.psum_scatter`` -> sharded ``optimizer.update`` ->
  ``lax.all_gather``), and :meth:`ShardedUpdate.init_opt_state` builds
  the optimizer state ALREADY laid out as one flat padded vector sharded
  along the data axis, so full-size ``mu``/``nu`` never materialize per
  device and the HBM peak actually drops;
- the native TCP ring (``training/native_ddp.py``): the same padded-ravel
  bookkeeping over ``Communicator.reduce_scatter``/``allgather``, with
  each rank holding only its shard's optimizer state as a host-visible
  array.

Layout: the parameter pytree ravels (``jax.flatten_util.ravel_pytree``
order) into a vector of ``size`` elements, zero-padded to ``padded =
shard * world`` so uneven ``size % world`` still shards equally; rank
``r`` owns elements ``[r * shard, (r + 1) * shard)``.  Optimizer state in
the sharded layout is ``optimizer.init`` of that flat padded vector -
for adam: the same zeros as the standard layout, just raveled - and the
``*_opt_state`` converters below are the bijection to/from the standard
``optimizer.init(params)`` layout, so CHECKPOINTS always carry the
unsharded layout (``--resume auto``, the PS, serving and streaming read
checkpoints and are unaffected by the flag).

Correctness bar (pinned by ``tests/test_sharded_update.py``): because
``psum_scatter`` produces exactly the matching slice of the ``psum`` and
the optimizer math is elementwise, sharded and replicated training are
bitwise-identical on CPU at every world size, divisible or not.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.flatten_util import ravel_pytree
from jax.sharding import NamedSharding, PartitionSpec as P


class ShardedUpdate:
    """Padded-ravel bookkeeping + the sharded update body for ONE
    (optimizer, params-structure, world) binding.

    ``params`` may be abstract (``ShapeDtypeStruct`` leaves - the lint
    trace registry's convention): only shapes/dtypes are read at
    construction, and the host-side layout converters build their
    unravel closure lazily from whatever concrete tree they are handed.

    ``poison_nonfinite=True`` is REQUIRED whenever ``optimizer`` is
    wrapped in ``optax.apply_if_finite`` (the non-finite guard): each
    shard's wrapper only sees its own slice, so without a global verdict
    one shard could skip a NaN step while the others apply theirs and
    the replicated-params invariant breaks.  The flag adds one scalar
    ``psum`` of a local any-non-finite flag and NaN-poisons EVERY
    shard's gradient slice when any shard is bad, so all wrappers take
    the identical skip decision.  (The verdict is taken on the reduced
    gradient, which is exactly what decides the replicated wrapper's
    skip for adam-family optimizers - their updates are non-finite iff
    the gradient is.)
    """

    def __init__(self, optimizer, params, world_size: int,
                 axis: str = "dp", poison_nonfinite: bool = False):
        self.optimizer = optimizer
        self.axis = axis
        self.world = int(world_size)
        self.poison_nonfinite = bool(poison_nonfinite)
        flat = jax.eval_shape(lambda p: ravel_pytree(p)[0], params)
        self.size = int(flat.shape[0])
        self.dtype = flat.dtype
        self.shard = -(-self.size // self.world)  # ceil
        self.padded = self.shard * self.world
        self._params_template = params
        self._unravel_fn = None

    # -- SPMD (shard_map) side ----------------------------------------------

    def apply(self, params, grads, opt_state):
        """Per-shard sharded update body; call INSIDE ``shard_map`` over
        ``self.axis`` with replicated ``params``, per-shard ``grads``
        (local, unreduced) and ``opt_state`` in the sharded flat layout.
        Returns ``(params, opt_state)`` with params replicated again via
        the trailing allgather."""
        flat_g, _ = ravel_pytree(grads)
        flat_g = jnp.pad(flat_g, (0, self.padded - self.size))
        # psum_scatter(tiled): this shard's slice of the summed gradient
        # - the reduce-scatter half of what the allreduce used to move
        g_shard = jax.lax.psum_scatter(
            flat_g, self.axis, scatter_dimension=0, tiled=True
        ) / self.world
        if self.poison_nonfinite:
            bad = jax.lax.psum(
                (~jnp.all(jnp.isfinite(g_shard))).astype(jnp.float32),
                self.axis,
            )
            g_shard = jnp.where(bad > 0, jnp.full_like(g_shard, jnp.nan),
                                g_shard)
        flat_p, unravel = ravel_pytree(params)
        r = jax.lax.axis_index(self.axis)
        p_shard = jax.lax.dynamic_slice(
            jnp.pad(flat_p, (0, self.padded - self.size)),
            (r * self.shard,), (self.shard,),
        )
        updates, opt_state = self.optimizer.update(g_shard, opt_state, p_shard)
        p_shard = optax.apply_updates(p_shard, updates)
        flat_new = jax.lax.all_gather(p_shard, self.axis, tiled=True)
        return unravel(flat_new[: self.size]), opt_state

    def abstract_opt_state(self):
        """Sharded-layout optimizer state as ``ShapeDtypeStruct`` leaves
        (full padded shapes; the per-device view divides by world)."""
        return jax.eval_shape(
            self.optimizer.init, jax.ShapeDtypeStruct((self.padded,),
                                                      self.dtype)
        )

    def _is_full_vector(self, leaf) -> bool:
        # the state leaves that mirror the parameter vector (mu/nu/...):
        # exactly the ones sharded along the axis and re-laid-out by the
        # checkpoint converters.  Scalar counters etc. pass through.
        return getattr(leaf, "ndim", 0) == 1 and leaf.shape[0] == self.padded

    def opt_state_specs(self):
        """``PartitionSpec`` pytree for the sharded flat layout:
        parameter-vector leaves ``P(axis)``, everything else replicated -
        the ``shard_map`` in/out spec for the opt-state argument."""
        return jax.tree.map(
            lambda l: P(self.axis) if self._is_full_vector(l) else P(),
            self.abstract_opt_state(),
        )

    def init_opt_state(self, params, mesh=None):
        """Concrete sharded-layout state, initialized ALREADY sharded
        over ``mesh`` (jitted init with ``NamedSharding`` out shardings,
        the ``parallel/zero.py`` idiom) so no device ever holds a full
        ``mu``/``nu``; ``mesh=None`` skips placement (native path /
        tests)."""
        def init(p):
            flat, _ = ravel_pytree(p)
            return self.optimizer.init(
                jnp.pad(flat, (0, self.padded - self.size))
            )

        if mesh is None:
            return jax.jit(init)(params)
        shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), self.opt_state_specs()
        )
        return jax.jit(init, out_shardings=shardings)(params)

    # -- layout bijection (checkpoints stay unsharded) ----------------------

    def _unravel(self):
        # built from a zeros tree, NOT the live template: the trainer's
        # initial params get donated (deleted) by the step program, and
        # the closure only needs shapes/dtypes/treedef anyway (this also
        # serves abstract ShapeDtypeStruct templates)
        if self._unravel_fn is None:
            zeros = jax.tree.map(
                lambda l: jnp.zeros(l.shape, l.dtype),
                self._params_template,
            )
            self._unravel_fn = ravel_pytree(zeros)[1]
        return self._unravel_fn

    def replicated_opt_state(self, flat_state):
        """Sharded flat layout -> the standard ``optimizer.init(params)``
        layout (host-side; gathers the sharded leaves).  What
        ``_checkpoint_state`` writes, so every checkpoint consumer keeps
        seeing the unsharded layout."""
        unravel = self._unravel()
        leaves, treedef = jax.tree.flatten(flat_state)
        out = []
        for leaf in leaves:
            if self._is_full_vector(leaf):
                out.append(unravel(jnp.asarray(leaf)[: self.size]))
            else:
                out.append(leaf)
        # unflatten with pytrees in the vector slots nests them - exactly
        # the standard layout, where mu/nu are params-shaped pytrees
        return jax.tree.unflatten(treedef, out)

    def flat_opt_state(self, std_state):
        """Standard layout -> sharded flat layout (the resume path: a
        checkpoint's unsharded state re-raveled for the live step)."""
        struct = self.abstract_opt_state()
        outer = jax.tree.structure(struct)
        out = []
        for sub, spec in zip(outer.flatten_up_to(std_state),
                             jax.tree.leaves(struct)):
            if self._is_full_vector(spec):
                flat, _ = ravel_pytree(sub)
                out.append(jnp.pad(flat, (0, self.padded - self.size)))
            else:
                out.append(sub)
        return jax.tree.unflatten(outer, out)

    # -- native (process-per-rank) side -------------------------------------

    def _is_shard_vector(self, leaf) -> bool:
        return getattr(leaf, "ndim", 0) == 1 and leaf.shape[0] == self.shard

    def pad_flat(self, flat: np.ndarray) -> np.ndarray:
        """Zero-pad a raveled host vector to the equal-shard length."""
        out = np.zeros(self.padded, dtype=flat.dtype)
        out[: self.size] = flat
        return out

    def shard_slice(self, flat: np.ndarray, rank: int) -> np.ndarray:
        return flat[rank * self.shard: (rank + 1) * self.shard]

    def init_shard_opt_state(self, params, rank: int):
        """Rank's 1/world slice of the optimizer state - the only state
        a native rank keeps (the memory half of the paper's claim)."""
        flat, _ = ravel_pytree(params)
        p_shard = self.shard_slice(self.pad_flat(np.asarray(flat)), rank)
        return self.optimizer.init(jnp.asarray(p_shard))

    def gather_opt_state(self, shard_state, allgather):
        """Shard-layout state -> standard layout via ``allgather(vec) ->
        (world, len(vec))`` - the COLLECTIVE checkpoint gather, so it
        must run on every rank of the ring symmetrically."""
        unravel = self._unravel()
        leaves, treedef = jax.tree.flatten(shard_state)
        out = []
        for leaf in leaves:
            if self._is_shard_vector(leaf):
                full = np.asarray(
                    allgather(np.ascontiguousarray(np.asarray(leaf)))
                ).reshape(-1)[: self.size]
                out.append(unravel(jnp.asarray(full)))
            else:
                out.append(leaf)
        return jax.tree.unflatten(treedef, out)

    # -- bucketed overlap (native ring only) ---------------------------------
    #
    # Buckets partition THIS RANK's shard range [0, shard) - see
    # parallel/bucketing.py for why that (and not a contiguous split of
    # the padded vector) keeps the ring's per-element accumulation order,
    # and therefore the update, bitwise-identical to the monolithic path.
    # Optimizer state in bucketed mode is a LIST of per-bucket states
    # (each bucket's apply runs once per step, so scalar counters like
    # adam's `count` advance identically in every bucket); checkpoints
    # still carry the standard unsharded layout via merge -> gather.

    def bucket_plan(self, bucket_mb: float, itemsize: int | None = None):
        """The rank-shard bucket layout for this binding; ``itemsize``
        is the WIRE dtype's (what rides TCP - may differ from the param
        ravel dtype when the ring does not support it)."""
        from pytorch_distributed_rnn_tpu.parallel.bucketing import plan_buckets

        return plan_buckets(
            self.size, self.world,
            int(itemsize) if itemsize else np.dtype(self.dtype).itemsize,
            bucket_mb,
        )

    def _is_bucket_vector(self, leaf, blen: int) -> bool:
        return getattr(leaf, "ndim", 0) == 1 and leaf.shape[0] == blen

    def init_bucket_opt_state(self, params, rank: int, plan):
        """Per-bucket slices of the rank's shard optimizer state."""
        flat, _ = ravel_pytree(params)
        p_shard = self.shard_slice(self.pad_flat(np.asarray(flat)), rank)
        return [
            self.optimizer.init(jnp.asarray(p_shard[lo:hi]))
            for lo, hi in plan.bounds
        ]

    def merge_bucket_opt_state(self, bucket_states, plan):
        """Per-bucket states -> the rank's shard-layout state (vector
        leaves concatenated in bucket order = shard order; scalar leaves
        taken from bucket 0 - identical across buckets by construction).
        Feeds :meth:`gather_opt_state` at checkpoint time."""
        leaves0, treedef = jax.tree.flatten(bucket_states[0])
        all_leaves = [jax.tree.flatten(s)[0] for s in bucket_states]
        out = []
        for i, leaf in enumerate(leaves0):
            if self._is_bucket_vector(leaf, plan.bucket_len(0)):
                out.append(jnp.concatenate([
                    jnp.asarray(all_leaves[b][i])
                    for b in range(len(bucket_states))
                ]))
            else:
                out.append(leaf)
        return jax.tree.unflatten(treedef, out)

    def split_shard_opt_state(self, shard_state, plan):
        """The rank's shard-layout state -> per-bucket list (the bucketed
        resume path, after :meth:`shard_opt_state`)."""
        leaves, treedef = jax.tree.flatten(shard_state)
        return [
            jax.tree.unflatten(treedef, [
                jnp.asarray(l)[lo:hi] if self._is_shard_vector(l) else l
                for l in leaves
            ])
            for lo, hi in plan.bounds
        ]

    def shard_opt_state(self, std_state, rank: int):
        """Standard layout -> rank's shard-layout state (native resume)."""
        struct = jax.eval_shape(
            self.optimizer.init, jax.ShapeDtypeStruct((self.shard,),
                                                      self.dtype)
        )
        outer = jax.tree.structure(struct)
        out = []
        for sub, spec in zip(outer.flatten_up_to(std_state),
                             jax.tree.leaves(struct)):
            if self._is_shard_vector(spec):
                flat, _ = ravel_pytree(sub)
                out.append(jnp.asarray(
                    self.shard_slice(self.pad_flat(np.asarray(flat)), rank)
                ))
            else:
                out.append(sub)
        return jax.tree.unflatten(outer, out)
