"""Mesh strategies: TP/SP/PP as first-class *training* strategies.

The reference's key inversion is "strategy = CLI subcommand mapped onto one
shared loop" (``/root/reference/src/motion/trainer/__init__.py:10-18``);
its only axis is data parallelism.  Round 1 shipped tensor/sequence/
pipeline parallelism as forward-only library factories (``parallel/
{tp,sp,pp}.py``); this module promotes them to trainable strategies behind
a mesh spec like ``dp=2,sp=4``:

- the *loss body* here runs INSIDE the data-parallel ``shard_map`` programs
  built by ``parallel/dp.py`` (the trainers' epoch/run factories), where
  every mesh axis name is bound - so the same factories, batch plumbing,
  and checkpointing drive any composed mesh, and ``jax.grad`` transposes
  the sp/tp/pp collectives into the exact backward exchanges
  (ppermute -> reverse hop, psum -> broadcast, ...);
- batch rows shard over ``dp`` exactly as before; ``sp`` shards the time
  axis (wavefront relay), ``tp`` shards LSTM gates + head rows
  (Megatron-style), ``pp`` stages the layer stack (GPipe schedule).

Supported RNN meshes: ``dp`` composed with one of ``sp``/``tp``/``pp``,
plus the composed ``sp x tp`` pair for the char-LM family (gate-sharded
cell inside the sp relay, ``parallel/combined.py:sp_tp_stacked_rnn`` -
r4; the attention family composes the full dp x sp x tp via the same
module).  Cells: both LSTM and GRU run on every model axis - sp
(sequential relay), tp (gate-sharded), pp (GPipe stages).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P
from pytorch_distributed_rnn_tpu.utils.compat import shard_map

from pytorch_distributed_rnn_tpu.ops.losses import cross_entropy_loss
from pytorch_distributed_rnn_tpu.ops.rnn import dtype_of
from pytorch_distributed_rnn_tpu.parallel.collectives import broadcast_from
from pytorch_distributed_rnn_tpu.parallel.pp import pp_stacked_rnn
from pytorch_distributed_rnn_tpu.parallel.sp import (
    sp_stacked_gru,
    sp_stacked_lstm,
    sp_stacked_lstm_wavefront,
)
from pytorch_distributed_rnn_tpu.parallel.tp import (
    row_parallel_head,
    tp_stacked_gru,
    tp_stacked_lstm,
)

MODEL_AXES = ("sp", "tp", "pp")


def resolve_model_levers(model):
    """``(compute_dtype, remat)`` from a model's precision/remat fields -
    the one resolution shared by every mesh loss builder, so a new
    precision value cannot silently train at the wrong dtype at a missed
    call site."""
    return (dtype_of(getattr(model, "precision", "f32")),
            getattr(model, "remat", False))


def parse_mesh_spec(spec: str) -> dict[str, int]:
    """``"dp=2,sp=4"`` -> ``{"dp": 2, "sp": 4}``.  Axis names are
    validated; sizes are ints (-1 = all remaining devices, as in
    :func:`~pytorch_distributed_rnn_tpu.parallel.mesh.make_mesh`)."""
    axes: dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad mesh axis {part!r} (want name=size)")
        name, _, size = part.partition("=")
        name = name.strip()
        if name in axes:
            raise ValueError(f"duplicate mesh axis {name!r}")
        if name not in ("dp", "ep") + MODEL_AXES:
            raise ValueError(
                f"unknown mesh axis {name!r} (known: dp, sp, tp, pp, ep)"
            )
        axes[name] = int(size)
    if not axes:
        raise ValueError(f"empty mesh spec {spec!r}")
    return axes


def validate_rnn_mesh(axes: dict[str, int], cell: str = "lstm",
                      allow_sp_tp: bool = False):
    """Reject mesh specs the RNN kernels cannot run.

    Both cells run on every model axis: sp (sequential relay), tp
    (gate-sharded), pp (GPipe stage runner - cell-generic since r3).
    With ``allow_sp_tp`` (the char-LM family, r4) the sp and tp axes
    additionally COMPOSE - the gate-sharded cell runs inside the sp
    relay (``parallel/combined.py:sp_tp_stacked_rnn``) - returning the
    composite axis name ``"sp+tp"``.
    """
    model_axes = [a for a in MODEL_AXES if axes.get(a, 1) > 1]
    if len(model_axes) > 1:
        if allow_sp_tp and set(model_axes) == {"sp", "tp"}:
            if cell not in ("lstm", "gru"):
                raise ValueError(f"unknown cell {cell!r}")
            return "sp+tp"
        raise ValueError(
            f"RNN meshes support dp plus at most ONE of sp/tp/pp "
            f"(plus sp x tp for the char family), got {model_axes} "
            f"(the attention family composes dp x sp x tp, see "
            f"parallel/combined.py)"
        )
    if model_axes and cell not in ("lstm", "gru"):
        raise ValueError(f"unknown cell {cell!r}")
    return model_axes[0] if model_axes else None


def _sp_stack(cell: str, schedule: str):
    """The sp relay stack for a cell: the wavefront schedule is
    LSTM-structured, so GRU always relays layer-sequentially."""
    if cell == "gru":
        return sp_stacked_gru
    return (
        sp_stacked_lstm_wavefront if schedule == "wavefront"
        else sp_stacked_lstm
    )


def mesh_rnn_forward(params, x, *, sp=None, tp=None, pp=None,
                     schedule: str = "wavefront", num_microbatches: int = 4,
                     unroll: int = 1, dropout: float = 0.0,
                     dropout_key=None, cell: str = "lstm",
                     compute_dtype=None, remat: bool = False):
    """Motion-model forward (stacked LSTM/GRU -> last-step head) for use
    INSIDE a ``shard_map`` program where the named axes are bound.

    ``x`` (B_local, T, in) arrives dp-local and replicated over the model
    axes; logits (B_local, out) return replicated over the model axes (so
    the caller's dp-only loss/metric collectives stay correct).

    ``compute_dtype``/``remat`` thread through EVERY model-axis branch
    (sp relay, tp gate-sharded, pp GPipe stages, unsharded) - the head
    stays f32 like ``MotionModel.apply``.  ``dropout`` applies on the
    unsharded and ``sp`` branches only (each sp shard folds its index
    into the dropout key for an independent mask over its local
    positions); the tp/pp stacks have no dropout seam and the callers
    reject that combination loudly.
    """
    if sum(a is not None for a in (sp, tp, pp)) > 1:
        raise ValueError("compose dp with at most one of sp/tp/pp")

    if sp is not None:
        n = lax.axis_size(sp)
        k = lax.axis_index(sp)
        t = x.shape[1]
        if t % n != 0:
            raise ValueError(f"seq len {t} not divisible by sp={n}")
        t_local = t // n
        x_loc = lax.dynamic_slice_in_dim(x, k * t_local, t_local, axis=1)
        sp_key = (None if dropout_key is None
                  else jax.random.fold_in(dropout_key, k))
        out_local, _ = _sp_stack(cell, schedule)(
            params["rnn"], x_loc, sp, unroll=unroll,
            compute_dtype=compute_dtype, remat=remat,
            dropout=dropout, dropout_key=sp_key,
        )
        # true last step on shard n-1 only; head in f32 (model contract)
        last = out_local[:, -1, :].astype(jnp.float32)
        logits = last @ params["fc"]["weight"].T + params["fc"]["bias"]
        return broadcast_from(logits, sp, n - 1)

    if tp is not None:
        stack = tp_stacked_gru if cell == "gru" else tp_stacked_lstm
        out, _ = stack(params["rnn"], x, tp, unroll=unroll,
                       compute_dtype=compute_dtype, remat=remat)
        # head in f32 (model contract); no-op in pure f32
        return row_parallel_head(
            params["fc"], out[:, -1, :].astype(jnp.float32), tp
        )

    if pp is not None:
        out = pp_stacked_rnn(
            params["rnn"], x, pp, num_microbatches=num_microbatches,
            unroll=unroll, cell=cell, compute_dtype=compute_dtype,
            remat=remat,
        )
        last = out[:, -1, :].astype(jnp.float32)
        return last @ params["fc"]["weight"].T + params["fc"]["bias"]

    from pytorch_distributed_rnn_tpu.ops.rnn import stacked_rnn

    out, _ = stacked_rnn(params["rnn"], x, cell, unroll=unroll,
                         impl="scan", dropout=dropout,
                         dropout_key=dropout_key,
                         compute_dtype=compute_dtype, remat=remat)
    last = out[:, -1, :].astype(jnp.float32)
    return last @ params["fc"]["weight"].T + params["fc"]["bias"]


# ---------------------------------------------------------------------------
# Char-LM mesh training step (per-timestep head; the long-context story)
# ---------------------------------------------------------------------------

def _char_local_logits(params, tokens, *, sp=None, tp=None, pp=None,
                       schedule: str = "wavefront",
                       num_microbatches: int = 4, unroll: int = 1,
                       cell: str = "lstm", compute_dtype=None,
                       remat: bool = False, dropout: float = 0.0,
                       dropout_key=None):
    """The ONE char-LM mesh forward: ``(logits, targets, w_pos)``.

    ``tokens`` (B_local, T) int32, replicated over the model axes.  With
    ``sp`` the time axis is sharded - each shard embeds + runs its chunk
    through the relay stack and returns logits/targets for its LOCAL
    positions, with ``w_pos`` (1, t_local) masking the one padding
    position (the final global position predicts nothing); the shifted
    target slice is local arithmetic because tokens are replicated, so no
    boundary exchange is needed.  Without ``sp``: full-window logits
    (B, T-1, V), ``w_pos`` None.  With BOTH ``sp`` and ``tp`` (the
    composed char pair): the gate-sharded cell runs inside the sp relay
    and the per-timestep head is row-parallel over tp.
    ``compute_dtype``/``remat`` thread through EVERY model-axis branch
    (sp relay, sp x tp, tp gate-sharded, pp GPipe stages, unsharded);
    the head stays f32.  ``dropout`` applies on the unsharded, ``sp``,
    and ``sp x tp`` branches (each sp shard folds its index into the
    dropout key; the composed relay masks the gathered full-width
    interlayer seam); the tp-only/pp stacks have no dropout seam -
    callers reject that combination loudly.
    """
    if pp is not None and (sp is not None or tp is not None):
        raise ValueError("pp does not compose with sp/tp for the char LM")
    head_w, head_b = params["head"]["weight"], params["head"]["bias"]
    t = tokens.shape[1]

    def sp_chunk():
        """Shared sp prologue: this shard's token chunk embedded, plus
        the shard-folded dropout key and chunk coordinates."""
        n = lax.axis_size(sp)
        k = lax.axis_index(sp)
        if t % n != 0:
            raise ValueError(
                f"char-LM window ({t} = seq_length + 1) not divisible by "
                f"sp={n} - pick --seq-length so that sp divides "
                f"seq_length + 1"
            )
        t_local = t // n
        tok_loc = lax.dynamic_slice_in_dim(tokens, k * t_local, t_local,
                                           axis=1)
        sp_key = (None if dropout_key is None
                  else jax.random.fold_in(dropout_key, k))
        return k, t_local, params["embed"][tok_loc], sp_key

    def sp_targets(k, t_local):
        """Local target slice + padding-position weights: the final
        global position predicts nothing, masked via w_pos."""
        shifted = jnp.concatenate(
            [tokens[:, 1:], tokens[:, -1:]], axis=1
        )
        tgt_loc = lax.dynamic_slice_in_dim(shifted, k * t_local, t_local,
                                           axis=1)
        pos = k * t_local + jnp.arange(t_local)
        return tgt_loc, (pos < t - 1).astype(jnp.float32)[None, :]

    def row_parallel_timestep_head(h_local):
        """Row-parallel per-timestep head on this tp shard's (B, T', H/n)
        hidden slice: one psum combines partial logits; f32 head."""
        ntp = lax.axis_size(tp)
        ktp = lax.axis_index(tp)
        hidden = head_w.shape[1]
        if hidden % ntp != 0:
            raise ValueError(f"hidden {hidden} not divisible by tp={ntp}")
        per = hidden // ntp
        w_local = lax.dynamic_slice_in_dim(head_w, ktp * per, per, axis=1)
        # contract: the head accumulates logits in f32 regardless of the
        # backbone compute dtype (intentional upcast)
        return lax.psum(
            jnp.einsum("bth,vh->btv",
                       h_local.astype(jnp.float32),  # noqa: PD203
                       w_local), tp
        ) + head_b

    if sp is not None and tp is not None:
        # the composed axis pair: gate-sharded cell inside the sp relay
        # (parallel/combined.py) with a row-parallel per-timestep head
        from pytorch_distributed_rnn_tpu.parallel.combined import (
            sp_tp_stacked_rnn,
        )

        k, t_local, x_loc, sp_key = sp_chunk()
        out_local, _ = sp_tp_stacked_rnn(
            params["rnn"], x_loc, sp, tp, cell=cell, unroll=unroll,
            compute_dtype=compute_dtype, remat=remat,
            dropout=dropout, dropout_key=sp_key,
        )
        # out_local is already the tp-LOCAL (B, T/S, H/ntp) slice
        logits = row_parallel_timestep_head(out_local)
        tgt_loc, w_pos = sp_targets(k, t_local)
        return logits, tgt_loc, w_pos

    if sp is not None:
        k, t_local, x_loc, sp_key = sp_chunk()
        out_local, _ = _sp_stack(cell, schedule)(
            params["rnn"], x_loc, sp, unroll=unroll,
            compute_dtype=compute_dtype, remat=remat,
            dropout=dropout, dropout_key=sp_key,
        )
        # (B, t_local, V); head in f32 like the unsharded branch
        logits = out_local.astype(jnp.float32) @ head_w.T + head_b
        tgt_loc, w_pos = sp_targets(k, t_local)
        return logits, tgt_loc, w_pos

    x = params["embed"][tokens[:, :-1]]
    if tp is not None:
        stack = tp_stacked_gru if cell == "gru" else tp_stacked_lstm
        out, _ = stack(params["rnn"], x, tp, unroll=unroll,
                       compute_dtype=compute_dtype, remat=remat)
        # the tp stack all-gathers its output full-width; re-slice this
        # shard's piece for the row-parallel head (which validates the
        # hidden/tp divisibility)
        ntp = lax.axis_size(tp)
        per = max(head_w.shape[1] // ntp, 1)
        h_local = lax.dynamic_slice_in_dim(
            out, lax.axis_index(tp) * per, per, axis=2)
        logits = row_parallel_timestep_head(h_local)
    elif pp is not None:
        out = pp_stacked_rnn(
            params["rnn"], x, pp, num_microbatches=num_microbatches,
            unroll=unroll, cell=cell, compute_dtype=compute_dtype,
            remat=remat,
        )
        logits = out.astype(jnp.float32) @ head_w.T + head_b
    else:
        from pytorch_distributed_rnn_tpu.ops.rnn import stacked_rnn

        out, _ = stacked_rnn(params["rnn"], x, cell, unroll=unroll,
                             impl="scan", compute_dtype=compute_dtype,
                             remat=remat, dropout=dropout,
                             dropout_key=dropout_key)
        logits = out.astype(jnp.float32) @ head_w.T + head_b

    return logits, tokens[:, 1:], None


def char_mesh_loss(params, tokens, *, sp=None, tp=None, pp=None,
                   schedule: str = "wavefront", num_microbatches: int = 4,
                   unroll: int = 1, dp: str = "dp", cell: str = "lstm"):
    """Next-token loss for a CharRNN params tree inside a mesh program:
    the global mean over the window's T-1 predicted positions, assembled
    by weighted psum over ``sp`` when the time axis is sharded."""
    logits, targets, w_pos = _char_local_logits(
        params, tokens, sp=sp, tp=tp, pp=pp, schedule=schedule,
        num_microbatches=num_microbatches, unroll=unroll, cell=cell,
    )
    vocab = params["head"]["weight"].shape[0]
    if w_pos is not None:
        t = tokens.shape[1]
        nll = cross_entropy_loss(
            logits.reshape(-1, vocab), targets.reshape(-1),
            reduction="none",
        ).reshape(targets.shape)
        loss = lax.psum(jnp.sum(nll * w_pos), sp) / (
            tokens.shape[0] * (t - 1)
        )
        return lax.pmean(loss, dp)

    loss = cross_entropy_loss(
        logits.reshape(-1, vocab), targets.reshape(-1)
    )
    return lax.pmean(loss, dp)


def _axis_kwargs(axes: dict[str, int], cell: str = "lstm",
                 allow_sp_tp: bool = False):
    """``(kwargs, model_axis)``: {"sp": "sp" or None, ...} for the active
    model axis (or the composed sp x tp pair when ``allow_sp_tp``
    resolves to it, model_axis ``"sp+tp"``) - ONE validation call, so the
    kwargs and the axis name can never disagree."""
    model_axis = validate_rnn_mesh(axes, cell, allow_sp_tp=allow_sp_tp)
    if model_axis == "sp+tp":
        return {"sp": "sp", "tp": "tp", "pp": None}, model_axis
    kw = {a: (a if a == model_axis else None) for a in MODEL_AXES}
    return kw, model_axis


def _reject_unsupported_mesh_levers(model_axis, precision: str,
                                    remat: bool, dropout: float,
                                    schedule: str = "wavefront",
                                    cell: str = "lstm",
                                    num_layers: int | None = None):
    """Loud, never silent: bf16 + remat thread through EVERY model axis
    (sp relay since r2, tp gate-sharded + pp GPipe stages since r4) and
    dropout through the unsharded and sp branches - but sp dropout needs
    the SEQUENTIAL relay (the wavefront interleaves all layers in one
    scan, leaving no between-layer seam to mask at; GRU always relays
    sequentially), and the tp/pp stacks have no dropout seam at all.
    Honoring those flag combinations is not possible, so do not pretend
    to."""
    del precision, remat  # every model axis honors both since r4
    # NOTE: the composed "sp+tp" axis always relays layer-sequentially
    # (the gate-sharded chunk scan has no wavefront form); like the GRU,
    # the wavefront DEFAULT coerces to sequential there rather than
    # rejecting - --sp-schedule only ever selects among schedules that
    # exist for the cell/composition (see _sp_stack).
    if model_axis in ("tp", "pp") and dropout > 0.0:
        raise ValueError(
            f"dropout is not supported on the {model_axis} mesh (the "
            "stage/gate kernels thread no dropout) - use a dp or dp x sp "
            "mesh, or --dropout 0"
        )
    if (model_axis == "sp" and dropout > 0.0
            and cell == "lstm" and schedule != "sequential"
            and (num_layers is None or num_layers > 1)):
        # single-layer stacks have no between-layer seam: dropout is a
        # provable no-op there (and the wavefront delegates to the
        # sequential relay at L=1), so only multi-layer stacks reject
        raise ValueError(
            "sp dropout needs the sequential relay (the wavefront "
            "schedule has no between-layer seam to mask at) - pass "
            "--sp-schedule sequential or --dropout 0"
        )


def make_char_mesh_train_step(optimizer, mesh, axes: dict[str, int], *,
                              schedule: str = "wavefront",
                              num_microbatches: int = 4, unroll: int = 1,
                              donate: bool = True, cell: str = "lstm"):
    """Jitted char-LM training step over a composed mesh.

    ``step(params, opt_state, tokens)`` with ``tokens`` (B, T) sharded
    ``P("dp")`` on batch; params/opt replicated.  The model axis (sp, tp,
    pp, or the composed sp x tp pair) comes from ``axes``.

    The gradient is taken OUTSIDE the ``shard_map`` (like
    ``parallel/combined.py``): differentiating the replicated-scalar loss
    lets jax insert exactly the right backward collectives and the psums
    that re-reduce replicated-parameter cotangents - taking grad inside
    would double-count replicated pieces and drop cross-shard terms.
    """
    kw, _ = _axis_kwargs(axes, cell, allow_sp_tp=True)

    from functools import partial as _partial

    @_partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P("dp")),
        out_specs=P(),
        check_vma=False,
    )
    def loss_fn(params, tokens):
        return char_mesh_loss(
            params, tokens, schedule=schedule,
            num_microbatches=num_microbatches, unroll=unroll, cell=cell,
            **kw,
        )

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


def _char_per_sequence_stats(params, tokens, *, sp=None, tp=None, pp=None,
                             schedule: str = "wavefront",
                             num_microbatches: int = 4, unroll: int = 1,
                             cell: str = "lstm", compute_dtype=None,
                             remat: bool = False, dropout: float = 0.0,
                             dropout_key=None):
    """Per-sequence LM statistics inside a mesh program: ``(nll, acc)``,
    each ``(B_local,)`` - the mean over the window's T-1 predicted
    positions, assembled across the model axis when the time dim is
    sharded.  Per-SEQUENCE (not per-token) stats are what the weighted
    fused-run path needs: its 0/1 mask weights whole (padded) sequences.
    """
    logits, targets, w_pos = _char_local_logits(
        params, tokens, sp=sp, tp=tp, pp=pp, schedule=schedule,
        num_microbatches=num_microbatches, unroll=unroll, cell=cell,
        compute_dtype=compute_dtype, remat=remat, dropout=dropout,
        dropout_key=dropout_key,
    )
    t = tokens.shape[1]
    vocab = params["head"]["weight"].shape[0]
    nll = cross_entropy_loss(
        logits.reshape(-1, vocab), targets.reshape(-1), reduction="none"
    ).reshape(targets.shape)
    corr = (jnp.argmax(logits, axis=-1) == targets).astype(jnp.float32)
    if w_pos is not None:  # sp: local positions, assembled by psum
        per_seq_nll = lax.psum(jnp.sum(nll * w_pos, axis=1), sp) / (t - 1)
        per_seq_acc = lax.psum(jnp.sum(corr * w_pos, axis=1), sp) / (t - 1)
        return per_seq_nll, per_seq_acc
    return jnp.mean(nll, axis=1), jnp.mean(corr, axis=1)


def make_char_mesh_loss_fn(mesh, axes: dict[str, int], *,
                           schedule: str = "wavefront",
                           num_microbatches: int = 4, unroll: int = 1,
                           weighted: bool = False, dropout: float = 0.0,
                           cell: str = "lstm", precision: str = "f32",
                           remat: bool = False,
                           num_layers: int | None = None):
    """Shard_mapped ``loss_fn(params, tokens, y[, w][, key]) -> (loss,
    metrics)`` for the char-LM over a composed mesh - the trainer-contract
    sibling of :func:`make_motion_mesh_loss_fn` (same batch plumbing:
    ``y`` is the dataset's dummy label column, accepted and ignored so the
    shared loaders/epoch programs drive the LM unchanged).

    ``metrics['correct']`` sums per-sequence mean token accuracy over the
    GLOBAL batch (``training/lm.py`` semantics), so the shared loop's
    ``correct / len(dataset)`` prints mean token accuracy.
    """
    kw, model_axis = _axis_kwargs(axes, cell, allow_sp_tp=True)
    _reject_unsupported_mesh_levers(model_axis, precision, remat, dropout,
                                    schedule=schedule, cell=cell,
                                    num_layers=num_layers)
    compute_dtype = dtype_of(precision)

    from functools import partial as _partial

    batch_specs = (P("dp"), P("dp")) + ((P("dp"),) if weighted else ())
    key_specs = (P(),) if dropout > 0.0 else ()

    @_partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(),) + batch_specs + key_specs,
        out_specs=(P(), P()),
        check_vma=False,
    )
    def loss_fn(params, tokens, y, *extra):
        if dropout > 0.0:
            key = jax.random.fold_in(extra[-1], lax.axis_index("dp"))
            extra = extra[:-1]
        else:
            key = None
        per_seq_nll, per_seq_acc = _char_per_sequence_stats(
            params, tokens, schedule=schedule,
            num_microbatches=num_microbatches, unroll=unroll, cell=cell,
            compute_dtype=compute_dtype, remat=remat,
            dropout=dropout, dropout_key=key, **kw,
        )
        if weighted:
            w = extra[0]
            local = jnp.sum(per_seq_nll * w) / jnp.maximum(jnp.sum(w), 1.0)
            correct = jnp.sum(per_seq_acc * (w > 0))
        else:
            local = jnp.mean(per_seq_nll)
            correct = jnp.sum(per_seq_acc)
        return (
            lax.pmean(local, "dp"),
            {"correct": lax.psum(correct, "dp")},
        )

    return loss_fn


# ---------------------------------------------------------------------------
# Motion-model mesh factories (drive the shared Trainer loop)
# ---------------------------------------------------------------------------

def _make_pp_1f1b_loss_fn(mesh, axes, engine_of, *, weighted: bool):
    """The shared custom-vjp scaffold for the 1F1B loss factories.

    ``engine_of(params, batch_x, w) -> (loss_sum, correct, w_sum,
    grads)`` runs the family's self-differentiating schedule
    (``parallel/pp.py:_pp_interleaved_engine`` wrappers); this wrapper owns the
    mesh validation, the shard_map decoration, the custom_vjp that hands
    the precomputed stage-local grads to shard_map's replicated-param
    transpose, and the dp pmean/psum epilogue - ONE copy of the
    empirically-verified 1/pp cotangent-undo correction.
    """
    from functools import partial as _partial

    if (set(a for a, v in axes.items() if v != 1) - {"dp", "pp"}
            or "pp" not in axes):
        raise ValueError(
            f"1f1b runs on dp x pp meshes only (pp axis required); "
            f"got {dict(axes)}"
        )

    batch_specs = (P("dp"), P("dp")) + ((P("dp"),) if weighted else ())

    @_partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(),) + batch_specs,
        out_specs=(P(), P()),
        check_vma=False,
    )
    def loss_fn(params, x, y, *extra):
        w = extra[0] if weighted else None

        def engine(p):
            return engine_of(p, x, y, w)

        @jax.custom_vjp
        def f(p):
            loss_sum, correct, w_sum, _ = engine(p)
            return loss_sum / jnp.maximum(w_sum, 1.0), correct

        def f_fwd(p):
            loss_sum, correct, w_sum, grads = engine(p)
            grads = jax.tree.map(
                lambda g: g / jnp.maximum(w_sum, 1.0), grads
            )
            return (loss_sum / jnp.maximum(w_sum, 1.0), correct), grads

        def f_bwd(grads, cts):
            ct_loss, _ = cts  # `correct` is a metric, not differentiated
            # the replicated (P()) output's transpose splits the incoming
            # cotangent 1/pp across the pp shards; undo it so the
            # replicated-param transpose's sum counts each stage's
            # contribution exactly once (verified empirically at pp=2,4)
            ct_loss = ct_loss * lax.axis_size("pp")
            return (jax.tree.map(lambda g: g * ct_loss, grads),)

        f.defvjp(f_fwd, f_bwd)
        local, correct = f(params)
        return (
            lax.pmean(local, "dp"),
            {"correct": lax.psum(correct, "dp")},
        )

    return loss_fn


def make_motion_pp_1f1b_loss_fn(mesh, axes: dict[str, int], *,
                                num_microbatches: int = 4,
                                num_chunks: int = 1, unroll: int = 1,
                                weighted: bool = False, cell: str = "lstm",
                                precision: str = "f32"):
    """Shard_mapped motion loss over a dp x pp mesh running the 1F1B
    (PipeDream-flush) schedule instead of GPipe - same ``loss_fn(params,
    x, y[, w]) -> (loss, metrics)`` contract as
    :func:`make_motion_mesh_loss_fn`, so ``make_mesh_grad_step``'s
    ``jax.value_and_grad`` drives it unchanged.

    The 1F1B program computes its OWN gradients (the schedule interleaves
    each microbatch's backward right after its forward, bounding live
    activations to the in-flight limit instead of GPipe's all-M);
    ``jax.checkpoint``-style remat is inherent (the backward op
    recomputes its stage from the stashed input), so ``remat`` is not a
    separate lever here.
    """
    from pytorch_distributed_rnn_tpu.parallel.pp import (
        pp_rnn_1f1b_value_and_grad,
    )

    compute_dtype = dtype_of(precision)

    def engine_of(p, x, y, w):
        return pp_rnn_1f1b_value_and_grad(
            p["rnn"], p["fc"], x, y, "pp",
            num_microbatches=num_microbatches, num_chunks=num_chunks,
            unroll=unroll, cell=cell,
            compute_dtype=compute_dtype, sample_weights=w,
        )

    return _make_pp_1f1b_loss_fn(mesh, axes, engine_of, weighted=weighted)


def make_char_pp_1f1b_loss_fn(mesh, axes: dict[str, int], *,
                              num_microbatches: int = 4,
                              num_chunks: int = 1, unroll: int = 1,
                              weighted: bool = False, cell: str = "lstm",
                              precision: str = "f32"):
    """Char-LM sibling of :func:`make_motion_pp_1f1b_loss_fn`: the same
    custom-vjp contract (``loss_fn(params, tokens, y[, w]) -> (loss,
    metrics)``) over a dp x pp mesh running the 1F1B schedule, with the
    per-timestep vocab head and exact embedding gradients
    (``parallel/pp.py:pp_char_1f1b_value_and_grad``).  ``y`` is the
    dataset's dummy label column (the LM trainer contract)."""
    from pytorch_distributed_rnn_tpu.parallel.pp import (
        pp_char_1f1b_value_and_grad,
    )

    compute_dtype = dtype_of(precision)

    def engine_of(p, tokens, y, w):
        del y
        return pp_char_1f1b_value_and_grad(
            p["rnn"], p["head"], p["embed"], tokens, "pp",
            num_microbatches=num_microbatches, num_chunks=num_chunks,
            unroll=unroll, cell=cell,
            compute_dtype=compute_dtype, sample_weights=w,
        )

    return _make_pp_1f1b_loss_fn(mesh, axes, engine_of, weighted=weighted)


def make_motion_mesh_loss_fn(mesh, axes: dict[str, int], *,
                             schedule: str = "wavefront",
                             num_microbatches: int = 4, unroll: int = 1,
                             weighted: bool = False, dropout: float = 0.0,
                             cell: str = "lstm", precision: str = "f32",
                             remat: bool = False,
                             num_layers: int | None = None):
    """Shard_mapped ``loss_fn(params, x, y[, w][, key]) -> (loss,
    metrics)`` for the motion model over a composed mesh: ``x``/``y`` (and
    ``w``) shard their batch dim over ``dp``; the scalar loss and summed
    metrics come back replicated.  Grad is meant to be taken OUTSIDE (see
    :func:`make_char_mesh_train_step` for why).

    ``dropout > 0`` (dp-only meshes; the trainer guards the model axes)
    appends a trailing replicated per-step PRNG key argument; each dp
    shard folds its rank in for an independent mask.  ``precision``/
    ``remat`` thread through every model-axis branch exactly like the
    char mesh."""
    kw, model_axis = _axis_kwargs(axes, cell)
    _reject_unsupported_mesh_levers(model_axis, precision, remat, dropout,
                                    schedule=schedule, cell=cell,
                                    num_layers=num_layers)
    compute_dtype = dtype_of(precision)

    from functools import partial as _partial

    batch_specs = (P("dp"), P("dp")) + ((P("dp"),) if weighted else ())
    key_specs = (P(),) if dropout > 0.0 else ()

    @_partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(),) + batch_specs + key_specs,
        out_specs=(P(), P()),
        check_vma=False,
    )
    def loss_fn(params, x, y, *extra):
        if dropout > 0.0:
            key = jax.random.fold_in(extra[-1], lax.axis_index("dp"))
            extra = extra[:-1]
        else:
            key = None
        logits = mesh_rnn_forward(
            params, x, schedule=schedule,
            num_microbatches=num_microbatches, unroll=unroll,
            dropout=dropout, dropout_key=key, cell=cell,
            compute_dtype=compute_dtype, remat=remat, **kw,
        )
        local, correct = _classifier_loss_metrics(
            logits, y, extra[0] if weighted else None
        )
        return (
            lax.pmean(local, "dp"),
            {"correct": lax.psum(correct, "dp")},
        )

    return loss_fn


def _classifier_loss_metrics(logits, y, w=None):
    """The one (loss, correct) block shared by the motion and attention
    mesh losses: local mean loss + correct count, optionally 0/1-weighted
    (the fused whole-run path's padding mask).

    Weighted contract: the caller pmean's the LOCAL weighted means over
    ``dp``, which equals the global weighted mean only when every dp
    shard carries the same number of live (w>0) examples.  The trainers
    guarantee this - ``SpmdTrainer._pad_batch`` pads each rank's chunk
    independently (rank-equal live counts; see its docstring) - so do
    NOT feed this path batches padded only at the global tail."""
    if w is not None:
        nll = cross_entropy_loss(logits, y, reduction="none")
        local = jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)
        correct = jnp.sum((jnp.argmax(logits, axis=1) == y) * (w > 0))
    else:
        local = cross_entropy_loss(logits, y)
        correct = jnp.sum(jnp.argmax(logits, axis=1) == y)
    return local, correct


def make_attention_mesh_loss_fn(model, mesh, *, weighted: bool = False):
    """Shard_mapped ``loss_fn(params, x, y[, w]) -> (loss, metrics)`` for
    an :class:`AttentionClassifier` over a FULL dp x sp x tp mesh (any
    axis may have size 1): batch rows shard over ``dp``, time over ``sp``
    (ring attention rotates K/V blocks over the sp ring), heads + MLP
    hidden over ``tp`` (Megatron column/row sharding, one psum each).

    This is ``parallel/combined.py``'s composed program surfaced with the
    trainer loss/metrics contract, so the shared Trainer loop drives the
    full 3D composition from the CLI (``mesh --model attention --mesh
    dp=2,sp=2,tp=2``).
    """
    from functools import partial as _partial

    from pytorch_distributed_rnn_tpu.parallel.combined import (
        attention_mesh_logits,
    )
    from pytorch_distributed_rnn_tpu.ops.pallas_attention import (
        resolve_attention_impl,
    )

    impl = resolve_attention_impl(getattr(model, "impl", "auto"))
    compute_dtype, remat = resolve_model_levers(model)

    for axis in ("dp", "sp", "tp"):
        if axis not in mesh.shape:
            raise ValueError(
                f"attention mesh needs axis {axis!r} (size 1 is fine); "
                f"got {dict(mesh.shape)}"
            )

    batch_specs = (P("dp", "sp"), P("dp")) + (
        (P("dp"),) if weighted else ()
    )

    @_partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(),) + batch_specs,
        out_specs=(P(), P()),
        check_vma=False,
    )
    def loss_fn(params, x_local, y_local, *w):
        logits = attention_mesh_logits(params, x_local, model.num_heads,
                                       impl=impl,
                                       compute_dtype=compute_dtype,
                                       remat=remat)
        local, correct = _classifier_loss_metrics(
            logits, y_local, w[0] if weighted else None
        )
        return (
            lax.pmean(local, "dp"),
            {"correct": lax.psum(correct, "dp")},
        )

    return loss_fn


def make_attention_pp_loss_fn(model, mesh, *, num_microbatches: int = 4,
                              weighted: bool = False):
    """Shard_mapped ``loss_fn(params, x, y[, w]) -> (loss, metrics)`` for
    the attention family over a dp x pp (x tp) mesh: encoder blocks split
    into GPipe stages over ``pp`` (``parallel/pp.py:
    pp_transformer_blocks``), batch rows over ``dp``, and - when the mesh
    carries a tp axis of size > 1 - Megatron head/MLP sharding INSIDE
    each stage (each (pp, tp) cell computes its head group + MLP slice;
    the per-block psums ride tp).  Embed/positions and the pooled head
    run replicated on every stage (position-wise and tiny; the head
    computes f32).  ``model.precision``/``model.remat`` thread into the
    staged blocks (r4).  pp does not compose with sp in one program -
    the trainer rejects those specs loudly."""
    compute_dtype, remat = resolve_model_levers(model)

    from functools import partial as _partial

    from pytorch_distributed_rnn_tpu.models.attention import _linear
    from pytorch_distributed_rnn_tpu.ops.pallas_attention import (
        resolve_attention_impl,
    )
    from pytorch_distributed_rnn_tpu.parallel.pp import (
        pp_transformer_blocks,
    )

    # resolve the model's "auto" like the dp x sp x tp path: a flash
    # request must reach the staged blocks, not silently drop to dense
    impl = resolve_attention_impl(getattr(model, "impl", "auto"))

    for axis in ("dp", "pp"):
        if axis not in mesh.shape:
            raise ValueError(
                f"attention pp mesh needs axis {axis!r} (size 1 is "
                f"fine); got {dict(mesh.shape)}"
            )
    tp_axis = "tp" if mesh.shape.get("tp", 1) > 1 else None

    batch_specs = (P("dp"), P("dp")) + ((P("dp"),) if weighted else ())

    @_partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(),) + batch_specs,
        out_specs=(P(), P()),
        check_vma=False,
    )
    def loss_fn(params, x_local, y_local, *w):
        t = x_local.shape[1]
        h = _linear(params["embed"], x_local) + params["pos"][:t]
        h = pp_transformer_blocks(
            params["blocks"], h, "pp", num_heads=model.num_heads,
            num_microbatches=num_microbatches,
            compute_dtype=compute_dtype, remat=remat, tp_axis=tp_axis,
            impl=impl,
        )
        logits = _linear(params["head"],
                         jnp.mean(h.astype(jnp.float32), axis=1))
        local, correct = _classifier_loss_metrics(
            logits, y_local, w[0] if weighted else None
        )
        return (
            lax.pmean(local, "dp"),
            {"correct": lax.psum(correct, "dp")},
        )

    return loss_fn


def make_moe_mesh_loss_fn(model, mesh, *, weighted: bool = False):
    """Shard_mapped ``loss_fn(params, x, y[, w]) -> (loss, metrics)`` for a
    :class:`~pytorch_distributed_rnn_tpu.models.MoEClassifier` over a
    dp x ep mesh (either axis may have size 1).

    Layout (the textbook MoE placement): batch rows shard over the FULL
    dp x ep product - every device is a data shard for the backbone - and
    the experts shard over ``ep`` (``parallel/ep.py``: all_to_all
    dispatch/combine riding ICI).  Params replicated; grad outside the
    shard_map re-reduces replicated-parameter cotangents and transposes
    the all_to_alls into the reverse exchanges.

    The weighted path computes the EXACT global weighted mean
    (psum(num)/psum(den)) rather than the pmean-of-local-means shortcut:
    with data sharded over two axes the live-count-balance precondition of
    the shortcut (``_classifier_loss_metrics`` docstring) spans (dp, ep)
    cells, and exactness here is free.  Aux statistics pmean over BOTH
    axes, so the Switch loss is the global-batch value - identical to the
    dense single-device path when capacity is ample.
    ``model.precision``/``model.remat`` thread like the dense path (r4):
    backbone + expert matmuls and the all_to_all wire bytes in bf16, the
    router f32; remat checkpoints the backbone layers and the dispatch.
    """
    from functools import partial as _partial

    compute_dtype, remat = resolve_model_levers(model)

    for axis in ("dp", "ep"):
        if axis not in mesh.shape:
            raise ValueError(
                f"moe mesh needs axis {axis!r} (size 1 is fine); got "
                f"{dict(mesh.shape)}"
            )

    from pytorch_distributed_rnn_tpu.ops.rnn import stacked_rnn
    from pytorch_distributed_rnn_tpu.parallel.ep import ep_moe_ffn

    data = ("dp", "ep")
    batch_specs = (P(data), P(data)) + ((P(data),) if weighted else ())

    @_partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(),) + batch_specs,
        out_specs=(P(), P()),
        check_vma=False,
    )
    def loss_fn(params, x_local, y_local, *w):
        out, _ = stacked_rnn(
            params["rnn"], x_local, model.cell, unroll=model.unroll,
            impl="scan", compute_dtype=compute_dtype, remat=remat,
        )
        from pytorch_distributed_rnn_tpu.ops.moe import (
            cast_expert_params,
        )

        moe_params = cast_expert_params(params["moe"], compute_dtype)
        def moe_call(mp, h_in):
            return ep_moe_ffn(
                mp, h_in, "ep",
                capacity_factor=model.capacity_factor,
                num_selected=model.num_selected,
                router=model.router_type,
                stat_axes=data,
                group_size=getattr(model, "group_size", None),
            )

        moe_fn = jax.checkpoint(moe_call) if remat else moe_call
        moe_out, aux = moe_fn(moe_params, out)
        h = out + moe_out
        last = h[:, -1, :].astype(jnp.float32)
        logits = last @ params["fc"]["weight"].T + params["fc"]["bias"]

        if weighted:
            nll = cross_entropy_loss(logits, y_local, reduction="none")
            num = lax.psum(jnp.sum(nll * w[0]), data)
            den = lax.psum(jnp.sum(w[0]), data)
            loss = num / jnp.maximum(den, 1.0)
            correct = jnp.sum(
                (jnp.argmax(logits, axis=1) == y_local) * (w[0] > 0)
            )
        else:
            loss = lax.pmean(cross_entropy_loss(logits, y_local), data)
            correct = jnp.sum(jnp.argmax(logits, axis=1) == y_local)
        return (
            loss + model.aux_weight * aux,
            {"correct": lax.psum(correct, data)},
        )

    return loss_fn


def make_mesh_grad_step(loss_fn, optimizer):
    """``step(params, opt_state, batch, *extra) -> (params, opt_state,
    loss, metrics)`` with grad outside the shard_mapped ``loss_fn``;
    ``*extra`` (weight column and/or dropout key) is forwarded in order."""

    def step(params, opt_state, batch, *extra):
        x, y = batch
        (loss, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params, x, y, *extra)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, metrics

    return step
