"""Tensor parallelism: hidden-dimension sharding for RNNs and linears.

The reference has no tensor parallelism (SURVEY.md checklist: "no sharded
matmul anywhere in src/") - every rank holds a full model replica.  This
module adds it as a first-class axis so models whose hidden state exceeds
one chip's HBM (or whose matmuls want more MXUs) shard across a ``tp`` mesh
axis; it composes orthogonally with the ``dp`` and ``sp`` axes.

Sharding scheme for an LSTM layer (Megatron-style, adapted to recurrence):

- Every gate's H dimension is sharded: shard ``k`` owns rows
  ``[k*H/n, (k+1)*H/n)`` of each of the four gates of ``w_ih``, ``w_hh``
  and both biases, so its input/recurrent matmuls produce only its
  ``(B, 4H/n)`` gate slice and its ``(B, H/n)`` piece of ``h``/``c``.
- The recurrent matmul needs the *full* previous ``h``, so each scan step
  all-gathers the (B, H/n) hidden shards - the one collective per step,
  (B, H) bytes over ICI, overlapping with the gate math.
- The layer's output is all-gathered once per layer to feed the next
  layer's (full-width) input projection.
- The classifier head runs row-parallel: each shard multiplies its hidden
  slice against its slice of the head weight, one ``psum`` combines the
  partial logits (bias added after the sum).

Params stay replicated in HBM and each shard *slices* its piece inside the
SPMD program; XLA keeps the slice fused into the consuming matmul, and the
single replicated copy is the same memory the DP strategies already pay.
When the PARAMETER footprint itself is the constraint, use
``parallel/zero.py``: from-construction sharded params + optimizer state
(ZeRO/FSDP layout), which composes with this module's compute sharding.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from pytorch_distributed_rnn_tpu.utils.compat import shard_map

from pytorch_distributed_rnn_tpu.ops.rnn import (
    gru_input_proj,
    lstm_input_proj,
)


def shard_gates(w, n: int, k, num_gates: int = 4):
    """Slice shard ``k``'s rows of every gate from a (num_gates*H, ...)
    tensor: reshape to (num_gates, H, ...), take H/n rows per gate, flatten
    back to (num_gates*H/n, ...).  ``k`` may be traced (axis_index)."""
    gh = w.shape[0]
    h = gh // num_gates
    if h % n != 0:
        raise ValueError(f"hidden size {h} not divisible by tp size {n}")
    per = h // n
    gates = w.reshape(num_gates, h, *w.shape[1:])
    sliced = lax.dynamic_slice_in_dim(gates, k * per, per, axis=1)
    return sliced.reshape(num_gates * per, *w.shape[1:])


def _cast_local(local, x, compute_dtype):
    """Move the sliced weights + input to ``compute_dtype`` (bf16 matmuls
    at full MXU rate, half the collective bytes); None = stay as-is."""
    if compute_dtype is None:
        return local, x
    # contract: params stay f32, so these downcasts transpose to f32
    # cotangent accumulation in backward - intentional
    local = {k: v.astype(compute_dtype)  # noqa: PD203
             for k, v in local.items()}
    return local, x.astype(compute_dtype)  # noqa: PD203 (same contract)


def sharded_gate_params(params, n, k, x, *, num_gates: int = 4,
                        compute_dtype=None):
    """The gate-sharded prologue shared by the tp layers and the composed
    sp x tp layers (``parallel/combined.py``): slice shard ``k``'s rows of
    every gate tensor, then cast slices + input to the compute dtype."""
    local = {
        name: shard_gates(params[name], n, k, num_gates=num_gates)
        for name in ("w_ih", "w_hh", "b_ih", "b_hh")
    }
    return _cast_local(local, x, compute_dtype)


def tp_lstm_step(w_hh_l_t, axis: str, carry, xp_t):
    """One gate-sharded LSTM step: the tp sibling of
    :func:`~pytorch_distributed_rnn_tpu.ops.rnn.lstm_step`, shared by
    ``tp_lstm_layer`` and the composed sp x tp relay.  ``carry``: f32
    (B, H/n) slices; ``xp_t``: (B, 4H/n) pre-activation.  The one
    per-step collective all-gathers ``h`` in the compute dtype (half the
    ICI bytes under bf16); gate math runs f32 per the lstm_step
    mixed-precision contract."""
    h_local, c_local = carry
    # contract: carry is f32, the gather wire dtype is the compute
    # dtype; the downcast transposes to f32 accumulation in backward
    h_full = lax.all_gather(h_local.astype(xp_t.dtype), axis,  # noqa: PD203
                            axis=1, tiled=True)
    # contract: gate nonlinearities accumulate in f32 (the lstm_step
    # mixed-precision contract) - this upcast is the accumulation
    gates = (xp_t + h_full @ w_hh_l_t).astype(jnp.float32)  # noqa: PD203
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    c_local = jax.nn.sigmoid(f) * c_local + (
        jax.nn.sigmoid(i) * jnp.tanh(g)
    )
    h_local = jax.nn.sigmoid(o) * jnp.tanh(c_local)
    return (h_local, c_local), h_local.astype(xp_t.dtype)  # noqa: PD203


def tp_gru_step(w_hh_l_t, b_hh_l, axis: str, h_local, xp_t):
    """One gate-sharded GRU step (torch semantics: the hidden-side n-bias
    joins inside the ``r *`` product, sliced like the weights); the tp
    sibling of :func:`~pytorch_distributed_rnn_tpu.ops.rnn.gru_step`."""
    h_full = lax.all_gather(h_local.astype(xp_t.dtype), axis,
                            axis=1, tiled=True)
    h_proj = (h_full @ w_hh_l_t + b_hh_l).astype(jnp.float32)
    xr, xz, xn = jnp.split(xp_t.astype(jnp.float32), 3, axis=-1)
    hr, hz, hn = jnp.split(h_proj, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    new = jnp.tanh(xn + r * hn)
    h_local = (1.0 - z) * new + z * h_local
    return h_local, h_local.astype(xp_t.dtype)


def tp_lstm_layer(params, x, axis: str, *, unroll: int = 1,
                  compute_dtype=None):
    """One LSTM layer with the hidden dimension sharded over ``axis``, for
    use inside ``shard_map`` (params replicated, ``x`` (B, T, in) full).

    Returns ``(outputs (B, T, H) full-width, (h_T, c_T) full-width)`` -
    outputs are all-gathered so stacking composes; the per-step state stays
    sharded inside the scan.  Mixed-precision contract as
    :func:`~pytorch_distributed_rnn_tpu.ops.rnn.lstm_step`: the sharded
    carry stays f32, matmuls (and the per-step all-gather's wire bytes)
    run in ``compute_dtype``, emitted outputs follow it.
    """
    n = lax.axis_size(axis)
    k = lax.axis_index(axis)
    hidden = params["w_hh"].shape[1]
    per = hidden // n
    batch = x.shape[0]

    local, x = sharded_gate_params(params, n, k, x,
                                   compute_dtype=compute_dtype)
    x_proj = lstm_input_proj(local, x)               # (B, T, 4H/n)
    w_hh_l_t = local["w_hh"].T                       # (H, 4H/n)

    h0 = jnp.zeros((batch, per), jnp.float32)
    c0 = jnp.zeros((batch, per), jnp.float32)
    (h_t, c_t), out_local = lax.scan(
        lambda c, xp: tp_lstm_step(w_hh_l_t, axis, c, xp),
        (h0, c0), jnp.swapaxes(x_proj, 0, 1), unroll=unroll
    )
    out_local = jnp.swapaxes(out_local, 0, 1)        # (B, T, H/n)
    outputs = lax.all_gather(out_local, axis, axis=2, tiled=True)
    h_t = lax.all_gather(h_t, axis, axis=1, tiled=True)
    c_t = lax.all_gather(c_t, axis, axis=1, tiled=True)
    return outputs, (h_t, c_t)


def tp_stacked_lstm(layers, x, axis: str, *, unroll: int = 1,
                    compute_dtype=None, remat: bool = False):
    """Stack of :func:`tp_lstm_layer`; returns (outputs, [finals]).
    ``remat`` checkpoints each layer (recompute activations - including
    the per-step all-gathers - during backward)."""
    layer_fn = partial(tp_lstm_layer, axis=axis, unroll=unroll,
                       compute_dtype=compute_dtype)
    if remat:
        layer_fn = jax.checkpoint(layer_fn)
    finals = []
    out = x
    for layer in layers:
        out, final = layer_fn(layer, out)
        finals.append(final)
    return out, finals


def tp_gru_layer(params, x, axis: str, *, unroll: int = 1,
                 compute_dtype=None):
    """One GRU layer with the hidden dimension sharded over ``axis``.

    Same layout as :func:`tp_lstm_layer` with 3 gates (r, z, n): each
    shard owns H/n rows of every gate, computes its gate slice from the
    all-gathered full ``h`` (the one per-step collective), and emits its
    H/n slice of the new state.  torch semantics preserved: the
    hidden-side n-bias joins inside the ``r *`` product, sliced like the
    weights.  Mixed-precision contract as
    :func:`~pytorch_distributed_rnn_tpu.ops.rnn.gru_step`: f32 carry,
    compute-dtype matmuls and collective bytes.
    """
    n = lax.axis_size(axis)
    k = lax.axis_index(axis)
    hidden = params["w_hh"].shape[1]
    per = hidden // n
    batch = x.shape[0]

    local, x = sharded_gate_params(params, n, k, x, num_gates=3,
                                   compute_dtype=compute_dtype)
    x_proj = gru_input_proj(local, x)                # (B, T, 3H/n)
    w_hh_l_t = local["w_hh"].T                       # (H, 3H/n)
    b_hh_l = local["b_hh"]

    h0 = jnp.zeros((batch, per), jnp.float32)
    h_t, out_local = lax.scan(
        lambda h, xp: tp_gru_step(w_hh_l_t, b_hh_l, axis, h, xp),
        h0, jnp.swapaxes(x_proj, 0, 1), unroll=unroll
    )
    out_local = jnp.swapaxes(out_local, 0, 1)        # (B, T, H/n)
    outputs = lax.all_gather(out_local, axis, axis=2, tiled=True)
    h_t = lax.all_gather(h_t, axis, axis=1, tiled=True)
    return outputs, h_t


def tp_stacked_gru(layers, x, axis: str, *, unroll: int = 1,
                   compute_dtype=None, remat: bool = False):
    """Stack of :func:`tp_gru_layer`; returns (outputs, [finals])."""
    layer_fn = partial(tp_gru_layer, axis=axis, unroll=unroll,
                       compute_dtype=compute_dtype)
    if remat:
        layer_fn = jax.checkpoint(layer_fn)
    finals = []
    out = x
    for layer in layers:
        out, final = layer_fn(layer, out)
        finals.append(final)
    return out, finals


def row_parallel_head(params, h_full, axis: str):
    """Row-parallel linear: each shard multiplies its slice of the input
    dimension, one psum combines partial outputs, bias added after.

    ``params``: {"weight" (out, H), "bias" (out,)} replicated;
    ``h_full``: (B, H).
    """
    n = lax.axis_size(axis)
    k = lax.axis_index(axis)
    hidden = params["weight"].shape[1]
    if hidden % n != 0:
        raise ValueError(f"hidden size {hidden} not divisible by tp size {n}")
    per = hidden // n
    w_local = lax.dynamic_slice_in_dim(params["weight"], k * per, per, axis=1)
    h_local = lax.dynamic_slice_in_dim(h_full, k * per, per, axis=1)
    partial_out = h_local @ w_local.T
    return lax.psum(partial_out, axis) + params["bias"]


def make_tp_forward(mesh, axis: str = "tp", *, unroll: int = 1):
    """Jitted tensor-parallel forward for a MotionModel-shaped params tree:
    gate-sharded stacked LSTM + row-parallel head.  ``x`` replicated in,
    logits replicated out; numerics match ``MotionModel.apply`` exactly.
    """

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P()),
        out_specs=P(),
        check_vma=False,
    )
    def forward(params, x):
        out, _ = tp_stacked_lstm(params["rnn"], x, axis, unroll=unroll)
        return row_parallel_head(params["fc"], out[:, -1, :], axis)

    return jax.jit(forward)


# ---------------------------------------------------------------------------
# pdrnn-lint --deep trace registry (lint/trace_registry.py)


def declare_trace_entries(register):
    """Register the tensor-parallel char-LM step (bf16 compute: the tp
    family is where the dtype-flow rule PD203 earns its keep - params f32,
    gate matmuls bf16, head accumulation f32)."""

    def build():
        import optax

        from pytorch_distributed_rnn_tpu.lint.trace_registry import (
            abstract_init,
            lint_mesh,
            prng_spec,
            sds,
        )
        from pytorch_distributed_rnn_tpu.models import CharRNN
        from pytorch_distributed_rnn_tpu.parallel.strategy import (
            make_char_mesh_loss_fn,
            make_mesh_grad_step,
        )

        axes = {"dp": 2, "tp": 2}
        mesh = lint_mesh(axes)
        model = CharRNN(vocab_size=16, embed_dim=8, hidden_dim=8,
                        layer_dim=1, impl="scan")
        params = abstract_init(model.init, prng_spec())
        optimizer = optax.adam(1e-3)
        opt_state = abstract_init(optimizer.init, params)
        loss_fn = make_char_mesh_loss_fn(mesh, axes, precision="bf16")
        step = make_mesh_grad_step(loss_fn, optimizer)
        batch = (sds((4, 16), jnp.int32), sds((4,), jnp.int32))
        jitted = jax.jit(step, donate_argnums=(0, 1))
        return jitted, (params, opt_state, batch)

    register(
        name="tp.char_mesh_step", family="tp",
        path="pytorch_distributed_rnn_tpu/parallel/tp.py",
        build=build, mesh_axes={"dp": 2, "tp": 2}, data_axis="dp",
        donate=(0, 1),
    )
