"""ZeRO/FSDP-style parameter + optimizer-state sharding.

Fills the one empty row of SURVEY's parallelism checklist (the reference
keeps a full replica per rank, ``/root/reference/src/motion/trainer/
ddp.py:19``; ZeRO/FSDP absent).  TPU-native design: there is no wrapper
class and no hand-written gather/scatter schedule - parameters and
optimizer state are simply *constructed* with a sharded ``NamedSharding``
layout (each big tensor split along its largest divisible dimension over
the ``dp`` axis), and the train step is jit-compiled with those shardings
pinned on inputs and outputs.  XLA's SPMD partitioner then inserts the
FSDP communication pattern itself: all-gather weights where a matmul needs
them, reduce-scatter the gradients, update each parameter shard locally
(ZeRO-1's "every rank owns 1/n of the optimizer state") - and overlaps the
collectives with compute.  ``jax.checkpoint``/remat compose orthogonally.

Per-chip parameter + optimizer bytes drop to ~1/n of the replicated
layout, which is what makes the 50M-param LM family trainable at depth on
a small slice; tests verify the byte accounting per shard and the exact
numerical equivalence with replicated training.
"""

from __future__ import annotations

import math

import jax
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P


def shard_rule(shape, axis_size: int, axis: str = "dp",
               min_shard_elems: int = 1024):
    """The one shape->PartitionSpec rule used for params AND optimizer
    state (shape-based, so Adam's mu/nu land on their parameter's layout).

    Shards the largest dimension divisible by ``axis_size``; tensors too
    small to matter (or with no divisible dim) stay replicated - biases
    and scalars cost nothing to replicate and sharding them would only
    add collective latency.
    """
    if math.prod(shape) < min_shard_elems * axis_size:
        return P()
    dims = sorted(
        range(len(shape)), key=lambda d: shape[d], reverse=True
    )
    for d in dims:
        if shape[d] % axis_size == 0:
            spec = [None] * len(shape)
            spec[d] = axis
            return P(*spec)
    return P()


def sharded_specs(tree, mesh, axis: str = "dp",
                  min_shard_elems: int = 1024):
    """NamedShardings for every leaf of ``tree`` (arrays or ShapeDtype
    structs) under :func:`shard_rule`."""
    n = mesh.shape[axis]
    return jax.tree.map(
        lambda leaf: NamedSharding(
            mesh, shard_rule(leaf.shape, n, axis, min_shard_elems)
        ),
        tree,
    )


def init_sharded(model, key, mesh, axis: str = "dp"):
    """Construct model parameters DIRECTLY into the sharded layout: the
    initializer is jit-compiled with ``out_shardings``, so no host ever
    materializes (or transfers) a full replica - the point of
    from-construction sharding for models near the HBM limit."""
    shapes = jax.eval_shape(model.init, key)
    shardings = sharded_specs(shapes, mesh, axis)
    return jax.jit(model.init, out_shardings=shardings)(key), shardings


def init_sharded_opt_state(optimizer, params, mesh, axis: str = "dp"):
    """Optimizer state in the sharded layout (ZeRO-1: each rank owns 1/n
    of mu/nu; the shape-based rule makes them follow their parameter)."""
    shapes = jax.eval_shape(optimizer.init, params)
    shardings = sharded_specs(shapes, mesh, axis)
    return jax.jit(optimizer.init, out_shardings=shardings)(params), shardings


def make_fsdp_train_step(loss_fn, optimizer, mesh, param_shardings,
                         opt_shardings, axis: str = "dp",
                         donate: bool = True):
    """Jitted FSDP training step.

    ``loss_fn(params, batch) -> loss`` is the plain single-device loss on
    the GLOBAL batch; ``batch`` arrives sharded on ``axis``.  Sharding
    annotations alone produce the FSDP schedule: XLA all-gathers each
    weight where consumed, reduce-scatters its gradient, and updates the
    local optimizer-state shard.  Output shardings are pinned so updated
    params/opt state stay in the sharded layout step over step.
    """
    batch_sharding = NamedSharding(mesh, P(axis))
    rep = NamedSharding(mesh, P())

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(
        step,
        in_shardings=(param_shardings, opt_shardings, batch_sharding),
        out_shardings=(param_shardings, opt_shardings, rep),
        donate_argnums=(0, 1) if donate else (),
    )


def per_device_bytes(tree) -> int:
    """Max bytes any single device holds for ``tree`` (the per-chip
    memory the sharding actually buys; replicated leaves count fully)."""
    totals: dict = {}
    for leaf in jax.tree.leaves(tree):
        if not hasattr(leaf, "addressable_shards"):
            continue
        seen = set()
        for shard in leaf.addressable_shards:
            d = shard.device
            if d in seen:
                continue
            seen.add(d)
            totals[d] = totals.get(d, 0) + int(np.prod(shard.data.shape)) * leaf.dtype.itemsize
    return max(totals.values()) if totals else 0
