"""Device-mesh construction and common shardings.

The reference's notion of "rank/world" comes from MPI process launch
(``mpirun -np N``, ``/root/reference/fabfile.py:218-223``).  The TPU-native
analogue is a ``jax.sharding.Mesh`` over the chips visible to this
controller: one "rank" = one mesh position along the data-parallel axis, and
rendezvous/collectives ride ICI/DCN through XLA instead of MPI over
Ethernet.
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axes: dict[str, int] | None = None, devices=None) -> Mesh:
    """Build a mesh.  ``axes`` maps axis names to sizes, e.g.
    ``{"dp": 4, "tp": 2}``; a size of -1 means "all remaining devices".
    Default: one ``dp`` axis over every visible device.
    """
    if devices is None:
        devices = jax.devices()
    if axes is None:
        axes = {"dp": len(devices)}

    sizes = list(axes.values())
    n_known = math.prod(s for s in sizes if s != -1)
    if any(s == -1 for s in sizes):
        if sum(s == -1 for s in sizes) > 1:
            raise ValueError("at most one axis may have size -1")
        remainder = len(devices) // n_known
        sizes = [remainder if s == -1 else s for s in sizes]
    total = math.prod(sizes)
    if total > len(devices):
        raise ValueError(
            f"mesh {dict(zip(axes, sizes))} needs {total} devices, "
            f"have {len(devices)}"
        )
    mesh_devices = np.array(devices[:total]).reshape(sizes)
    return Mesh(mesh_devices, tuple(axes.keys()))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Shard the leading (batch) dimension along ``axis``."""
    return NamedSharding(mesh, P(axis))
