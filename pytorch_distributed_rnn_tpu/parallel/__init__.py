from pytorch_distributed_rnn_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    replicated_sharding,
)
from pytorch_distributed_rnn_tpu.parallel.collectives import (
    allgather_tree,
    broadcast_from,
    pmean_tree,
    psum_tree,
)
from pytorch_distributed_rnn_tpu.parallel.dp import (
    broadcast_params,
    distributed_optimizer,
    make_spmd_train_step,
)
from pytorch_distributed_rnn_tpu.parallel.p2p import ring_relay_from_root
from pytorch_distributed_rnn_tpu.parallel.sp import (
    make_sp_attention_forward,
    make_sp_forward,
    sp_gru_layer,
    sp_lstm_layer,
    sp_stacked_gru,
    sp_stacked_lstm,
    sp_stacked_lstm_wavefront,
)
from pytorch_distributed_rnn_tpu.parallel.tp import (
    make_tp_forward,
    tp_gru_layer,
    tp_lstm_layer,
    tp_stacked_gru,
    tp_stacked_lstm,
)
from pytorch_distributed_rnn_tpu.parallel.pp import (
    make_pp_forward,
    pp_stacked_lstm,
    pp_stacked_rnn,
)
from pytorch_distributed_rnn_tpu.parallel.ep import (
    ep_moe_ffn,
    make_ep_moe_forward,
    make_ep_train_step,
)
from pytorch_distributed_rnn_tpu.parallel.multihost import (
    global_device_mesh,
    initialize_multihost,
    process_info,
)
from pytorch_distributed_rnn_tpu.parallel.strategy import (
    make_char_mesh_train_step,
    make_motion_mesh_loss_fn,
    parse_mesh_spec,
    validate_rnn_mesh,
)
from pytorch_distributed_rnn_tpu.parallel.zero import (
    init_sharded,
    init_sharded_opt_state,
    make_fsdp_train_step,
    per_device_bytes,
    sharded_specs,
)

__all__ = [
    "make_mesh",
    "make_char_mesh_train_step",
    "make_motion_mesh_loss_fn",
    "parse_mesh_spec",
    "validate_rnn_mesh",
    "init_sharded",
    "init_sharded_opt_state",
    "make_fsdp_train_step",
    "per_device_bytes",
    "sharded_specs",
    "batch_sharding",
    "replicated_sharding",
    "allgather_tree",
    "broadcast_from",
    "pmean_tree",
    "psum_tree",
    "make_spmd_train_step",
    "broadcast_params",
    "distributed_optimizer",
    "ring_relay_from_root",
    "make_sp_forward",
    "make_sp_attention_forward",
    "sp_gru_layer",
    "sp_lstm_layer",
    "sp_stacked_gru",
    "sp_stacked_lstm",
    "sp_stacked_lstm_wavefront",
    "make_tp_forward",
    "tp_gru_layer",
    "tp_lstm_layer",
    "tp_stacked_gru",
    "tp_stacked_lstm",
    "make_pp_forward",
    "pp_stacked_lstm",
    "pp_stacked_rnn",
    "ep_moe_ffn",
    "make_ep_moe_forward",
    "make_ep_train_step",
    "initialize_multihost",
    "global_device_mesh",
    "process_info",
]
