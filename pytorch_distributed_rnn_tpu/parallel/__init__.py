from pytorch_distributed_rnn_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    replicated_sharding,
)
from pytorch_distributed_rnn_tpu.parallel.collectives import (
    allgather_tree,
    broadcast_from,
    pmean_tree,
    psum_tree,
)
from pytorch_distributed_rnn_tpu.parallel.dp import (
    broadcast_params,
    distributed_optimizer,
    make_spmd_train_step,
)
from pytorch_distributed_rnn_tpu.parallel.p2p import ring_relay_from_root

__all__ = [
    "make_mesh",
    "batch_sharding",
    "replicated_sharding",
    "allgather_tree",
    "broadcast_from",
    "pmean_tree",
    "psum_tree",
    "make_spmd_train_step",
    "broadcast_params",
    "distributed_optimizer",
    "ring_relay_from_root",
]
