"""Bucket plan for overlapped gradient communication on the native ring.

The sharded weight update (``parallel/sharded_update.py``) moves one
monolithic reduce-scatter (gradients) and one monolithic allgather
(fresh params) per step; the host sits blocked for the full wire time
of each.  Bucketing splits that traffic into ``--bucket-mb``-bounded
pieces so bucket k's optimizer apply can run while bucket k+1's
reduce-scatter is still on the wire (the DDP ``bucket_cap_mb`` reducer
idea, SURVEY.md `trainer/ddp.py:19`, on top of 2004.13336's sharding).

Layout - the part that makes bucketing BITWISE-identical to the
monolithic path: buckets partition each rank's monolithic shard range
``[0, shard)`` into contiguous sub-ranges ``[lo, hi)``, NOT the flat
padded vector.  Bucket b's wire vector is the concatenation over ranks
of ``padded[r*shard+lo : r*shard+hi]``, so ring chunk r of the bucket
is exactly rank r's sub-slice.  The ring's per-chunk accumulation
sequence starts at the chunk's own index, which therefore matches the
monolithic reduce-scatter chunk-for-chunk: every element is summed in
the identical rank order and association, and each bucket's output is
the bitwise-equal sub-slice of the monolithic ``g_shard``.  (A naive
contiguous split of the padded vector would reassign elements to
different chunk indices and change the f32 summation order.)

This module is pure stdlib on purpose: ``lint/collective_check.py``
recomputes the plan to enforce the per-bucket-bytes-sum-to-monolithic
invariant without importing jax.
"""

from __future__ import annotations

from dataclasses import dataclass

# DDP's bucket_cap_mb default: the reference reducer packs gradients
# into 25 MB buckets before allreducing them during backward
DEFAULT_BUCKET_MB = 25.0


@dataclass(frozen=True)
class BucketPlan:
    """Immutable bucket layout for one (size, world, wire-itemsize,
    bucket_mb) binding.

    ``bounds`` are ``[lo, hi)`` sub-ranges of the PER-RANK shard range
    ``[0, shard)``; every bucket's wire vector holds ``(hi-lo) * world``
    elements, so each bucket's total wire size (not its per-rank slice)
    is what ``bucket_mb`` caps - the same accounting as DDP's
    ``bucket_cap_mb``.
    """

    size: int        # raveled (unpadded) parameter count
    world: int
    itemsize: int    # wire dtype bytes/element
    bucket_mb: float
    shard: int       # per-rank elements, ceil(size / world)
    padded: int      # shard * world
    bounds: tuple[tuple[int, int], ...]

    @property
    def num_buckets(self) -> int:
        return len(self.bounds)

    def bucket_len(self, b: int) -> int:
        lo, hi = self.bounds[b]
        return hi - lo

    def rs_bytes(self, b: int) -> int:
        """Bucket b's reduce-scatter wire-vector bytes."""
        return self.bucket_len(b) * self.world * self.itemsize

    def ag_bytes(self, b: int) -> int:
        """Bucket b's allgather per-rank contribution bytes."""
        return self.bucket_len(b) * self.itemsize

    @property
    def monolithic_rs_bytes(self) -> int:
        """The un-bucketed reduce-scatter's wire-vector bytes; the
        per-bucket ``rs_bytes`` MUST sum to exactly this (the collective
        gate's relational invariant: overlap must not change traffic)."""
        return self.padded * self.itemsize

    @property
    def monolithic_ag_bytes(self) -> int:
        """The un-bucketed allgather's per-rank contribution bytes; the
        per-bucket ``ag_bytes`` MUST sum to exactly this."""
        return self.shard * self.itemsize

    def wire_expectations(self) -> dict:
        """The ``native_wire`` section of
        ``lint/collective_expectations.json``: enough config to replay
        the plan plus the per-bucket and monolithic byte counts the gate
        cross-checks."""
        return {
            "config": {
                "size": self.size,
                "world": self.world,
                "itemsize": self.itemsize,
                "bucket_mb": self.bucket_mb,
            },
            "monolithic": {
                "reduce_scatter_bytes": self.monolithic_rs_bytes,
                "allgather_bytes": self.monolithic_ag_bytes,
            },
            "buckets": [
                {
                    "reduce_scatter_bytes": self.rs_bytes(b),
                    "allgather_bytes": self.ag_bytes(b),
                }
                for b in range(self.num_buckets)
            ],
        }


def plan_buckets(
    size: int,
    world: int,
    itemsize: int,
    bucket_mb: float = DEFAULT_BUCKET_MB,
) -> BucketPlan:
    """Split the per-rank shard range into contiguous buckets whose
    total wire size (``len * world * itemsize``) stays under
    ``bucket_mb`` (at least one element per rank per bucket, so a tiny
    cap degenerates to 1-element buckets, never zero buckets)."""
    if size <= 0:
        raise ValueError(f"plan_buckets needs size > 0, got {size}")
    if world <= 0:
        raise ValueError(f"plan_buckets needs world > 0, got {world}")
    if itemsize <= 0:
        raise ValueError(f"plan_buckets needs itemsize > 0, got {itemsize}")
    if bucket_mb <= 0:
        raise ValueError(
            f"plan_buckets needs bucket_mb > 0, got {bucket_mb} "
            "(use --no-bucketed-comm to disable bucketing)"
        )
    shard = -(-size // world)  # ceil
    padded = shard * world
    cap_bytes = float(bucket_mb) * (1 << 20)
    per_rank_len = max(1, int(cap_bytes // (itemsize * world)))
    bounds = []
    lo = 0
    while lo < shard:
        hi = min(shard, lo + per_rank_len)
        bounds.append((lo, hi))
        lo = hi
    return BucketPlan(
        size=int(size),
        world=int(world),
        itemsize=int(itemsize),
        bucket_mb=float(bucket_mb),
        shard=shard,
        padded=padded,
        bounds=tuple(bounds),
    )
