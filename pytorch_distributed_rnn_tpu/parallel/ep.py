"""Expert parallelism: MoE experts sharded over an ``ep`` mesh axis.

Tokens live batch-sharded along ``ep``; experts live expert-sharded along
the same axis.  Each shard routes its local tokens against the (replicated)
router, packs them into per-expert capacity slots with the one-hot dispatch
einsum (``ops/moe.py``), and two ``lax.all_to_all`` collectives move token
blocks to the shards owning their experts and back - the XLA-native
equivalent of the dispatch/combine exchange in Switch/GShard, riding ICI
instead of host networking.  Per-shard expert compute is
``E/n`` experts x ``n*C`` slots; with ample capacity the result equals the
dense reference exactly (drops otherwise, standard Switch semantics).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from pytorch_distributed_rnn_tpu.utils.compat import shard_map

from pytorch_distributed_rnn_tpu.ops.moe import (
    _expert_ffn,
    _route_expert_choice,
    _route_topk,
    grouped_combine_topk,
    grouped_pack_topk,
    make_dispatch_topk,
    moe_capacity,
)


def ep_moe_ffn(params, x_local, axis: str, *, capacity_factor: float = 2.0,
               num_selected: int = 1, router: str = "token",
               stat_axes=None, group_size: int | None = None):
    """Expert-parallel MoE FFN inside ``shard_map``.

    ``params`` replicated, ``x_local``: this shard's (..., D) tokens
    (batch-sharded along ``axis``).  ``router="token"``:
    ``num_selected=1`` is Switch, ``2`` is GShard (renormalized gates,
    choice-major capacity).  ``router="expert"``: expert-choice - each
    expert picks its top-C tokens among this SHARD's tokens (the
    standard sharded EC practice: selection is shard-local, so each
    expert owner processes exactly n_shards x C slots - perfectly
    balanced by construction), aux is 0.
    ``group_size`` (token-choice only): route this shard's tokens in
    independent groups of that size (GShard grouped routing,
    ``ops/moe.py::moe_ffn``) - per-group capacity keeps the one-hot
    dispatch einsums linear in the shard's token count.  The all_to_all
    slot dim becomes groups x per-group-capacity, which is >= the
    global capacity whenever the per-group ceil rounds up - slightly
    more (padded) wire bytes bought for much cheaper dispatch compute.
    Returns ``(out_local, aux_loss)`` with ``aux_loss`` the Switch
    load-balancing loss averaged over ``stat_axes`` (default: the expert
    axis only).  When tokens also shard over other mesh axes (the
    dp x ep training layout), pass them all so the aux fractions are
    means over the GLOBAL batch - averaging per-shard aux products
    instead would bias the estimator.
    """
    n = lax.axis_size(axis)
    k = lax.axis_index(axis)
    shape = x_local.shape
    d = shape[-1]
    xt = x_local.reshape(-1, d)
    n_tok = xt.shape[0]
    e = params["w1"].shape[0]
    if e % n != 0:
        raise ValueError(f"{e} experts do not shard over {n} devices")
    e_local = e // n

    # group_size=None or >= n_tok -> one global group; anything else
    # (including invalid <= 0) flows into grouped_pack_topk, whose
    # shared validation keeps this path's errors identical to moe_ffn's
    grouped = bool(router != "expert" and group_size is not None
                   and group_size < n_tok)
    if router == "expert":
        if num_selected != 1:
            # same loud reject as the model surface: --moe-top-k is a
            # token-choice knob; silently ignoring it here would let a
            # caller believe they got top-2 semantics
            raise ValueError(
                "num_selected is a token-choice knob; expert-choice "
                "routing picks per-expert capacities instead"
            )
        if group_size is not None:
            # `is not None`, not truthiness: group_size=0 is invalid
            # everywhere and must be rejected here as loudly as the
            # token-choice path rejects it, not silently accepted
            raise ValueError(
                "group_size is a token-choice knob; expert-choice "
                "selection is already per-shard"
            )
        sel, combine_ecn = _route_expert_choice(
            params, xt, moe_capacity(n_tok, e, capacity_factor))
        dispatch = sel.transpose(2, 0, 1)  # (N, E, C)
        combine = combine_ecn.transpose(2, 0, 1)
    else:
        experts_k, probs_k, gates = _route_topk(params, xt, num_selected)
        expert = experts_k[:, 0]  # first choice drives the aux loss
        if grouped:
            tokens, comb_g, g, capacity = grouped_pack_topk(
                xt, experts_k, probs_k, e, group_size, capacity_factor,
                num_selected)
        else:
            capacity = moe_capacity(n_tok, e, capacity_factor,
                                    num_selected)
            dispatch, combine = make_dispatch_topk(experts_k, probs_k, e,
                                                   capacity, xt.dtype)

    # pack local tokens into (E, C, D) slots, send each expert block to its
    # owner: (E, C, D) -> (E/n, n*C, D) with slots ordered by source shard.
    # Grouped routing already packed (E, G*C_g, D) - same exchange shape
    # class, smaller one-hots.
    if not grouped:
        tokens = jnp.einsum("nec,nd->ecd", dispatch, xt)
    tokens = lax.all_to_all(tokens, axis, split_axis=0, concat_axis=1,
                            tiled=True)

    local_params = {
        name: lax.dynamic_slice_in_dim(params[name], k * e_local, e_local)
        for name in ("w1", "b1", "w2", "b2")
    }
    out_tokens = _expert_ffn(local_params, tokens)

    # return processed slots to their source shards and combine
    out_tokens = lax.all_to_all(out_tokens, axis, split_axis=1,
                                concat_axis=0, tiled=True)
    if grouped:
        out = grouped_combine_topk(out_tokens, comb_g, g, capacity)
    else:
        out = jnp.einsum("nec,ecd->nd", combine, out_tokens)

    if router == "expert":
        # perfectly balanced by construction - no load-balancing loss
        return out.reshape(shape), jnp.float32(0.0)
    # the Switch aux loss is a product of two *global* means - average the
    # per-shard means first (pmean of each factor), then combine; averaging
    # per-shard losses would bias the product
    one_hot = jax.nn.one_hot(expert, e, dtype=gates.dtype)
    stat_axes = (axis,) if stat_axes is None else stat_axes
    frac_tokens = lax.pmean(jnp.mean(one_hot, axis=0), stat_axes)
    frac_prob = lax.pmean(jnp.mean(gates, axis=0), stat_axes)
    aux = e * jnp.sum(frac_tokens * frac_prob)
    return out.reshape(shape), aux


def make_ep_train_step(optimizer, mesh, axis: str = "ep", *,
                       capacity_factor: float = 2.0,
                       num_selected: int = 1, router: str = "token",
                       aux_weight: float = 0.01, donate: bool = True,
                       group_size: int | None = None):
    """Jitted expert-parallel MoE *training* step (regression shape):
    ``step(params, opt_state, x, y)`` with ``x``/``y`` (N, D) sharded
    along ``axis``; loss = global MSE + aux_weight * Switch aux loss.

    Grad is taken OUTSIDE the shard_mapped loss (the combined.py
    pattern), so the two ``all_to_all``s transpose into the reverse
    dispatch/combine exchanges and replicated-parameter cotangents
    re-reduce correctly - EP is a trainable strategy, not just a forward
    factory.
    """
    import optax

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis)),
        out_specs=P(),
        check_vma=False,
    )
    def loss_fn(params, x_local, y_local):
        out, aux = ep_moe_ffn(params, x_local, axis,
                              capacity_factor=capacity_factor,
                              num_selected=num_selected, router=router,
                              group_size=group_size)
        local = jnp.mean((out - y_local) ** 2)
        return lax.pmean(local, axis) + aux_weight * aux

    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


def make_ep_moe_forward(mesh, axis: str = "ep", *,
                        capacity_factor: float = 2.0,
                        num_selected: int = 1, router: str = "token",
                        group_size: int | None = None):
    """Jitted expert-parallel MoE FFN: tokens (N, D) sharded along ``axis``
    on entry, outputs sharded the same way; aux loss replicated."""

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(axis)),
        out_specs=(P(axis), P()),
        check_vma=False,
    )
    def forward(params, x_local):
        return ep_moe_ffn(params, x_local, axis,
                          capacity_factor=capacity_factor,
                          num_selected=num_selected, router=router,
                          group_size=group_size)

    return jax.jit(forward)


# ---------------------------------------------------------------------------
# pdrnn-lint --deep trace registry (lint/trace_registry.py)


def declare_trace_entries(register):
    """Register the expert-parallel regression step (all_to_all
    dispatch/combine; grads over the ep axis)."""

    def build():
        import optax

        from pytorch_distributed_rnn_tpu.lint.trace_registry import (
            abstract_init,
            lint_mesh,
            prng_spec,
            sds,
        )
        from pytorch_distributed_rnn_tpu.ops.moe import init_moe_ffn

        mesh = lint_mesh({"ep": 2})
        params = abstract_init(
            lambda key: init_moe_ffn(key, 8, 2, 16), prng_spec()
        )
        optimizer = optax.adam(1e-3)
        opt_state = abstract_init(optimizer.init, params)
        step = make_ep_train_step(optimizer, mesh)
        x = sds((4, 8), jnp.float32)
        y = sds((4, 8), jnp.float32)
        return step, (params, opt_state, x, y)

    register(
        name="ep.moe_train_step", family="ep",
        path="pytorch_distributed_rnn_tpu/parallel/ep.py",
        build=build, mesh_axes={"ep": 2}, data_axis="ep",
        donate=(0, 1),
    )
