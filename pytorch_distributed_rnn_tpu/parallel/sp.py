"""Sequence/context parallelism for recurrent models.

The reference has no long-sequence story at all: sequence length is a fixed
property of the data (128 HAR timesteps consumed on one device,
``/root/reference/src/motion/model.py:13-16``, ``processor.py:93``).  This
module is the TPU-native capability that lifts that limit: the time axis is
sharded over an ``sp`` mesh axis, so a sequence S times longer fits in the
same per-chip HBM and the parallelizable work scales out.

An LSTM/GRU splits cleanly into two cost classes:

- **Input projections** ``(B*T, in) x (in, 4H)`` - the large MXU matmuls
  where the FLOPs are.  These have no time dependency and run fully parallel
  on the sharded time chunks.
- **Gate recurrence** - inherently serial in T.  It runs as a *chunk relay*:
  every turn, all shards scan their local chunk; the (h, c) carry then hops
  to the next shard via ``lax.ppermute`` (XLA CollectivePermute over ICI).
  Shard ``s``'s scan consumes the correct incoming carry exactly at turn
  ``s`` (induction: shard 0 starts from the true initial carry at turn 0;
  shard ``s`` receives shard ``s-1``'s turn-``s-1`` result), so its outputs
  are captured at that turn.  Serial latency stays O(T) - that is the
  recurrence's true dependency depth - but per-chip memory and all
  projection FLOPs scale 1/S.

For stacked RNNs the relay admits a **wavefront schedule**: cell
``(layer l, chunk s)`` depends on ``(l, s-1)`` (carry) and ``(l-1, s)``
(activations, already resident on shard ``s``).  Scheduling ``l = w - s`` at
wavefront ``w`` overlaps layers across shards, finishing in ``L + S - 1``
turns of ``T/S`` recurrence steps each - latency ``T + (L-1)*T/S`` instead
of the layer-sequential ``L*T``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from pytorch_distributed_rnn_tpu.utils.compat import shard_map

from pytorch_distributed_rnn_tpu.ops.rnn import (
    gru_input_proj,
    gru_step,
    interlayer_dropout,
    lstm_input_proj,
    lstm_step,
)
from pytorch_distributed_rnn_tpu.parallel.collectives import broadcast_from


def _lstm_chunk_scan(w_hh_t, carry, x_proj_chunk, unroll: int = 1):
    """Scan the LSTM gate recurrence (the shared :func:`ops.rnn.lstm_step`)
    over one local time chunk.

    ``x_proj_chunk``: (B, T_local, 4H) pre-activations (input projection plus
    both biases already folded in); ``carry``: ``(h, c)`` each (B, H).
    Returns ``((h, c), outputs (B, T_local, H))``.
    """
    carry, out = lax.scan(
        lambda c, xp_t: lstm_step(w_hh_t, c, xp_t),
        carry,
        jnp.swapaxes(x_proj_chunk, 0, 1),
        unroll=unroll,
    )
    return carry, jnp.swapaxes(out, 0, 1)


def _relay(axis: str, n: int, carry, chunk_fn):
    """Run ``chunk_fn(carry) -> (carry, outputs)`` as an ``n``-turn relay
    over mesh axis ``axis``.

    All shards execute every turn (SPMD); shard ``s``'s outputs are valid at
    turn ``s`` and captured then.  Carries rotate one hop per turn.  Returns
    ``(final_carry, outputs)`` with ``final_carry`` = the last shard's carry,
    replicated to all shards.
    """
    idx = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def select(active, new, old):
        return jax.tree.map(
            lambda a, b: jnp.where(active, a, b), new, old
        )

    def turn(state, t):
        carry, outputs = state
        new_carry, new_out = chunk_fn(carry)
        outputs = select(idx == t, new_out, outputs)
        shifted = jax.tree.map(
            lambda x: lax.ppermute(x, axis, perm), new_carry
        )
        # shard t+1 adopts what arrived; everyone else keeps their state so
        # an already-captured carry isn't clobbered by garbage.
        carry = select(idx == t + 1, shifted, carry)
        return (carry, outputs), new_carry

    out0 = jax.eval_shape(chunk_fn, carry)[1]
    outputs = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), out0)
    (_, outputs), carries = lax.scan(
        turn, (carry, outputs), jnp.arange(n)
    )

    # At turn n-1 the last shard is the active one, so its new_carry is the
    # true final carry; take that turn's slot and broadcast from shard n-1.
    final_carry = jax.tree.map(lambda x: x[-1], carries)
    final_carry = broadcast_from(final_carry, axis, n - 1)
    return final_carry, outputs


def sp_lstm_layer(params, x_local, axis: str, *, unroll: int = 1):
    """One LSTM layer over a time-sharded sequence, inside ``shard_map``.

    ``x_local``: this shard's (B, T/S, in) time chunk.  Returns
    ``(outputs_local (B, T/S, H), (h_T, c_T))`` with the final carry
    replicated across the ``sp`` axis.  Numerics match
    :func:`~pytorch_distributed_rnn_tpu.ops.rnn.lstm_layer` on the gathered
    sequence exactly (same gate order, same fold of both biases into the
    input projection).
    """
    n = lax.axis_size(axis)
    batch = x_local.shape[0]
    hidden = params["w_hh"].shape[1]

    # Fully parallel across time shards: the big MXU matmul.
    x_proj = lstm_input_proj(params, x_local)
    w_hh_t = params["w_hh"].T

    # f32 carry per the lstm_step mixed-precision contract
    h0 = jnp.zeros((batch, hidden), jnp.float32)
    c0 = jnp.zeros((batch, hidden), jnp.float32)

    final, outputs = _relay(
        axis, n, (h0, c0),
        partial(_lstm_chunk_scan, w_hh_t, x_proj_chunk=x_proj, unroll=unroll),
    )
    return outputs, final


def _cast_for_compute(layers, x_local, compute_dtype):
    """Mixed-precision entry shared by the sp stacks: params and the local
    activations move to ``compute_dtype`` (bf16 matmuls at full MXU rate);
    the per-step carry stays f32 inside :func:`ops.rnn.lstm_step` /
    :func:`gru_step` (their documented contract), so sp numerics degrade
    exactly like the unsharded ``stacked_rnn(compute_dtype=...)`` path."""
    if compute_dtype is None:
        return layers, x_local
    layers = [
        jax.tree.map(lambda p: p.astype(compute_dtype), layer)
        for layer in layers
    ]
    return layers, x_local.astype(compute_dtype)


def sp_stacked_lstm(layers, x_local, axis: str, *, unroll: int = 1,
                    compute_dtype=None, remat: bool = False,
                    dropout: float = 0.0, dropout_key=None):
    """Layer-sequential stacked LSTM over a time-sharded sequence.

    Each layer is a full relay; total latency O(L*T).  Prefer
    :func:`sp_stacked_lstm_wavefront` when L > 1 (unless dropout is on -
    the wavefront interleaves layers across shards and threads no
    between-layer masks, so dropout relays layer-sequentially).
    Returns ``(outputs_local, [per-layer final carries])``.

    ``compute_dtype``/``remat`` are the same TPU levers as
    ``ops.rnn.stacked_rnn``: bf16 compute with f32 carries, and
    per-layer ``jax.checkpoint`` (the relay - including its ppermute
    hops - is replayed during backward instead of saving activations).
    ``dropout``/``dropout_key`` follow the ``stacked_rnn`` contract:
    between layers only, skipped when the key is ``None`` (eval mode).
    """
    layer_fn = partial(sp_lstm_layer, axis=axis, unroll=unroll)
    if remat:
        layer_fn = jax.checkpoint(layer_fn)
    layers, out = _cast_for_compute(layers, x_local, compute_dtype)
    finals = []
    for idx, layer in enumerate(layers):
        out, final = layer_fn(layer, out)
        finals.append(final)
        if dropout > 0.0 and dropout_key is not None and idx < len(layers) - 1:
            out, dropout_key = interlayer_dropout(out, dropout_key, dropout)
    return out, finals


def _gru_chunk_scan(w_hh_t, b_hh, carry, x_proj_chunk, unroll: int = 1):
    """Scan the GRU gate recurrence (the shared :func:`ops.rnn.gru_step`)
    over one local time chunk.  ``carry``: h (B, H) f32."""
    carry, out = lax.scan(
        lambda h, xp_t: gru_step(w_hh_t, b_hh, h, xp_t),
        carry,
        jnp.swapaxes(x_proj_chunk, 0, 1),
        unroll=unroll,
    )
    return carry, jnp.swapaxes(out, 0, 1)


def sp_gru_layer(params, x_local, axis: str, *, unroll: int = 1):
    """One GRU layer over a time-sharded sequence, inside ``shard_map``.
    Same relay as :func:`sp_lstm_layer`; the carry is just ``h``."""
    n = lax.axis_size(axis)
    batch = x_local.shape[0]
    hidden = params["w_hh"].shape[1]

    x_proj = gru_input_proj(params, x_local)  # b_ih folded; b_hh in-step
    w_hh_t = params["w_hh"].T
    h0 = jnp.zeros((batch, hidden), jnp.float32)

    final, outputs = _relay(
        axis, n, h0,
        partial(_gru_chunk_scan, w_hh_t, params["b_hh"],
                x_proj_chunk=x_proj, unroll=unroll),
    )
    return outputs, final


def sp_stacked_gru(layers, x_local, axis: str, *, unroll: int = 1,
                   compute_dtype=None, remat: bool = False,
                   dropout: float = 0.0, dropout_key=None):
    """Layer-sequential stacked GRU over a time-sharded sequence.
    ``compute_dtype``/``remat``/``dropout`` as :func:`sp_stacked_lstm`."""
    layer_fn = partial(sp_gru_layer, axis=axis, unroll=unroll)
    if remat:
        layer_fn = jax.checkpoint(layer_fn)
    layers, out = _cast_for_compute(layers, x_local, compute_dtype)
    finals = []
    for idx, layer in enumerate(layers):
        out, final = layer_fn(layer, out)
        finals.append(final)
        if dropout > 0.0 and dropout_key is not None and idx < len(layers) - 1:
            out, dropout_key = interlayer_dropout(out, dropout_key, dropout)
    return out, finals


def sp_stacked_lstm_wavefront(layers, x_local, axis: str, *,
                              unroll: int = 1, compute_dtype=None,
                              remat: bool = False,
                              dropout: float = 0.0, dropout_key=None):
    """Wavefront-scheduled stacked LSTM over a time-sharded sequence.

    Cell ``(l, s)`` = layer ``l``'s recurrence over shard ``s``'s chunk.  At
    wavefront ``w`` shard ``s`` computes ``l = w - s`` (when ``0 <= l < L``):
    the carry for ``(l, s)`` arrived from shard ``s-1`` at wavefront ``w-1``,
    and the layer input - layer ``l-1``'s output on this chunk - was produced
    locally at wavefront ``w-1``.  ``L + S - 1`` wavefronts total, so deep
    stacks overlap across shards instead of serializing (GPipe's schedule,
    transposed onto the time axis).

    Layer 0's input projection (heterogeneous width: ``in`` not ``H``) is
    precomputed for the local chunk - fully parallel, outside the wavefront -
    so layer 0's recurrence joins the same schedule as every deeper layer.
    Returns ``(outputs_local, [per-layer final carries])`` matching
    :func:`sp_stacked_lstm` exactly.
    """
    if len(layers) == 1:
        # single layer: no between-layer seam exists, so dropout is a
        # provable no-op - delegate (with the args threaded, where the
        # idx < L-1 guard makes them inert) rather than reject
        return sp_stacked_lstm(
            layers, x_local, axis, unroll=unroll,
            compute_dtype=compute_dtype, remat=remat,
            dropout=dropout, dropout_key=dropout_key,
        )
    if dropout > 0.0 and dropout_key is not None:
        # the wavefront interleaves all layers in one scan - there is no
        # between-layer seam to mask at; callers route dropout>0 to the
        # sequential relay (strategy._sp_stack / the mesh trainer gate)
        raise ValueError(
            "the wavefront schedule threads no between-layer dropout - "
            "use the sequential sp schedule"
        )

    layers, x_local = _cast_for_compute(layers, x_local, compute_dtype)
    run = partial(_wavefront_run, axis=axis, unroll=unroll)
    if remat:
        # one checkpoint around the whole wavefront: its scan interleaves
        # all layers, so there is no per-layer seam to cut at - backward
        # replays the L + S - 1 turns (ppermutes included) once
        run = jax.checkpoint(run)
    return run(layers, x_local)


def _wavefront_run(layers, x_local, *, axis: str, unroll: int):
    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]

    L = len(layers)
    batch, t_local, _ = x_local.shape
    hidden = layers[0]["w_hh"].shape[1]
    dtype = x_local.dtype

    # Layer 0's pre-activations: parallel across shards, ready before the
    # wavefront starts.
    xp0 = lstm_input_proj(layers[0], x_local)
    # Recurrent weights for ALL layers (homogeneous (H, 4H)); input weights
    # and bias sums for the deep layers only (homogeneous (4H, H) / (4H,)).
    w_hh_t_all = jnp.stack([p["w_hh"].T for p in layers])
    w_ih_deep = jnp.stack([p["w_ih"] for p in layers[1:]])
    b_deep = jnp.stack([p["b_ih"] + p["b_hh"] for p in layers[1:]])

    def select(active, new, old):
        return jax.tree.map(lambda a, b: jnp.where(active, a, b), new, old)

    zero_carry = (  # f32 per the lstm_step mixed-precision contract
        jnp.zeros((batch, hidden), jnp.float32),
        jnp.zeros((batch, hidden), jnp.float32),
    )

    def wavefront(state, w):
        # acts: (B, T/S, H) previous layer's output on this chunk; carry:
        # incoming (h, c); outs: captured last-layer outputs; finals:
        # (L, B, H) x2 captured per-layer final carries.
        acts, carry, outs, finals = state
        l = w - idx
        active = (l >= 0) & (l < L)
        l_safe = jnp.clip(l, 0, L - 1)
        dl = jnp.clip(l - 1, 0, L - 2)
        xp_deep = (
            jnp.einsum(
                "bti,gi->btg",
                acts,
                lax.dynamic_index_in_dim(w_ih_deep, dl, keepdims=False),
            )
            + lax.dynamic_index_in_dim(b_deep, dl, keepdims=False)
        )
        x_proj = jnp.where(l == 0, xp0, xp_deep)
        new_carry, new_out = _lstm_chunk_scan(
            lax.dynamic_index_in_dim(w_hh_t_all, l_safe, keepdims=False),
            carry, x_proj, unroll=unroll,
        )

        # capture final carries: shard n-1 finishing layer l
        is_final = active & (idx == n - 1)
        finals = jax.tree.map(
            lambda buf, new: jnp.where(
                is_final
                & (jnp.arange(L)[:, None, None] == l_safe),
                new[None], buf,
            ),
            finals, new_carry,
        )
        # capture last-layer outputs on every shard
        outs = select(active & (l == L - 1), new_out, outs)
        # next wavefront's input on this shard is this wavefront's output
        acts = select(active, new_out, acts)

        # relay the carry to the next shard; shard 0 always (re)starts the
        # next layer from zeros.
        shifted = jax.tree.map(
            lambda x: lax.ppermute(x, axis, perm), new_carry
        )
        carry = select(idx == 0, zero_carry, shifted)
        return (acts, carry, outs, finals), None

    outs = jnp.zeros((batch, t_local, hidden), dtype)
    acts0 = jnp.zeros((batch, t_local, hidden), dtype)
    finals_buf = (  # carries are f32 (lstm_step contract)
        jnp.zeros((L, batch, hidden), jnp.float32),
        jnp.zeros((L, batch, hidden), jnp.float32),
    )
    (_, _, outs, finals_buf), _ = lax.scan(
        wavefront,
        (acts0, zero_carry, outs, finals_buf),
        jnp.arange(L + n - 1),
    )
    # final carries live on shard n-1 only; replicate.
    finals_buf = broadcast_from(finals_buf, axis, n - 1)
    finals = [(finals_buf[0][l], finals_buf[1][l]) for l in range(L)]
    return outs, finals


def make_sp_forward(mesh, axis: str = "sp", *,
                    schedule: str = "wavefront", unroll: int = 1):
    """Build a jitted sequence-parallel forward for a MotionModel-shaped
    params tree (``{"rnn": [...], "fc": {...}}``): stacked LSTM over a
    time-sharded (B, T, in) input followed by the last-timestep projection.

    The input is sharded ``P(None, axis)`` (time), the logits come back
    replicated - only the shard owning the last chunk computes a non-trivial
    projection; a psum-based broadcast makes the result uniform.
    """
    if schedule not in ("wavefront", "sequential"):
        raise ValueError(f"unknown schedule {schedule!r}")
    n = mesh.shape[axis]
    stack = (
        sp_stacked_lstm_wavefront if schedule == "wavefront"
        else sp_stacked_lstm
    )

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(None, axis)),
        out_specs=P(),
        check_vma=False,
    )
    def forward(params, x_local):
        out_local, _ = stack(params["rnn"], x_local, axis, unroll=unroll)
        last = out_local[:, -1, :]  # true last step only on shard n-1
        logits = last @ params["fc"]["weight"].T + params["fc"]["bias"]
        return broadcast_from(logits, axis, n - 1)

    return jax.jit(forward)


def sp_embed_prologue(params, x_local, axis: str):
    """Shared sequence-parallel prologue for attention models: embed the
    local chunk and add its slice of the positional table, guarding against
    ``dynamic_slice``'s silent clamping when T exceeds ``max_len``."""
    from pytorch_distributed_rnn_tpu.models.attention import _linear

    t_local = x_local.shape[1]
    n = lax.axis_size(axis)
    max_len = params["pos"].shape[0]
    if t_local * n > max_len:
        raise ValueError(
            f"sequence length {t_local * n} exceeds the model's "
            f"max_len {max_len}; dynamic_slice would silently clamp"
        )
    offset = lax.axis_index(axis) * t_local
    pos = lax.dynamic_slice_in_dim(params["pos"], offset, t_local)
    return _linear(params["embed"], x_local) + pos


def sp_mean_pool(h, axis: str):
    """Global mean-pool of a time-sharded (B, T/S, D) activation: local
    mean + pmean over the axis (every chunk has equal length)."""
    return lax.pmean(jnp.mean(h, axis=1), axis)


def make_sp_attention_forward(model, mesh, axis: str = "sp", *,
                              method: str = "ring", causal: bool = False,
                              impl: str | None = None):
    """Build a jitted sequence-parallel forward for an
    :class:`~pytorch_distributed_rnn_tpu.models.AttentionClassifier`.

    The (B, T, in) input is sharded on time; every position-wise piece
    (embed, layernorm, QKV/output projections, MLP, residuals) runs locally
    on the chunk, and the attention core runs as ring attention (K/V blocks
    rotating via ppermute) or Ulysses all-to-all, selected by ``method``.
    ``impl`` (default: the model's ``impl`` field) picks the ring's inner
    step: ``dense`` XLA online-softmax or the fused ``flash`` Pallas
    kernel (``ops/pallas_attention.py``); Ulysses runs its local full
    attention through the same selection.  The global mean-pool is a
    local mean + ``pmean`` over the axis.
    """
    from pytorch_distributed_rnn_tpu.models.attention import (
        _linear, apply_block)
    from pytorch_distributed_rnn_tpu.ops.attention import (
        ring_attention, ulysses_attention)
    from pytorch_distributed_rnn_tpu.ops.pallas_attention import (
        flash_attention, resolve_attention_impl, ring_flash_attention)

    if method not in ("ring", "ulysses"):
        raise ValueError(f"unknown sp attention method {method!r}")
    impl = resolve_attention_impl(impl if impl is not None
                                  else getattr(model, "impl", "auto"))
    if method == "ring":
        attn_fn = (ring_flash_attention if impl == "flash"
                   else ring_attention)
    elif impl == "flash":
        attn_fn = partial(ulysses_attention, attn=flash_attention)
    else:
        attn_fn = ulysses_attention

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(None, axis)),
        out_specs=P(),
        check_vma=False,
    )
    def forward(params, x_local):
        h = sp_embed_prologue(params, x_local, axis)
        for blk in params["blocks"]:
            h = apply_block(
                blk, h, model.num_heads,
                attention=lambda q, k, v: attn_fn(
                    q, k, v, axis, causal=causal),
            )
        return _linear(params["head"], sp_mean_pool(h, axis))

    return jax.jit(forward)


# ---------------------------------------------------------------------------
# pdrnn-lint --deep trace registry (lint/trace_registry.py)


def declare_trace_entries(register):
    """Register the sequence-parallel char-LM step (the relay/wavefront
    family: per-turn ppermute inside lax.scan - the collective pattern
    HLO text parsing undercounts and the jaxpr pass sees exactly)."""

    def build():
        import optax

        from pytorch_distributed_rnn_tpu.lint.trace_registry import (
            abstract_init,
            lint_mesh,
            prng_spec,
            sds,
        )
        from pytorch_distributed_rnn_tpu.models import CharRNN
        from pytorch_distributed_rnn_tpu.parallel.strategy import (
            make_char_mesh_loss_fn,
            make_mesh_grad_step,
        )

        axes = {"dp": 2, "sp": 2}
        mesh = lint_mesh(axes)
        model = CharRNN(vocab_size=16, embed_dim=8, hidden_dim=8,
                        layer_dim=2, impl="scan")
        params = abstract_init(model.init, prng_spec())
        optimizer = optax.adam(1e-3)
        opt_state = abstract_init(optimizer.init, params)
        loss_fn = make_char_mesh_loss_fn(mesh, axes)
        step = make_mesh_grad_step(loss_fn, optimizer)
        batch = (sds((4, 16), jnp.int32), sds((4,), jnp.int32))
        jitted = jax.jit(step, donate_argnums=(0, 1))
        return jitted, (params, opt_state, batch)

    register(
        name="sp.char_mesh_step", family="sp",
        path="pytorch_distributed_rnn_tpu/parallel/sp.py",
        build=build, mesh_axes={"dp": 2, "sp": 2}, data_axis="dp",
        donate=(0, 1),
    )
