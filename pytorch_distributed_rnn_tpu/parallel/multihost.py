"""Multi-host rendezvous: the ``MASTER_ADDR``/``mpirun`` analogue.

The reference reaches multi-node scale three ways, all host-network based:
``mpirun`` launches N python processes that rendezvous through OpenMPI
(``/root/reference/fabfile.py:218-223``), Horovod does the same through
``horovodrun`` (``:225-231``), and the parameter-server strategy sets
``MASTER_ADDR``/``MASTER_PORT`` env vars for torch RPC
(``param_server/__init__.py:41-42``).

The TPU-native equivalent is ``jax.distributed.initialize``: every host
process dials one coordinator, after which ``jax.devices()`` spans ALL
hosts' chips and a single ``Mesh`` built over them makes XLA route
collectives over ICI within a slice and DCN across hosts - no MPI, no
per-rank send/recv code.  This module wraps that rendezvous with the same
env-var ergonomics the reference used, so launchers (ours or bare
``srun``/GKE) configure it the familiar way:

- ``PDRNN_COORDINATOR``: coordinator ``host:port``.
- ``PDRNN_NUM_PROCESSES``: process count.
- ``PDRNN_PROCESS_ID``: this process's id.

The reference-style names (``MASTER_ADDR``/``MASTER_PORT``,
``WORLD_SIZE``/``RANK``) are honored only when ``PDRNN_MULTIHOST=1``
explicitly opts in: those names are ALSO the native TCP runtime's
rendezvous contract (``runtime/native.py``), and a CI harness that injects
``WORLD_SIZE`` alone must not send every CLI invocation dialing a JAX
coordinator.

On TPU pods ``jax.distributed.initialize()`` with no arguments discovers
everything from the TPU metadata service, so all of this is optional there;
the env path exists for CPU/GPU clusters and tests.
"""

from __future__ import annotations

import os

import jax


def rendezvous_spec_from_env():
    """Read the rendezvous triple from the environment.  Returns
    ``(coordinator, num_processes, process_id)`` with ``None`` for anything
    unset.  Reference-style names (``MASTER_ADDR`` etc.) are read only
    under ``PDRNN_MULTIHOST=1`` - they double as the native TCP runtime's
    contract and must not implicitly re-route to a JAX rendezvous."""
    legacy = os.environ.get("PDRNN_MULTIHOST") == "1"

    coordinator = os.environ.get("PDRNN_COORDINATOR")
    if coordinator is None and legacy:
        addr = os.environ.get("MASTER_ADDR")
        port = os.environ.get("MASTER_PORT")
        if addr is not None and port is not None:
            coordinator = f"{addr}:{port}"

    def _int_env(*names):
        for name in names:
            val = os.environ.get(name)
            if val is not None:
                return int(val)
        return None

    num_processes = _int_env(
        "PDRNN_NUM_PROCESSES", *(("WORLD_SIZE",) if legacy else ())
    )
    process_id = _int_env(
        "PDRNN_PROCESS_ID", *(("RANK",) if legacy else ())
    )
    return coordinator, num_processes, process_id


def initialize_multihost(coordinator=None, num_processes=None,
                         process_id=None) -> bool:
    """Join the multi-host world.  Explicit arguments win over env vars;
    with nothing set anywhere this is a no-op (single-controller mode) and
    returns False.  Safe to call twice (the second call is a no-op)."""
    env = rendezvous_spec_from_env()
    coordinator = coordinator if coordinator is not None else env[0]
    num_processes = num_processes if num_processes is not None else env[1]
    process_id = process_id if process_id is not None else env[2]

    if coordinator is None or num_processes is None or process_id is None:
        if (coordinator, num_processes, process_id) != (None, None, None):
            raise ValueError(
                "incomplete multi-host rendezvous spec: need coordinator, "
                f"num_processes AND process_id, got ({coordinator!r}, "
                f"{num_processes!r}, {process_id!r})"
            )
        return False
    if jax.distributed.is_initialized():
        return True  # already initialized
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        if "must be called before" in str(e):
            raise RuntimeError(
                "multi-host rendezvous must happen before the first JAX "
                "computation - call initialize_multihost() at process "
                "start (the launcher does this when PDRNN_COORDINATOR "
                "is set)"
            ) from e
        raise  # real rendezvous failures (unreachable coordinator, ...)
    return True


def process_info() -> tuple[int, int]:
    """(rank, world_size) in reference terms: this process's index and the
    number of processes in the rendezvous."""
    return jax.process_index(), jax.process_count()


def global_device_mesh(axes=None):
    """A mesh over EVERY host's devices (``jax.devices()`` is global after
    :func:`initialize_multihost`).  ``axes`` as in
    :func:`~pytorch_distributed_rnn_tpu.parallel.mesh.make_mesh`; default is
    one ``dp`` axis over all chips with hosts laid out contiguously, so dp
    collectives ride ICI within a host/slice before crossing DCN."""
    from pytorch_distributed_rnn_tpu.parallel.mesh import make_mesh

    return make_mesh(axes, devices=jax.devices())
