"""Composed 3D parallelism: dp x sp x tp in one SPMD training step.

The reference's only parallelism is data-parallel replicas over MPI
(SURVEY.md checklist).  Here the three axes compose in a single
``shard_map`` program over one mesh:

- ``dp``: batch rows sharded; gradients sync via the pmean that
  differentiating the global-mean loss induces (XLA AllReduce over ICI).
- ``sp``: the time axis sharded; attention runs as ring attention
  (``ops/attention.py``) with K/V blocks rotating over the ``sp`` ring.
- ``tp``: attention heads and MLP hidden dim Megatron-sharded; QKV/fc1 are
  column-parallel (no collective), wo/fc2 are row-parallel (one psum each).

The loss is assembled to a fully-replicated scalar inside the program
(logits psum'd over tp, pooled via pmean over sp, loss pmean'd over dp), so
``jax.grad`` OF the shard_mapped function transposes every collective into
exactly the right gradient exchange - no hand-written backward collectives,
the property the reference's DDP reducer implements in C++
(``/root/reference/src/motion/trainer/ddp.py:19``).

Parameters stay replicated (the DP memory model, like the reference);
shards slice their piece inside the program, which XLA fuses into the
consuming matmul.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P
from pytorch_distributed_rnn_tpu.utils.compat import shard_map

from pytorch_distributed_rnn_tpu.models.attention import (
    _layer_norm,
    _linear,
)
from pytorch_distributed_rnn_tpu.ops.attention import (
    mha_attention,
    ring_attention,
)
from pytorch_distributed_rnn_tpu.ops.losses import cross_entropy_loss
from pytorch_distributed_rnn_tpu.parallel.sp import (
    sp_embed_prologue,
    sp_mean_pool,
)


def _col_slice(p, k, per):
    """Column-parallel slice: shard ``k`` takes ``per`` output rows."""
    return {
        "weight": lax.dynamic_slice_in_dim(p["weight"], k * per, per, axis=0),
        "bias": lax.dynamic_slice_in_dim(p["bias"], k * per, per),
    }


def _row_slice(p, k, per):
    """Row-parallel slice: shard ``k`` takes ``per`` input columns; bias is
    added once, after the psum."""
    return lax.dynamic_slice_in_dim(p["weight"], k * per, per, axis=1)


def tp_sp_block(blk, h, num_heads: int, *, sp_axis: str | None,
                tp_axis: str, causal: bool = False, impl: str = "dense"):
    """One encoder block with heads tp-sharded and time sp-sharded.

    ``h``: (B_local, T_local, dim).  QKV column-parallel -> ring attention
    over ``sp`` on this shard's head group -> wo row-parallel (one psum
    over ``tp``) -> MLP column+row parallel (one more psum).  ``impl``
    picks the ring's inner step: ``dense`` XLA online-softmax or the
    fused ``flash`` Pallas kernel.

    ``sp_axis=None`` runs LOCAL attention over the full (unsharded)
    sequence on this shard's head group - the pure-tp form the pp x tp
    composition uses, where no sequence axis exists in the mesh.
    """
    ntp = lax.axis_size(tp_axis)
    ktp = lax.axis_index(tp_axis)
    dim = h.shape[-1]
    if num_heads % ntp != 0:
        raise ValueError(f"{num_heads} heads do not shard over tp={ntp}")
    heads_local = num_heads // ntp
    dh = dim // num_heads
    per = heads_local * dh

    def split_heads(x):
        b, t, _ = x.shape
        return x.reshape(b, t, heads_local, dh).transpose(0, 2, 1, 3)

    y = _layer_norm(h, **blk["ln1"])
    q = split_heads(_linear(_col_slice(blk["wq"], ktp, per), y))
    k = split_heads(_linear(_col_slice(blk["wk"], ktp, per), y))
    v = split_heads(_linear(_col_slice(blk["wv"], ktp, per), y))

    if sp_axis is None:
        if impl == "flash":
            from pytorch_distributed_rnn_tpu.ops.pallas_attention import (
                flash_attention,
            )

            attn = flash_attention(q, k, v, causal=causal)
        else:
            attn = mha_attention(q, k, v, causal=causal)
    elif impl == "flash":
        from pytorch_distributed_rnn_tpu.ops.pallas_attention import (
            ring_flash_attention,
        )

        attn = ring_flash_attention(q, k, v, sp_axis, causal=causal)
    else:
        attn = ring_attention(q, k, v, sp_axis, causal=causal)
    b, hl, t, _ = attn.shape
    merged = attn.transpose(0, 2, 1, 3).reshape(b, t, per)

    wo_l = _row_slice(blk["wo"], ktp, per)
    h = h + lax.psum(merged @ wo_l.T, tp_axis) + blk["wo"]["bias"]

    y = _layer_norm(h, **blk["ln2"])
    mlp_hidden = blk["fc1"]["weight"].shape[0]
    if mlp_hidden % ntp != 0:
        raise ValueError(f"mlp hidden {mlp_hidden} does not shard over tp")
    per_mlp = mlp_hidden // ntp
    u = jax.nn.gelu(_linear(_col_slice(blk["fc1"], ktp, per_mlp), y))
    fc2_l = _row_slice(blk["fc2"], ktp, per_mlp)
    return h + lax.psum(u @ fc2_l.T, tp_axis) + blk["fc2"]["bias"]


def attention_mesh_logits(params, x_local, num_heads: int, *,
                          sp_axis: str = "sp", tp_axis: str = "tp",
                          causal: bool = False, impl: str = "dense",
                          compute_dtype=None, remat: bool = False):
    """The composed sp x tp forward for an AttentionClassifier params
    tree, for use INSIDE a shard_map where both axes are bound (size 1 is
    fine).  ``x_local``: this shard's (B_local, T_local, in) chunk;
    logits return replicated over sp and tp.  ``compute_dtype`` moves the
    block params/activations (and the tp psum + sp ring wire bytes) to
    e.g. bf16 - layernorm stats stay f32 (models/attention._layer_norm)
    and the pooled head computes f32; ``remat`` checkpoints each block
    (ring ppermutes replay during backward)."""
    h = sp_embed_prologue(params, x_local, sp_axis)
    if compute_dtype is not None:
        h = h.astype(compute_dtype)

    def block_fn(blk, h):
        return tp_sp_block(blk, h, num_heads, sp_axis=sp_axis,
                           tp_axis=tp_axis, causal=causal, impl=impl)

    if remat:
        block_fn = jax.checkpoint(block_fn)
    for blk in params["blocks"]:
        if compute_dtype is not None:
            blk = jax.tree.map(lambda p: p.astype(compute_dtype), blk)
        h = block_fn(blk, h)
    return _linear(params["head"],
                   sp_mean_pool(h.astype(jnp.float32), sp_axis))


def make_3d_loss_fn(model, mesh, *, dp_axis: str = "dp", sp_axis: str = "sp",
                    tp_axis: str = "tp", causal: bool = False):
    """Replicated-scalar loss for an AttentionClassifier over a
    (dp, sp, tp) mesh: ``loss(params, x, y)`` with ``x`` (B, T, in) sharded
    (dp, sp) and ``y`` (B,) sharded (dp)."""
    from pytorch_distributed_rnn_tpu.ops.pallas_attention import (
        resolve_attention_impl,
    )

    from pytorch_distributed_rnn_tpu.parallel.strategy import (
        resolve_model_levers,
    )

    impl = resolve_attention_impl(getattr(model, "impl", "auto"))
    compute_dtype, remat = resolve_model_levers(model)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(dp_axis, sp_axis), P(dp_axis)),
        out_specs=P(),
        check_vma=False,
    )
    def loss_fn(params, x_local, y_local):
        logits = attention_mesh_logits(
            params, x_local, model.num_heads, sp_axis=sp_axis,
            tp_axis=tp_axis, causal=causal, impl=impl,
            compute_dtype=compute_dtype, remat=remat,
        )
        return lax.pmean(cross_entropy_loss(logits, y_local), dp_axis)

    return loss_fn


def make_3d_train_step(model, optimizer, mesh, *, dp_axis: str = "dp",
                       sp_axis: str = "sp", tp_axis: str = "tp",
                       causal: bool = False, donate: bool = True):
    """Jitted full training step with dp x sp x tp composed.

    ``step(params, opt_state, (x, y)) -> (params, opt_state, loss)``;
    ``x`` (B, T, in) should arrive sharded (dp, sp) on (batch, time) and
    ``y`` (B,) sharded (dp) - jit reshards automatically if not.
    """
    loss_fn = make_3d_loss_fn(model, mesh, dp_axis=dp_axis, sp_axis=sp_axis,
                              tp_axis=tp_axis, causal=causal)

    def step(params, opt_state, batch):
        x, y = batch
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


# ---------------------------------------------------------------------------
# dp x sp x tp for the RNN families: gate-sharded cell inside the sp relay
# ---------------------------------------------------------------------------
#
# The sp relay (parallel/sp.py:_relay) rotates the recurrent carry around
# the time shards; the tp gate sharding (parallel/tp.py) splits every
# gate's H rows across shards with one all-gather of h per step.  They
# compose because they act on DIFFERENT parts of the step: the relay
# moves the (h, c) carry BETWEEN time chunks along sp, while inside a
# chunk's scan each (sp, tp) shard computes only its 4H/ntp gate slice
# and carries its H/ntp slice of the state - ppermute over sp moves the
# tp-local slices between sp neighbours at a fixed tp coordinate, so the
# two axes never exchange with each other.  (This replaces the old
# "RNN cells take dp plus at most one model axis" claim, which was a
# scoping decision, not a structural limit - VERDICT r3 item 6.)


def sp_tp_lstm_layer(params, x_local, sp_axis: str, tp_axis: str, *,
                     unroll: int = 1, compute_dtype=None):
    """One LSTM layer with time sharded over ``sp_axis`` AND the hidden
    dimension gate-sharded over ``tp_axis``, inside ``shard_map``.

    ``x_local``: this shard's (B, T/S, in) time chunk (replicated over
    tp).  Returns ``(outputs_local (B, T/S, H/ntp), (h_T, c_T))`` -
    outputs stay tp-local (callers gather between layers or run a
    row-parallel head); the relayed carry is the tp-local (B, H/ntp)
    slice pair, f32 per the lstm_step mixed-precision contract.
    """
    from pytorch_distributed_rnn_tpu.ops.rnn import lstm_input_proj
    from pytorch_distributed_rnn_tpu.parallel.sp import _relay
    from pytorch_distributed_rnn_tpu.parallel.tp import (
        sharded_gate_params,
        tp_lstm_step,
    )

    nsp = lax.axis_size(sp_axis)
    ntp = lax.axis_size(tp_axis)
    ktp = lax.axis_index(tp_axis)
    hidden = params["w_hh"].shape[1]
    per = hidden // ntp
    batch = x_local.shape[0]

    local, x_local = sharded_gate_params(params, ntp, ktp, x_local,
                                         compute_dtype=compute_dtype)
    x_proj = lstm_input_proj(local, x_local)             # (B, T/S, 4H/ntp)
    w_hh_l_t = local["w_hh"].T                           # (H, 4H/ntp)

    def chunk(carry):
        carry, out = lax.scan(
            lambda c, xp: tp_lstm_step(w_hh_l_t, tp_axis, c, xp),
            carry, jnp.swapaxes(x_proj, 0, 1), unroll=unroll
        )
        return carry, jnp.swapaxes(out, 0, 1)

    h0 = jnp.zeros((batch, per), jnp.float32)
    c0 = jnp.zeros((batch, per), jnp.float32)
    final, outputs = _relay(sp_axis, nsp, (h0, c0), chunk)
    return outputs, final


def sp_tp_gru_layer(params, x_local, sp_axis: str, tp_axis: str, *,
                    unroll: int = 1, compute_dtype=None):
    """GRU sibling of :func:`sp_tp_lstm_layer` (3 gates r, z, n; torch
    semantics - the hidden-side n-bias joins inside the ``r *`` product,
    sliced like the weights)."""
    from pytorch_distributed_rnn_tpu.ops.rnn import gru_input_proj
    from pytorch_distributed_rnn_tpu.parallel.sp import _relay
    from pytorch_distributed_rnn_tpu.parallel.tp import (
        sharded_gate_params,
        tp_gru_step,
    )

    nsp = lax.axis_size(sp_axis)
    ntp = lax.axis_size(tp_axis)
    ktp = lax.axis_index(tp_axis)
    hidden = params["w_hh"].shape[1]
    per = hidden // ntp
    batch = x_local.shape[0]

    local, x_local = sharded_gate_params(params, ntp, ktp, x_local,
                                         num_gates=3,
                                         compute_dtype=compute_dtype)
    x_proj = gru_input_proj(local, x_local)              # (B, T/S, 3H/ntp)
    w_hh_l_t = local["w_hh"].T
    b_hh_l = local["b_hh"]

    def chunk(carry):
        carry, out = lax.scan(
            lambda h, xp: tp_gru_step(w_hh_l_t, b_hh_l, tp_axis, h, xp),
            carry, jnp.swapaxes(x_proj, 0, 1), unroll=unroll
        )
        return carry, jnp.swapaxes(out, 0, 1)

    h0 = jnp.zeros((batch, per), jnp.float32)
    final, outputs = _relay(sp_axis, nsp, h0, chunk)
    return outputs, final


def sp_tp_stacked_rnn(layers, x_local, sp_axis: str, tp_axis: str, *,
                      cell: str = "lstm", unroll: int = 1,
                      compute_dtype=None, remat: bool = False,
                      dropout: float = 0.0, dropout_key=None):
    """Stack of sp x tp layers - layer-sequential relay (each layer is a
    full relay over sp) with gate-sharded cells over tp.

    Intermediate layer outputs are all-gathered over tp (the next layer's
    input projection wants full H); the LAST layer's output stays
    tp-local (B, T/S, H/ntp) so callers can run a row-parallel head
    without re-gathering.  ``dropout`` masks between layers on the
    gathered full-width activations (the same seam as the sequential sp
    relay; the key folds in the sp index only, so tp shards agree on the
    mask).  ``remat`` checkpoints each layer's relay.
    """
    from pytorch_distributed_rnn_tpu.ops.rnn import interlayer_dropout

    layer_fn = (sp_tp_gru_layer if cell == "gru" else sp_tp_lstm_layer)
    layer_fn = partial(layer_fn, sp_axis=sp_axis, tp_axis=tp_axis,
                       unroll=unroll, compute_dtype=compute_dtype)
    if remat:
        layer_fn = jax.checkpoint(layer_fn)
    out = x_local
    finals = []
    for idx, layer in enumerate(layers):
        out_local, final = layer_fn(layer, out)
        finals.append(final)
        if idx < len(layers) - 1:
            out = lax.all_gather(out_local, tp_axis, axis=2, tiled=True)
            if dropout > 0.0 and dropout_key is not None:
                out, dropout_key = interlayer_dropout(out, dropout_key,
                                                      dropout)
    return out_local, finals
