"""Point-to-point primitives: the ``dist.send``/``dist.recv`` analogue.

The reference's p2p example has rank 0 send a tensor to every other rank
(``/root/reference/src/example/example_distributed.py:8-14``).  On TPU the
idiomatic transport is ``lax.ppermute`` (XLA CollectivePermute over ICI):
``ring_relay_from_root`` forwards the root's value hop-by-hop around the
ring - (n-1) nearest-neighbor hops instead of n-1 long-haul unicast sends,
which is how data actually wants to move on a torus interconnect.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from pytorch_distributed_rnn_tpu.utils.compat import shard_map


def ring_relay_from_root(x, mesh, axis: str = "dp", root: int = 0):
    """Relay ``root``'s shard of ``x`` (sharded along ``axis``) to every
    shard via ring ppermute hops.  Returns the relayed value, replicated."""
    n = mesh.shape[axis]

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(axis),
        check_vma=False,
    )
    def _relay(val):
        idx = lax.axis_index(axis)
        perm = [(i, (i + 1) % n) for i in range(n)]

        def hop(carry, _):
            received = lax.ppermute(carry, axis, perm)
            # keep own value at root; everyone else adopts what arrived
            keep = (idx == root)
            carry = jax.tree.map(
                lambda own, got: jnp.where(keep, own, got), carry, received
            )
            return carry, None

        out, _ = lax.scan(hop, val, None, length=n - 1)
        return out

    return _relay(x)


def ppermute_shift(x, mesh, axis: str = "dp", shift: int = 1):
    """Cyclically shift shards along ``axis`` by ``shift`` positions - the
    raw send/recv building block (each rank sends to rank+shift)."""
    n = mesh.shape[axis]
    perm = [(i, (i + shift) % n) for i in range(n)]

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(axis),
        check_vma=False,
    )
    def _shift(val):
        return lax.ppermute(val, axis, perm)

    return _shift(x)
