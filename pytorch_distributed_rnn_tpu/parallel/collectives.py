"""Collective primitives over mesh axes, for use inside ``shard_map``.

These are the XLA-native replacements for the reference's MPI/Horovod
primitives (broadcast / allreduce / allgather - see
``/root/reference/src/motion/trainer/ddp.py:18-19``,
``example_horovod.py:42``): ``psum``/``pmean`` lower to XLA AllReduce over
ICI/DCN, ``broadcast_from`` lowers to a masked AllReduce, ``all_gather`` to
XLA AllGather.  They operate on whole parameter pytrees.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def psum_tree(tree, axis):
    """``axis`` may be one mesh-axis name or a tuple of names - a tuple
    reduces over their product, which is how hierarchical data parallelism
    (inner axis over ICI within a slice, outer axis over DCN across
    slices) expresses a global allreduce: XLA decomposes the multi-axis
    reduction into the per-network stages."""
    return jax.tree.map(lambda x: lax.psum(x, axis), tree)


def pmean_tree(tree, axis):
    return jax.tree.map(lambda x: lax.pmean(x, axis), tree)


def broadcast_from(tree, axis: str, root: int = 0):
    """Every shard receives ``root``'s values (hvd.broadcast_parameters
    analogue).  Implemented as mask + psum: a single XLA AllReduce."""
    idx = lax.axis_index(axis)

    def _bcast(x):
        mask = (idx == root).astype(x.dtype)
        return lax.psum(x * mask, axis)

    return jax.tree.map(_bcast, tree)


def allgather_tree(tree, axis: str):
    """Gather per-shard values along a new leading axis (rank order)."""
    return jax.tree.map(lambda x: lax.all_gather(x, axis), tree)
