"""Data-parallel SPMD train step: the DDP/Horovod-capability analogue.

The reference gets data parallelism from wrapper machinery - torch DDP's C++
reducer allreducing gradient buckets during ``backward()``
(``/root/reference/src/motion/trainer/ddp.py:19``) or Horovod's
DistributedOptimizer allreducing in ``step()``
(``trainer/horovod.py:33-35``).  The TPU-native design needs neither hook:
the whole train step is one SPMD program over a mesh - each shard computes
the gradient of its micro-batch, one ``pmean`` (XLA AllReduce over ICI)
averages gradients, and the optimizer update runs replicated.  XLA fuses and
overlaps the collective with compute; there is no bucketing to hand-tune.

``sync="backward"`` (DDP flavor) averages gradients immediately after the
backward pass; ``sync="step"`` (Horovod flavor) hands raw local gradients to
an optimizer-wrapper that averages them inside the update, mirroring where
each reference strategy hooks its allreduce.  Both produce identical math -
the flavors exist so each strategy's semantics (and failure modes) stay
independently testable, like the reference's two trainers.
"""

from __future__ import annotations

from functools import partial

import jax
import optax
from jax.sharding import PartitionSpec as P
from jax import shard_map

from pytorch_distributed_rnn_tpu.parallel.collectives import (
    broadcast_from,
    pmean_tree,
    psum_tree,
)


def broadcast_params(params, mesh, axis: str = "dp", root: int = 0):
    """Synchronize parameters from ``root``'s shard to all shards - the
    ``hvd.broadcast_parameters`` / DDP-construction-broadcast analogue.

    ``params`` may be per-device divergent (sharded along ``axis`` with one
    replica per shard); the result is root's copy everywhere.
    """

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(axis),
        check_vma=False,
    )
    def _bcast(tree):
        return broadcast_from(tree, axis, root)

    return _bcast(params)


def distributed_optimizer(optimizer, axis: str = "dp"):
    """Wrap an optax optimizer so its ``update`` averages gradients across
    ``axis`` first - the ``hvd.DistributedOptimizer`` analogue
    (``/root/reference/src/motion/trainer/horovod.py:33-35``): callers hand
    it *local* gradients and the allreduce happens inside the optimizer
    step.  Only usable inside an SPMD context (shard_map) where ``axis`` is
    bound."""

    def init(params):
        return optimizer.init(params)

    def update(grads, state, params=None):
        return optimizer.update(pmean_tree(grads, axis), state, params)

    return optax.GradientTransformation(init, update)


def make_spmd_train_step(
    loss_and_metrics,
    optimizer,
    mesh,
    axis: str = "dp",
    sync: str = "backward",
    donate: bool = True,
):
    """Build a jitted SPMD data-parallel train step.

    ``loss_and_metrics(params, batch) -> (loss, metrics)`` computes the
    *local* (per-shard) mean loss and a pytree of summable metrics (counts /
    sums).  The returned ``step(params, opt_state, batch)`` expects ``batch``
    sharded along ``axis`` on its leading dim and params/opt_state
    replicated; it returns ``(params, opt_state, loss, metrics)`` where
    ``loss`` is the global mean and ``metrics`` are globally summed.
    """
    if sync not in ("backward", "step"):
        raise ValueError(f"sync must be 'backward' or 'step', got {sync!r}")

    param_spec = P()  # replicated
    batch_spec = P(axis)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(param_spec, param_spec, batch_spec),
        out_specs=(param_spec, param_spec, param_spec, param_spec),
        check_vma=False,
    )
    def _step(params, opt_state, batch):
        (loss, metrics), grads = jax.value_and_grad(
            loss_and_metrics, has_aux=True
        )(params, batch)

        if sync == "backward":
            # DDP flavor: allreduce right after backward, optimizer sees
            # averaged gradients.
            grads = pmean_tree(grads, axis)
            updates, opt_state = optimizer.update(grads, opt_state, params)
        else:
            # Horovod flavor: raw local gradients go into a
            # distributed_optimizer, which allreduces inside its update.
            updates, opt_state = distributed_optimizer(optimizer, axis).update(
                grads, opt_state, params
            )

        params = optax.apply_updates(params, updates)
        loss = jax.lax.pmean(loss, axis)
        metrics = psum_tree(metrics, axis)
        return params, opt_state, loss, metrics

    jitted = jax.jit(_step, donate_argnums=(0, 1) if donate else ())
    return jitted
