"""Data-parallel SPMD train step: the DDP/Horovod-capability analogue.

The reference gets data parallelism from wrapper machinery - torch DDP's C++
reducer allreducing gradient buckets during ``backward()``
(``/root/reference/src/motion/trainer/ddp.py:19``) or Horovod's
DistributedOptimizer allreducing in ``step()``
(``trainer/horovod.py:33-35``).  The TPU-native design needs neither hook:
the whole train step is one SPMD program over a mesh - each shard computes
the gradient of its micro-batch, one ``pmean`` (XLA AllReduce over ICI)
averages gradients, and the optimizer update runs replicated.  XLA fuses and
overlaps the collective with compute; there is no bucketing to hand-tune.

``sync="backward"`` (DDP flavor) averages gradients immediately after the
backward pass; ``sync="step"`` (Horovod flavor) hands raw local gradients to
an optimizer-wrapper that averages them inside the update, mirroring where
each reference strategy hooks its allreduce.  Both produce identical math -
the flavors exist so each strategy's semantics (and failure modes) stay
independently testable, like the reference's two trainers.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P
from pytorch_distributed_rnn_tpu.utils.compat import shard_map

from pytorch_distributed_rnn_tpu.parallel.collectives import (
    broadcast_from,
    pmean_tree,
    psum_tree,
)


def broadcast_params(params, mesh, axis: str = "dp", root: int = 0):
    """Synchronize parameters from ``root``'s shard to all shards - the
    ``hvd.broadcast_parameters`` / DDP-construction-broadcast analogue.

    ``params`` may be per-device divergent (sharded along ``axis`` with one
    replica per shard); the result is root's copy everywhere.
    """

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(axis),
        check_vma=False,
    )
    def _bcast(tree):
        return broadcast_from(tree, axis, root)

    return _bcast(params)


def distributed_optimizer(optimizer, axis: str = "dp"):
    """Wrap an optax optimizer so its ``update`` averages gradients across
    ``axis`` first - the ``hvd.DistributedOptimizer`` analogue
    (``/root/reference/src/motion/trainer/horovod.py:33-35``): callers hand
    it *local* gradients and the allreduce happens inside the optimizer
    step.  Only usable inside an SPMD context (shard_map) where ``axis`` is
    bound."""

    def init(params):
        return optimizer.init(params)

    def update(grads, state, params=None):
        return optimizer.update(pmean_tree(grads, axis), state, params)

    return optax.GradientTransformation(init, update)


def _make_grad_step(loss_and_metrics, optimizer, axis: str, sync: str,
                    sharded=None):
    """The one grad+sync+update body every SPMD factory shares.

    ``sync="backward"`` (DDP flavor) allreduces gradients right after the
    backward pass, so the optimizer sees averaged gradients;
    ``sync="step"`` (Horovod flavor) hands raw local gradients to a
    :func:`distributed_optimizer` that allreduces inside its update -
    mirroring where each reference strategy hooks its allreduce.  Returns
    ``step(params, opt_state, batch, *extra) -> (params, opt_state,
    local_loss, local_metrics)``; ``*extra`` is forwarded to the loss fn
    (the weighted-run path's mask).

    ``sharded`` (a :class:`~..parallel.sharded_update.ShardedUpdate` bound
    to ``optimizer`` and ``axis``) replaces the allreduce + replicated
    full apply with reduce-scatter + 1/world optimizer apply + params
    allgather (PAPERS.md 2004.13336).  Both sync flavors share the one
    sharded body: ``psum_scatter(g)/world`` IS the matching slice of the
    pmean both flavors converge to, so the flavors differ only in where
    the replicated path hooks its allreduce - a distinction the sharded
    schedule dissolves by construction.  ``opt_state`` must then be in
    the sharded flat layout (``ShardedUpdate.init_opt_state``).
    """
    if sync not in ("backward", "step"):
        raise ValueError(f"sync must be 'backward' or 'step', got {sync!r}")
    if sharded is not None:

        def step(params, opt_state, batch, *extra):
            (loss, metrics), grads = jax.value_and_grad(
                loss_and_metrics, has_aux=True
            )(params, batch, *extra)
            params, opt_state = sharded.apply(params, grads, opt_state)
            return params, opt_state, loss, metrics

        return step
    opt = distributed_optimizer(optimizer, axis) if sync == "step" else optimizer

    def step(params, opt_state, batch, *extra):
        (loss, metrics), grads = jax.value_and_grad(
            loss_and_metrics, has_aux=True
        )(params, batch, *extra)
        if sync == "backward":
            grads = pmean_tree(grads, axis)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, metrics

    return step


def make_spmd_train_step(
    loss_and_metrics,
    optimizer,
    mesh,
    axis: str = "dp",
    sync: str = "backward",
    donate: bool = True,
    with_key: bool = False,
    sharded=None,
):
    """Build a jitted SPMD data-parallel train step.

    ``loss_and_metrics(params, batch) -> (loss, metrics)`` computes the
    *local* (per-shard) mean loss and a pytree of summable metrics (counts /
    sums).  The returned ``step(params, opt_state, batch)`` expects ``batch``
    sharded along ``axis`` on its leading dim and params/opt_state
    replicated; it returns ``(params, opt_state, loss, metrics)`` where
    ``loss`` is the global mean and ``metrics`` are globally summed.

    ``with_key=True`` adds a trailing replicated per-step PRNG key argument
    forwarded to the loss fn (train-mode dropout; the loss fn folds the
    rank in so each shard draws an independent mask).

    ``sharded`` switches the update to the reduce-scatter / sharded-apply /
    allgather schedule; ``opt_state`` must then be in the sharded flat
    layout and stays sharded along ``axis`` across steps.
    """
    grad_step = _make_grad_step(loss_and_metrics, optimizer, axis, sync,
                                sharded=sharded)
    rep = P()
    st = sharded.opt_state_specs() if sharded is not None else rep
    key_specs = (rep,) if with_key else ()

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(rep, st, P(axis)) + key_specs,
        out_specs=(rep, st, rep, rep),
        check_vma=False,
    )
    def _step(params, opt_state, batch, *extra):
        params, opt_state, loss, metrics = grad_step(
            params, opt_state, batch, *extra
        )
        return (
            params,
            opt_state,
            jax.lax.pmean(loss, axis),
            psum_tree(metrics, axis),
        )

    return jax.jit(_step, donate_argnums=(0, 1) if donate else ())


def make_spmd_idx_train_step(
    loss_and_metrics,
    optimizer,
    mesh,
    axis: str = "dp",
    sync: str = "backward",
    donate: bool = True,
    with_key: bool = False,
    sharded=None,
):
    """Like :func:`make_spmd_train_step` but the batch is selected ON
    DEVICE: ``step(params, opt_state, features, labels, idx)`` gathers
    ``(features[idx], labels[idx])`` inside the SPMD program.

    TPU-native data path: the dataset lives in HBM (replicated), and only
    the per-batch *indices* cross host->device each step - the reference
    instead re-loads per-rank tensors from host memory every batch
    (``/root/reference/src/motion/trainer/base.py:107``), which over a slow
    host link starves the accelerator.  ``idx`` is sharded along ``axis``
    (rank-major), so each shard gathers exactly its rank's micro-batch.
    """
    grad_step = _make_grad_step(loss_and_metrics, optimizer, axis, sync,
                                sharded=sharded)
    rep = P()
    st = sharded.opt_state_specs() if sharded is not None else rep
    key_specs = (rep,) if with_key else ()

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(rep, st, rep, rep, P(axis)) + key_specs,
        out_specs=(rep, st, rep, rep),
        check_vma=False,
    )
    def _step(params, opt_state, features, labels, idx, *extra):
        batch = (features[idx], labels[idx])
        params, opt_state, loss, metrics = grad_step(
            params, opt_state, batch, *extra
        )
        return (
            params,
            opt_state,
            jax.lax.pmean(loss, axis),
            psum_tree(metrics, axis),
        )

    return jax.jit(_step, donate_argnums=(0, 1) if donate else ())


def make_spmd_epoch_fn(
    loss_and_metrics,
    optimizer,
    mesh,
    axis: str = "dp",
    sync: str = "backward",
    donate: bool = True,
    with_key: bool = False,
    sharded=None,
):
    """Whole-epoch SPMD program: ``lax.scan`` over the epoch's batch-index
    matrix, one device dispatch per epoch.

    ``epoch_fn(params, opt_state, features, labels, idx_mat)`` with
    ``idx_mat`` of shape (num_batches, global_batch) sharded
    ``P(None, axis)`` runs every train step back-to-back on device and
    returns ``(params, opt_state, loss_sum, metrics_sum)`` where
    ``loss_sum`` is the sum over batches of the global-mean batch loss (the
    quantity the reference accumulates, ``base.py:123-128``).  Eliminates
    per-step dispatch/transfer latency entirely - the TPU-native answer to
    the reference's per-batch Python loop.

    ``with_key=True`` adds a trailing replicated (num_batches, 2) per-step
    key matrix riding the scan (train-mode dropout).
    """
    grad_step = _make_grad_step(loss_and_metrics, optimizer, axis, sync,
                                sharded=sharded)
    rep = P()
    st = sharded.opt_state_specs() if sharded is not None else rep
    key_specs = (P(None),) if with_key else ()

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(rep, st, rep, rep, P(None, axis)) + key_specs,
        out_specs=(rep, st, rep, rep),
        check_vma=False,
    )
    def _epoch(params, opt_state, features, labels, idx_mat, *key_mat):
        def body(carry, step_in):
            params, opt_state = carry
            idx = step_in[0] if with_key else step_in
            extra = (step_in[1],) if with_key else ()
            batch = (features[idx], labels[idx])
            params, opt_state, loss, metrics = grad_step(
                params, opt_state, batch, *extra
            )
            return (params, opt_state), (loss, metrics)

        xs = (idx_mat, key_mat[0]) if with_key else idx_mat
        (params, opt_state), (losses, metrics) = jax.lax.scan(
            body, (params, opt_state), xs
        )
        # pmean is linear: one scalar AllReduce after the scan instead of
        # one per step
        loss_sum = jax.lax.pmean(jnp.sum(losses), axis)
        metrics_sum = psum_tree(
            jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics), axis
        )
        return params, opt_state, loss_sum, metrics_sum

    return jax.jit(_epoch, donate_argnums=(0, 1) if donate else ())


def make_spmd_run_fn(
    weighted_loss_and_metrics,
    optimizer,
    mesh,
    axis: str = "dp",
    sync: str = "backward",
    donate: bool = True,
    with_key: bool = False,
    sharded=None,
):
    """The whole multi-epoch training run as ONE SPMD program: scan over
    every (weight-masked) batch of every epoch.

    ``run(params, opt_state, features, labels, idx_mat, w_mat)`` with
    ``idx_mat``/``w_mat`` of shape (total_steps, global_batch) sharded
    ``P(None, axis)``; returns per-step global-mean losses and summed
    correct-counts.  The weighted local means pmean exactly to the global
    weighted mean because every rank's chunk carries the same number of
    live examples (the sampler pads shards to equal length, and batch
    padding is per-rank-equal by construction).
    """
    grad_step = _make_grad_step(weighted_loss_and_metrics, optimizer, axis,
                                sync, sharded=sharded)
    rep = P()
    st = sharded.opt_state_specs() if sharded is not None else rep
    key_specs = (P(None),) if with_key else ()

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(rep, st, rep, rep, P(None, axis), P(None, axis))
        + key_specs,
        out_specs=(rep, st, rep, rep),
        check_vma=False,
    )
    def _run(params, opt_state, features, labels, idx_mat, w_mat, *key_mat):
        def body(carry, step_in):
            params, opt_state = carry
            idx, w = step_in[0], step_in[1]
            extra = (step_in[2],) if with_key else ()
            batch = (features[idx], labels[idx])
            params, opt_state, loss, metrics = grad_step(
                params, opt_state, batch, w, *extra
            )
            return (params, opt_state), (loss, metrics["correct"])

        xs = (
            (idx_mat, w_mat, key_mat[0]) if with_key else (idx_mat, w_mat)
        )
        (params, opt_state), (losses, correct) = jax.lax.scan(
            body, (params, opt_state), xs
        )
        # pmean/psum are linear: one vector collective each after the scan
        # instead of one per step
        return (
            params,
            opt_state,
            jax.lax.pmean(losses, axis),
            jax.lax.psum(correct, axis),
        )

    return jax.jit(_run, donate_argnums=(0, 1) if donate else ())


# ---------------------------------------------------------------------------
# pdrnn-lint --deep trace registry (lint/trace_registry.py)


def _lint_motion_program():
    """Tiny motion-model pieces shared by the dp trace entries: abstract
    params/opt-state specs only (jax.eval_shape), no real data."""
    import optax

    from pytorch_distributed_rnn_tpu.lint.trace_registry import (
        abstract_init,
        lint_mesh,
        prng_spec,
        sds,
    )
    from pytorch_distributed_rnn_tpu.models import MotionModel
    from pytorch_distributed_rnn_tpu.ops import cross_entropy_loss

    mesh = lint_mesh({"dp": 2})
    model = MotionModel(input_dim=9, hidden_dim=8, layer_dim=1,
                        output_dim=6, impl="scan")
    params = abstract_init(model.init, prng_spec())
    optimizer = optax.adam(1e-3)
    opt_state = abstract_init(optimizer.init, params)

    def loss_and_metrics(p, batch):
        x, y = batch
        logits = model.apply(p, x)
        return cross_entropy_loss(logits, y), {
            "correct": jnp.sum(jnp.argmax(logits, axis=1) == y)
        }

    return mesh, optimizer, loss_and_metrics, params, opt_state, sds


def declare_trace_entries(register):
    """Register the SPMD data-parallel step programs for the jaxpr-level
    lint pass: the per-batch step (the DDP/Horovod strategies' core) and
    the whole-epoch scan program (collectives inside lax.scan)."""
    path = "pytorch_distributed_rnn_tpu/parallel/dp.py"

    def build_step():
        mesh, opt, loss, params, opt_state, sds = _lint_motion_program()
        step = make_spmd_train_step(loss, opt, mesh)
        batch = (sds((4, 16, 9), jnp.float32), sds((4,), jnp.int32))
        return step, (params, opt_state, batch)

    register(
        name="dp.spmd_train_step", family="ddp", path=path,
        build=build_step, mesh_axes={"dp": 2}, data_axis="dp",
        donate=(0, 1),
    )

    def build_epoch():
        mesh, opt, loss, params, opt_state, sds = _lint_motion_program()
        epoch = make_spmd_epoch_fn(loss, opt, mesh)
        features = sds((8, 16, 9), jnp.float32)
        labels = sds((8,), jnp.int32)
        idx_mat = sds((3, 4), jnp.int32)
        return epoch, (params, opt_state, features, labels, idx_mat)

    register(
        name="dp.spmd_epoch_fn", family="ddp", path=path,
        build=build_epoch, mesh_axes={"dp": 2}, data_axis="dp",
        donate=(0, 1),
    )

    # Sharded-update variants (PAPERS.md 2004.13336): the same programs
    # with the update-phase allreduce replaced by reduce-scatter +
    # 1/world apply + allgather.  The per-entry collective artifact diffs
    # these against the replicated entries above (see
    # lint/collective_check.py).
    def _sharded(sync):
        from pytorch_distributed_rnn_tpu.parallel.sharded_update import (
            ShardedUpdate,
        )

        mesh, opt, loss, params, _, sds = _lint_motion_program()
        sharded = ShardedUpdate(opt, params, mesh.shape["dp"])
        return mesh, opt, loss, params, sharded.abstract_opt_state(), sds, sharded

    def build_step_sharded():
        mesh, opt, loss, params, opt_state, sds, sharded = _sharded("backward")
        step = make_spmd_train_step(loss, opt, mesh, sharded=sharded)
        batch = (sds((4, 16, 9), jnp.float32), sds((4,), jnp.int32))
        return step, (params, opt_state, batch)

    register(
        name="dp.spmd_train_step_sharded", family="ddp", path=path,
        build=build_step_sharded, mesh_axes={"dp": 2}, data_axis="dp",
        donate=(0, 1),
    )

    def build_step_sharded_hvd():
        mesh, opt, loss, params, opt_state, sds, sharded = _sharded("step")
        step = make_spmd_train_step(loss, opt, mesh, sync="step",
                                    sharded=sharded)
        batch = (sds((4, 16, 9), jnp.float32), sds((4,), jnp.int32))
        return step, (params, opt_state, batch)

    register(
        name="dp.spmd_train_step_sharded_hvd", family="horovod", path=path,
        build=build_step_sharded_hvd, mesh_axes={"dp": 2}, data_axis="dp",
        donate=(0, 1),
    )

    def build_epoch_sharded():
        mesh, opt, loss, params, opt_state, sds, sharded = _sharded("backward")
        epoch = make_spmd_epoch_fn(loss, opt, mesh, sharded=sharded)
        features = sds((8, 16, 9), jnp.float32)
        labels = sds((8,), jnp.int32)
        idx_mat = sds((3, 4), jnp.int32)
        return epoch, (params, opt_state, features, labels, idx_mat)

    register(
        name="dp.spmd_epoch_fn_sharded", family="ddp", path=path,
        build=build_epoch_sharded, mesh_axes={"dp": 2}, data_axis="dp",
        donate=(0, 1),
    )
