"""MPMD pipeline runner: one process, one program per stage.

Every schedule in ``parallel/pp.py`` (GPipe, 1F1B, interleaved) is a
single SPMD program over one device mesh - efficient, but one failure
domain: a dead rank kills the whole pipeline world and every survivor
recompiles on the rebuilt mesh.  This module is the MPMD counterpart
(PAPERS.md arxiv 2412.14374; the Podracer decoupled-process shape,
arxiv 2104.06272): each stage is its OWN process that jits only its
slice of the model -

- stage 0: input + the first layers (and the deterministic synthetic
  data producer, so a restarted stage 0 regenerates identical batches);
- middle stages: layers, forward + vjp-recompute backward;
- the last stage: layers + classifier head + loss, one fused
  loss/grad program;

and exchanges activations/gradients over per-link framed TCP worlds
(``runtime/stage.py``).  Fill-drain GPipe semantics with
``--microbatches`` microbatches per step, per-stage adam, gradients
accumulated across the step then applied - bit-for-bit the math of the
equivalent single-process model, which is what makes the chaos drill's
loss-parity assertion exact.

Robustness is the headline.  A :class:`~pytorch_distributed_rnn_tpu.
launcher.supervisor.StageSupervisor` respawns a SIGKILLed/preempted
stage into the same stage-id; the restarted process restores params +
optimizer state from its own per-stage crash-safe checkpoint
(``training/checkpoint.py``, written every step BEFORE the next step's
sends), re-dials its neighbors through the links' fixed ports, and the
watermark handshake replays the bounded in-flight microbatch window
exactly once.  Surviving stages keep their compiled programs - the
per-program trace counters in :class:`StagePrograms` pin
restart-without-recompile the same way serving's zero-retrace contract
does.  Chaos rides the standard ``FaultSchedule`` ``@rank`` scoping
(``--faults step:2:kill@1`` SIGKILLs stage 1 at step 2), telemetry
rides ``obs/`` (``stage`` timeline lane, ``stage_restart``/``replay``
events, heartbeat/health, stack-dump watchdog hook).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import multiprocessing as mp
import time
import zlib
from pathlib import Path

import numpy as np

log = logging.getLogger(__name__)

# exit code of a stage that drained on SIGTERM: 0 on purpose, same
# contract as the PS world (a voluntary leave is success; the telemetry
# distinction rides the member_drain event)
DRAIN_EXIT_CODE = 0


# ---------------------------------------------------------------------------
# configuration


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    """Static pipeline geometry - every stage derives its slice, link
    shapes, and watermarks from this one value, so all processes agree
    by construction."""

    stages: int = 3
    layers: int = 4
    feature_dim: int = 6
    hidden_dim: int = 16
    num_classes: int = 5
    seq_len: int = 8
    microbatch_size: int = 4
    microbatches: int = 2
    steps: int = 6
    lr: float = 1e-2
    seed: int = 0

    def __post_init__(self):
        if self.stages < 1:
            raise ValueError("stages must be >= 1")
        if self.layers < self.stages:
            raise ValueError(
                f"need at least one layer per stage "
                f"({self.layers} layers < {self.stages} stages)"
            )

    @classmethod
    def from_args(cls, args) -> "PipelineConfig":
        return cls(
            stages=args.stages, layers=args.layers,
            feature_dim=args.feature_dim, hidden_dim=args.hidden_dim,
            num_classes=args.num_classes, seq_len=args.seq_len,
            microbatch_size=args.microbatch_size,
            microbatches=args.microbatches, steps=args.steps,
            lr=args.lr, seed=args.seed,
        )

    def layer_range(self, stage: int) -> tuple[int, int]:
        """Contiguous, balanced layer slice ``[lo, hi)`` for ``stage``."""
        base, extra = divmod(self.layers, self.stages)
        lo = stage * base + min(stage, extra)
        return lo, lo + base + (1 if stage < extra else 0)

    def input_shape(self, stage: int) -> tuple[int, int, int]:
        dim = self.feature_dim if stage == 0 else self.hidden_dim
        return (self.microbatch_size, self.seq_len, dim)

    def act_shape(self) -> tuple[int, int, int]:
        """Tensor shape crossing every inter-stage link (activations
        downstream, their cotangents upstream)."""
        return (self.microbatch_size, self.seq_len, self.hidden_dim)

    def link_port(self, link: int, base_port: int) -> int:
        """Fixed port of link ``k`` (stage k <-> k+1): deterministic so
        a respawned stage re-dials without any rendezvous exchange."""
        return base_port + link


# ---------------------------------------------------------------------------
# model slice: params, forward, backward, update


def _init_layer(seed: int, layer: int, in_dim: int, hidden: int) -> dict:
    # seeded PER LAYER (not per stage): the same global layer gets the
    # same init under any stage partitioning, so an S-stage pipeline is
    # bit-comparable to the single-process composition of the same model
    rng = np.random.default_rng(seed * 1_000_003 + layer)
    return {
        "w": (rng.standard_normal((in_dim, hidden)) / np.sqrt(in_dim))
        .astype(np.float32),
        "u": (rng.standard_normal((hidden, hidden)) / np.sqrt(hidden))
        .astype(np.float32),
        "b": np.zeros((hidden,), np.float32),
    }


def init_stage_params(cfg: PipelineConfig, stage: int) -> dict:
    lo, hi = cfg.layer_range(stage)
    params = {
        "layers": [
            _init_layer(
                cfg.seed, layer,
                cfg.feature_dim if layer == 0 else cfg.hidden_dim,
                cfg.hidden_dim,
            )
            for layer in range(lo, hi)
        ]
    }
    if stage == cfg.stages - 1:
        rng = np.random.default_rng(cfg.seed * 1_000_003 + cfg.layers)
        params["head"] = {
            "wo": (
                rng.standard_normal((cfg.hidden_dim, cfg.num_classes))
                / np.sqrt(cfg.hidden_dim)
            ).astype(np.float32),
            "bo": np.zeros((cfg.num_classes,), np.float32),
        }
    return params


def _layer_forward(layer, x):
    import jax
    import jax.numpy as jnp

    def cell(h, x_t):
        h = jnp.tanh(x_t @ layer["w"] + h @ layer["u"] + layer["b"])
        return h, h

    h0 = jnp.zeros((x.shape[0], layer["u"].shape[0]), x.dtype)
    _, hs = jax.lax.scan(cell, h0, jnp.swapaxes(x, 0, 1))
    return jnp.swapaxes(hs, 0, 1)


def stage_apply(params, x):
    """This stage's layer stack over the (batch, time, features) input."""
    h = x
    for layer in params["layers"]:
        h = _layer_forward(layer, h)
    return h


def make_forward(cfg: PipelineConfig, stage: int):
    """Forward program of a non-last stage: ``fwd(params, x) -> acts``."""
    del cfg, stage  # the slice lives in the params pytree

    def forward(params, x):
        return stage_apply(params, x)

    return forward


def make_backward(cfg: PipelineConfig, stage: int):
    """Backward program of a non-last stage: vjp-recompute from the
    SAVED INPUT (not saved activations) - the standard pipeline
    rematerialization trade, and what keeps the link payload a single
    tensor per direction."""
    del cfg, stage

    def backward(params, x, d_out):
        import jax

        _, vjp = jax.vjp(stage_apply, params, x)
        d_params, d_x = vjp(d_out)
        return d_params, d_x

    return backward


def make_last_step(cfg: PipelineConfig):
    """The last stage's fused program: layers + head + softmax
    cross-entropy, returning ``(loss, d_params, d_input)`` in one
    compiled call per microbatch."""

    def last_step(params, x, labels):
        import jax
        import jax.numpy as jnp

        def loss_fn(p, xx):
            pooled = stage_apply(p, xx).mean(axis=1)
            logits = pooled @ p["head"]["wo"] + p["head"]["bo"]
            logp = jax.nn.log_softmax(logits)
            picked = jnp.take_along_axis(logp, labels[:, None], axis=1)
            return -picked.mean()

        loss, (d_params, d_x) = jax.value_and_grad(
            loss_fn, argnums=(0, 1)
        )(params, x)
        return loss, d_params, d_x

    return last_step


def make_update(cfg: PipelineConfig, optimizer):
    """Per-stage optimizer application over the step's ACCUMULATED
    gradients (summed across microbatches; the 1/M scaling happens here
    so every stage normalizes identically)."""

    def update(params, opt_state, grads):
        import jax
        import optax

        grads = jax.tree.map(
            lambda g: g / cfg.microbatches, grads
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    return update


def _counted(fn, counts: dict, name: str):
    """Serving-style zero-retrace pin: the counter bumps INSIDE the
    traced body, so ``counts[name]`` is exactly the number of traces -
    a survivor whose count stays 1 across a neighbor's respawn provably
    never recompiled."""

    def wrapped(*args):
        counts[name] = counts.get(name, 0) + 1
        return fn(*args)

    return wrapped


class StagePrograms:
    """One stage's compiled programs + trainable state."""

    def __init__(self, cfg: PipelineConfig, stage: int):
        import jax
        import optax

        self.cfg = cfg
        self.stage = stage
        self.is_first = stage == 0
        self.is_last = stage == cfg.stages - 1
        self.params = init_stage_params(cfg, stage)
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.trace_counts: dict[str, int] = {}
        if self.is_last:
            self.last_step = jax.jit(
                _counted(make_last_step(cfg), self.trace_counts, "last_step")
            )
        else:
            self.forward = jax.jit(
                _counted(
                    make_forward(cfg, stage), self.trace_counts, "forward"
                )
            )
            self.backward = jax.jit(
                _counted(
                    make_backward(cfg, stage), self.trace_counts, "backward"
                )
            )
        self.update = jax.jit(
            _counted(
                make_update(cfg, self.optimizer), self.trace_counts, "update"
            )
        )


def batch_for_step(cfg: PipelineConfig, step: int):
    """Deterministic synthetic batch for ``step``: seeded per (seed,
    step), so stage 0 regenerates identical features and the LAST stage
    regenerates identical labels locally - labels never ride the
    pipeline, and a restarted stage replays the exact data stream."""
    rng = np.random.default_rng(cfg.seed * 7_919 + step + 1)
    features = rng.standard_normal(
        (cfg.microbatches, cfg.microbatch_size, cfg.seq_len, cfg.feature_dim)
    ).astype(np.float32)
    labels = rng.integers(
        0, cfg.num_classes, size=(cfg.microbatches, cfg.microbatch_size)
    ).astype(np.int32)
    return features, labels


def params_crc(params) -> int:
    """Order-stable CRC of a params pytree - the drill's bitwise
    end-state identity check across chaos/baseline runs."""
    import jax

    crc = 0
    for leaf in jax.tree.leaves(params):
        crc = zlib.crc32(np.ascontiguousarray(leaf).tobytes(), crc)
    return crc


# ---------------------------------------------------------------------------
# stage process


def run_stage(args, stage_id: int, rejoin: bool = False) -> None:
    """One pipeline stage, start to finish (or drain)."""
    from pytorch_distributed_rnn_tpu.obs import install_stack_dump_handler
    from pytorch_distributed_rnn_tpu.obs.recorder import MetricsRecorder
    from pytorch_distributed_rnn_tpu.resilience.faults import FaultSchedule
    from pytorch_distributed_rnn_tpu.resilience.membership import (
        DrainRequested,
        DrainSignal,
    )
    from pytorch_distributed_rnn_tpu.runtime.stage import LinkEnd
    from pytorch_distributed_rnn_tpu.training.checkpoint import (
        find_latest_checkpoint,
        load_checkpoint,
        rotate_checkpoints,
        save_checkpoint,
    )

    logging.basicConfig(level=args.log)
    cfg = PipelineConfig.from_args(args)
    programs = StagePrograms(cfg, stage_id)
    recorder = MetricsRecorder.resolve(
        args, rank=stage_id,
        meta={
            "role": f"stage-{stage_id}", "stage": stage_id,
            "stages": cfg.stages, "rejoin": rejoin,
        },
    )
    if recorder.enabled:
        install_stack_dump_handler(recorder.path)
    faults = FaultSchedule.resolve(args, rank=stage_id)
    if faults is not None:
        if rejoin:
            faults = faults.for_rejoin()
        faults.recorder = recorder
    drain = DrainSignal()
    drain.install()

    stage_dir = Path(args.checkpoint_directory) / f"stage-{stage_id}"
    start_step, restored_from = 0, None
    latest = find_latest_checkpoint(stage_dir)
    if latest is not None:
        programs.params, programs.opt_state, meta = load_checkpoint(
            latest, programs.params, programs.opt_state
        )
        start_step, restored_from = int(meta["epoch"]), latest
        log.info(
            f"stage {stage_id}: restored {latest} -> resume step "
            f"{start_step}"
        )
    if rejoin and recorder.enabled:
        recorder.record(
            "stage_restart", stage=stage_id, resume_step=start_step,
            ckpt=str(restored_from or ""),
        )
        recorder.flush()

    M = cfg.microbatches
    window = 2 * M
    act_shape = cfg.act_shape()

    def link_event(kind, **fields):
        if recorder.enabled:
            recorder.record(kind, stage=stage_id, **fields)

    # the downstream listener binds FIRST (construction), so a dialing
    # neighbor - initial start or respawn re-dial - always has a target;
    # then connect upstream, then accept downstream: the chain cascades
    # from stage 0 without deadlock
    down = up = None
    if not programs.is_last:
        down = LinkEnd(
            LinkEnd.HOST, port=cfg.link_port(stage_id, args.master_port),
            window=window, name=f"link{stage_id}:down",
            seed=cfg.seed * 101 + stage_id * 2,
            reconnect_deadline_s=args.link_timeout, on_event=link_event,
        )
        down.recv_next = start_step * M
    if not programs.is_first:
        up = LinkEnd(
            LinkEnd.DIAL, addr=args.master_addr,
            port=cfg.link_port(stage_id - 1, args.master_port),
            window=window, name=f"link{stage_id - 1}:up",
            seed=cfg.seed * 101 + stage_id * 2 + 1,
            reconnect_deadline_s=args.link_timeout, on_event=link_event,
        )
        up.recv_next = start_step * M
        up.connect(initial=not rejoin)
    if down is not None:
        down.connect(initial=not rejoin)

    t_run = time.perf_counter()
    step_loss = None
    try:
        for step in range(start_step, cfg.steps):
            drain.check()
            if faults is not None:
                faults.maybe_kill(step=step)
            t_step = time.perf_counter()
            acc = None
            mb_losses = []
            saved_inputs = []
            features = labels = None
            if programs.is_first or programs.is_last:
                if faults is not None and programs.is_first:
                    faults.on_producer_item(step)
                features, labels = batch_for_step(cfg, step)
            # forward (fill): microbatches flow down in order
            for mb in range(M):
                seq = step * M + mb
                if programs.is_first:
                    x = features[mb]
                else:
                    _, x = up.recv(cfg.input_shape(stage_id))
                if programs.is_last:
                    loss, d_params, d_x = programs.last_step(
                        programs.params, x, labels[mb]
                    )
                    mb_losses.append(float(loss))
                    acc = _tree_add(acc, d_params)
                    if up is not None:
                        up.send(seq, np.asarray(d_x))
                else:
                    saved_inputs.append(x)
                    acts = programs.forward(programs.params, x)
                    down.send(seq, np.asarray(acts))
            # backward (drain): cotangents flow back up in order
            if not programs.is_last:
                for mb in range(M):
                    seq = step * M + mb
                    _, d_out = down.recv(act_shape)
                    d_params, d_x = programs.backward(
                        programs.params, saved_inputs[mb], d_out
                    )
                    acc = _tree_add(acc, d_params)
                    if up is not None:
                        up.send(seq, np.asarray(d_x))
            programs.params, programs.opt_state = programs.update(
                programs.params, programs.opt_state, acc
            )
            step_loss = (
                sum(mb_losses) / len(mb_losses) if mb_losses else None
            )
            # checkpoint BEFORE the next step's sends: a stage therefore
            # never restarts more than one step behind its neighbors,
            # which is exactly what the links' two-step replay window
            # (and the prune below) is sized for
            save_checkpoint(
                stage_dir, epoch=step, params=programs.params,
                opt_state=programs.opt_state, loss=step_loss or 0.0,
            )
            rotate_checkpoints(stage_dir, args.keep_checkpoints)
            for link in (up, down):
                if link is not None:
                    link.prune(step * M)
            if recorder.enabled:
                # deferred emission: tm overridden to the step START
                # (the timeline exporter draws the step span forward
                # from tm; stamping the end would overlap neighbors)
                recorder.record(
                    "step", step=step, loss=step_loss,
                    dispatch_s=time.perf_counter() - t_step, tm=t_step,
                )
                recorder.note_progress(step)
    except DrainRequested:
        log.info(f"stage {stage_id}: drain requested; leaving cleanly")
        if recorder.enabled:
            recorder.record(
                "member_drain", rank_slot=stage_id, stage=stage_id,
            )
            recorder.close()
        for link in (up, down):
            if link is not None:
                link.close()
        raise SystemExit(DRAIN_EXIT_CODE)

    stats = {"replayed": 0, "dup_drops": 0, "reconnects": 0}
    for link in (up, down):
        if link is not None:
            for key in stats:
                stats[key] += link.stats[key]
            link.close()
    result = {
        "stage": stage_id,
        "stages": cfg.stages,
        "steps": cfg.steps,
        "resumed_from_step": start_step,
        "final_loss": step_loss,
        "params_crc": params_crc(programs.params),
        "trace_counts": dict(programs.trace_counts),
        **stats,
    }
    result_path = Path(args.checkpoint_directory) / (
        f"result-stage{stage_id}.json"
    )
    result_path.write_text(json.dumps(result, indent=2) + "\n")
    if recorder.enabled:
        recorder.record(
            "run_summary", duration_s=time.perf_counter() - t_run,
            final_loss=step_loss, trace_counts=dict(programs.trace_counts),
            faults_fired=faults.fired_snapshot() if faults else {},
            **stats,
        )
        recorder.close()
    log.info(f"stage {stage_id}: done ({result})")


def _tree_add(acc, grads):
    import jax

    if acc is None:
        return grads
    return jax.tree.map(lambda a, g: a + g, acc, grads)


# ---------------------------------------------------------------------------
# supervised spawn world


def _spawn_entry(args, stage_id, worker_id=None, rejoin=False):
    # force CPU in spawned children: each stage would otherwise race to
    # claim the single local accelerator (same rule as the PS world)
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")
    del worker_id  # stage-id IS the stable identity
    run_stage(args, stage_id, rejoin=rejoin)


def run(args) -> None:
    """Spawn and supervise the whole pipeline locally (the fake-cluster
    pattern): one process per stage under a :class:`StageSupervisor` -
    a dead stage is respawned into the same stage-id and rejoins by
    re-dialing its fixed link ports."""
    from pytorch_distributed_rnn_tpu.launcher.supervisor import (
        StageSupervisor,
        supervision_alert_hook,
    )
    from pytorch_distributed_rnn_tpu.obs.live import resolve_event_push
    from pytorch_distributed_rnn_tpu.obs.recorder import MetricsRecorder
    from pytorch_distributed_rnn_tpu.resilience.faults import FaultSchedule

    logging.basicConfig(level=args.log)
    cfg = PipelineConfig.from_args(args)
    faults = FaultSchedule.resolve(args)
    if faults is not None:
        # netem-analogue delay/loss must be in the env BEFORE any child
        # builds its link communicators
        faults.export_network()
    # the supervisor's own sidecar rides one rank slot past the stages:
    # respawn/lost/collapse events land there, and the final
    # run_summary marks supervision itself as finished for `health`
    recorder = MetricsRecorder.resolve(
        args, rank=cfg.stages,
        meta={"role": "stage-supervisor", "stages": cfg.stages},
    )

    on_event = supervision_alert_hook(
        recorder=recorder, push=resolve_event_push(args, role="stage-sup"),
    )

    ctx = mp.get_context("spawn")

    def spawn_stage(rank, worker_id, rejoin):
        proc = ctx.Process(
            target=_spawn_entry, args=(args, rank, worker_id, rejoin),
            name=f"mpmd-stage-{rank}",
        )
        proc.start()
        return proc

    supervisor = StageSupervisor(
        spawn_stage, max_respawns=args.max_respawns,
        respawn_delay_s=0.2, on_event=on_event,
    )
    t0 = time.perf_counter()
    supervisor.launch(range(cfg.stages))
    healthy = supervisor.supervise_all()
    supervisor.shutdown()
    verdict = supervisor.verdict()
    log.info(f"stage supervisor verdict: {verdict}")
    if recorder.enabled:
        recorder.record(
            "run_summary", duration_s=time.perf_counter() - t0, **verdict
        )
        recorder.close()
    if not healthy or verdict["failed"]:
        raise SystemExit(
            f"MPMD pipeline failed: supervisor verdict {verdict}"
        )


# ---------------------------------------------------------------------------
# CLI


def build_parser(parser=None):
    import argparse

    if parser is None:
        parser = argparse.ArgumentParser(
            prog="pdrnn-mpmd",
            description=(
                "fault-tolerant MPMD pipeline: one supervised process "
                "+ one compiled program per stage"
            ),
        )
    parser.add_argument("--stages", type=int, default=3)
    parser.add_argument("--layers", type=int, default=4,
                        help="total layers across all stages")
    parser.add_argument("--feature-dim", type=int, default=6)
    parser.add_argument("--hidden-dim", type=int, default=16)
    parser.add_argument("--num-classes", type=int, default=5)
    parser.add_argument("--seq-len", type=int, default=8)
    parser.add_argument("--microbatch-size", type=int, default=4)
    parser.add_argument("--microbatches", type=int, default=2)
    parser.add_argument("--steps", type=int, default=6)
    parser.add_argument("--lr", type=float, default=1e-2)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--master-addr", default="127.0.0.1")
    parser.add_argument("--master-port", type=int, default=29700,
                        help="base port; link k listens on base+k")
    parser.add_argument("--checkpoint-directory", default="mpmd-ckpt",
                        help="per-stage crash-safe checkpoints + results")
    parser.add_argument("--keep-checkpoints", type=int, default=3)
    parser.add_argument("--link-timeout", type=float, default=120.0,
                        help="reconnect deadline budget per link (s)")
    parser.add_argument("--max-respawns", type=int, default=3)
    parser.add_argument("--faults", default=None,
                        help="chaos schedule, e.g. 'step:2:kill@1'")
    parser.add_argument("--metrics", default=None,
                        help="metrics sidecar path (per-stage -r<k>)")
    parser.add_argument("--log", default="INFO")
    return parser


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    run(args)


# ---------------------------------------------------------------------------
# trace-registry provider (lint deep pass)

# abstract pipeline geometry for the deep pass: 3 stages covers all
# three roles (first / middle / last); the rules are shape-generic
_LINT_CFG = PipelineConfig()


def declare_trace_entries(register):
    """MPMD per-stage programs for ``pdrnn-lint --deep``: the non-last
    forward/backward pair, the last stage's fused loss/grad step, and
    the per-stage update - abstract specs, single-device (no mesh),
    exactly the programs :class:`StagePrograms` jits."""
    from pytorch_distributed_rnn_tpu.lint.trace_registry import sds

    def abstract_params(stage: int):
        import jax

        return jax.tree.map(
            lambda a: sds(a.shape, a.dtype),
            init_stage_params(_LINT_CFG, stage),
        )

    def build_forward():
        import jax.numpy as jnp

        return make_forward(_LINT_CFG, 1), (
            abstract_params(1),
            sds(_LINT_CFG.input_shape(1), jnp.float32),
        )

    def build_backward():
        import jax.numpy as jnp

        return make_backward(_LINT_CFG, 1), (
            abstract_params(1),
            sds(_LINT_CFG.input_shape(1), jnp.float32),
            sds(_LINT_CFG.act_shape(), jnp.float32),
        )

    def build_last_step():
        import jax.numpy as jnp

        last = _LINT_CFG.stages - 1
        return make_last_step(_LINT_CFG), (
            abstract_params(last),
            sds(_LINT_CFG.input_shape(last), jnp.float32),
            sds((_LINT_CFG.microbatch_size,), jnp.int32),
        )

    def build_update():
        import jax
        import optax

        params = abstract_params(1)
        optimizer = optax.adam(_LINT_CFG.lr)
        opt_state = jax.eval_shape(optimizer.init, params)
        return make_update(_LINT_CFG, optimizer), (
            params, opt_state, params,
        )

    path = "pytorch_distributed_rnn_tpu/parallel/mpmd.py"
    register(
        name="mpmd.stage_forward", family="mpmd", path=path,
        build=build_forward, kind="forward",
    )
    register(
        name="mpmd.stage_backward", family="mpmd", path=path,
        build=build_backward, kind="train_step",
    )
    register(
        name="mpmd.last_stage_step", family="mpmd", path=path,
        build=build_last_step, kind="train_step",
    )
    register(
        name="mpmd.stage_update", family="mpmd", path=path,
        build=build_update, kind="update",
    )


if __name__ == "__main__":
    main()
