"""Pipeline parallelism: stacked RNN layers partitioned into stages.

The reference model is monolithic (SURVEY.md checklist: "no stage
partitioning", ``/root/reference/src/motion/model.py:4-17``).  This module
adds GPipe-style pipeline parallelism as a first-class axis: a stack of L
RNN layers is split into S contiguous stages over a ``pp`` mesh axis, the
batch is split into M microbatches, and stage ``k`` processes microbatch
``m`` at tick ``t = k + m`` - ``M + S - 1`` ticks total, with activations
hopping stage-to-stage via ``lax.ppermute`` (CollectivePermute over ICI).
Bubble fraction (S-1)/(M+S-1) shrinks as M grows, the classic GPipe
trade-off.  Backward works by differentiating straight through the SPMD
program (ppermute transposes to the reverse hop), giving exact gradients -
the schedule's reverse pass is XLA's transpose of the forward scan.

An RNN pipelines over *depth*, not time: each stage runs its layers over a
microbatch's full sequence, so stage state is just the (B_m, T, width)
activation block.  Layer 0's input width (``in``) differs from every other
layer's (``H``); to keep the stage loop homogeneous for traced layer
indexing, inputs and all ``w_ih`` matrices are zero-padded to
``W = max(in, H)`` - mathematically identical (the padded columns multiply
zeros) and XLA folds the constant-zero columns away.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from jax import shard_map

from pytorch_distributed_rnn_tpu.ops.rnn import gru_step, lstm_step
from pytorch_distributed_rnn_tpu.parallel.collectives import broadcast_from


def _pad_last(x, width: int):
    pad = width - x.shape[-1]
    if pad == 0:
        return x
    cfg = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
    return jnp.pad(x, cfg)


def _stack_padded(layers, width: int, cell: str = "lstm"):
    """Stack per-layer params into (L, ...) arrays, w_ih column-padded to
    ``width`` so traced layer indexing sees homogeneous shapes.  For the
    LSTM both biases fold into the input projection; torch GRU semantics
    put ``b_hh`` inside the n-gate's ``r *`` product, so it stays a
    separate per-layer array and joins inside ``gru_step``."""
    stacked = {
        "w_ih": jnp.stack([_pad_last(p["w_ih"], width) for p in layers]),
        "w_hh_t": jnp.stack([p["w_hh"].T for p in layers]),
    }
    if cell == "gru":
        stacked["b"] = jnp.stack([p["b_ih"] for p in layers])
        stacked["b_hh"] = jnp.stack([p["b_hh"] for p in layers])
    else:
        stacked["b"] = jnp.stack([p["b_ih"] + p["b_hh"] for p in layers])
    return stacked


def _run_layer(stacked, l, acts, *, unroll: int = 1, cell: str = "lstm"):
    """Run layer ``l`` (traced index) over acts (B_m, T, W) -> (B_m, T, H)."""
    w_ih = lax.dynamic_index_in_dim(stacked["w_ih"], l, keepdims=False)
    w_hh_t = lax.dynamic_index_in_dim(stacked["w_hh_t"], l, keepdims=False)
    b = lax.dynamic_index_in_dim(stacked["b"], l, keepdims=False)
    x_proj = jnp.einsum("bti,gi->btg", acts, w_ih) + b
    batch, hidden = acts.shape[0], w_hh_t.shape[0]
    xs = jnp.swapaxes(x_proj, 0, 1)
    if cell == "gru":
        b_hh = lax.dynamic_index_in_dim(stacked["b_hh"], l, keepdims=False)
        h0 = jnp.zeros((batch, hidden), jnp.float32)
        _, out = lax.scan(
            lambda h, xp: gru_step(w_hh_t, b_hh, h, xp),
            h0, xs, unroll=unroll,
        )
    else:
        carry0 = (  # f32 per the lstm_step mixed-precision contract
            jnp.zeros((batch, hidden), jnp.float32),
            jnp.zeros((batch, hidden), jnp.float32),
        )
        _, out = lax.scan(
            lambda c, xp: lstm_step(w_hh_t, c, xp),
            carry0, xs, unroll=unroll,
        )
    return jnp.swapaxes(out, 0, 1)


def _gpipe_schedule(axis: str, x_micro, run_stage, *, hop, out_tail,
                    dtype):
    """The one GPipe tick loop shared by every pipelined family.

    Stage ``k`` processes microbatch ``m`` at tick ``t = k + m``:
    stage 0 reads microbatch ``m`` from ``x_micro`` (M, B_m, T, W_in),
    every other stage consumes what arrived from the previous stage;
    ``run_stage(stage_idx, acts)`` runs the stage's layers; the last
    stage captures its microbatch's output; ``hop(acts)`` shapes the
    activation for the stage-to-stage ``ppermute`` (identity when every
    stage speaks the same width, a pad when layer 0's input width
    differs).  Returns the (M, B_m, T, *out_tail) outputs replicated
    from the last stage.
    """
    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    M = x_micro.shape[0]

    def tick(state, tk):
        buf, outs = state
        m = tk - idx
        active = (m >= 0) & (m < M)
        m_safe = jnp.clip(m, 0, M - 1)
        inp = jnp.where(
            idx == 0,
            lax.dynamic_index_in_dim(x_micro, m_safe, keepdims=False),
            buf,
        )
        acts = run_stage(idx, inp)
        outs = jnp.where(
            (active & (idx == n - 1))
            & (jnp.arange(M)[:, None, None, None] == m_safe),
            acts[None], outs,
        )
        buf = lax.ppermute(hop(acts), axis, perm)
        return (buf, outs), None

    buf0 = jnp.zeros(x_micro.shape[1:], dtype)
    outs0 = jnp.zeros(x_micro.shape[:3] + out_tail, dtype)
    (_, outs), _ = lax.scan(tick, (buf0, outs0), jnp.arange(M + n - 1))
    # outputs live on the last stage; replicate them everywhere
    return broadcast_from(outs, axis, n - 1)


def pp_stacked_rnn(layers, x, axis: str, *, num_microbatches: int,
                   unroll: int = 1, cell: str = "lstm",
                   compute_dtype=None, remat: bool = False):
    """GPipe-scheduled stacked RNN (LSTM or GRU), for use inside
    ``shard_map`` over the ``pp`` axis (params and ``x`` (B, T, in)
    replicated per stage).

    ``L`` layers split into ``axis_size`` contiguous stages (L must divide
    evenly); the batch splits into ``num_microbatches``.  Returns the full
    (B, T, H) last-layer outputs, identical to
    :func:`~pytorch_distributed_rnn_tpu.ops.rnn.stacked_rnn`.
    ``compute_dtype`` moves the stage matmuls AND the stage-to-stage hop
    payloads (ppermute wire bytes) to e.g. bf16; ``lstm_step``/``gru_step``
    keep the per-step carry f32 per their mixed-precision contract.
    ``remat`` checkpoints each (stage, microbatch) tick - the classic
    GPipe activation-recompute trade.
    """
    n = lax.axis_size(axis)
    L = len(layers)
    if L % n != 0:
        raise ValueError(f"{L} layers do not split into {n} stages")
    # The gate count is derivable from the tree (4H for LSTM, 3H for
    # GRU), and a mismatched ``cell`` would split the pre-activations
    # into bogus gates with NO shape error whenever 4 | 3H - so verify
    # rather than trust the caller.
    gates = layers[0]["w_ih"].shape[0] // layers[0]["w_hh"].shape[1]
    expected = {"lstm": 4, "gru": 3}[cell]
    if gates != expected:
        raise ValueError(
            f"cell={cell!r} expects {expected}H-wide gates but the params "
            f"tree carries {gates}H - wrong cell for this tree"
        )
    per_stage = L // n
    M = num_microbatches
    batch, t, in_dim = x.shape
    if batch % M != 0:
        raise ValueError(f"batch {batch} not divisible into {M} microbatches")
    bm = batch // M
    hidden = layers[0]["w_hh"].shape[1]
    width = max(in_dim, hidden)
    dtype = x.dtype

    stacked = _stack_padded(layers, width, cell)
    x_micro = _pad_last(x, width).reshape(M, bm, t, width)
    if compute_dtype is not None:
        stacked = jax.tree.map(lambda p: p.astype(compute_dtype), stacked)
        x_micro = x_micro.astype(compute_dtype)
        dtype = compute_dtype

    def run_stage(stage, acts):
        for j in range(per_stage):
            # every layer consumes width-W input (layer output is H-wide)
            acts = _run_layer(stacked, stage * per_stage + j,
                              _pad_last(acts, width), unroll=unroll,
                              cell=cell)
        return acts

    if remat:
        run_stage = jax.checkpoint(run_stage)

    outs = _gpipe_schedule(
        axis, x_micro, run_stage,
        hop=lambda acts: _pad_last(acts, width),  # hops are W-wide
        out_tail=(hidden,), dtype=dtype,
    )
    return outs.reshape(batch, t, hidden)


# Backwards-compatible name from when the stage runner was LSTM-only.
pp_stacked_lstm = pp_stacked_rnn


def pp_transformer_blocks(blocks, h, axis: str, *, num_heads: int,
                          num_microbatches: int):
    """GPipe-scheduled Transformer encoder blocks, for use inside
    ``shard_map`` over the ``pp`` axis (params and ``h`` (B, T, D)
    replicated per stage) - the attention family's pipeline axis.

    Same tick schedule as :func:`pp_stacked_rnn`, but simpler state:
    every block is D -> D (no layer-0 width mismatch, so no padding),
    and the hop payload is the (B_m, T, D) activation block.  ``L``
    blocks split into ``axis_size`` contiguous stages; embed/positions
    and the pooled head stay with the caller (position-wise and tiny -
    they run replicated).
    """
    from pytorch_distributed_rnn_tpu.models.attention import apply_block

    n = lax.axis_size(axis)
    L = len(blocks)
    if L % n != 0:
        raise ValueError(f"{L} blocks do not split into {n} stages")
    per_stage = L // n
    M = num_microbatches
    batch, t, d = h.shape
    if batch % M != 0:
        raise ValueError(f"batch {batch} not divisible into {M} microbatches")
    bm = batch // M

    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
    h_micro = h.reshape(M, bm, t, d)

    def run_stage(stage, acts):
        for j in range(per_stage):
            p = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(
                    a, stage * per_stage + j, keepdims=False),
                stacked,
            )
            acts = apply_block(p, acts, num_heads)
        return acts

    outs = _gpipe_schedule(
        axis, h_micro, run_stage,
        hop=lambda acts: acts,  # every block is D -> D: no padding
        out_tail=(d,), dtype=h.dtype,
    )
    return outs.reshape(batch, t, d)


def make_pp_forward(mesh, axis: str = "pp", *, num_microbatches: int = 4,
                    unroll: int = 1, cell: str = "lstm"):
    """Jitted pipeline-parallel forward for a MotionModel-shaped params
    tree: staged stacked RNN + last-timestep head (computed replicated -
    it is tiny).  ``x`` replicated in, logits replicated out; numerics
    match ``MotionModel.apply`` exactly.  ``cell`` must match the params
    tree - a GRU tree run as LSTM would split (B, 3H) pre-activations
    into four bogus gates without a shape error whenever 4 | 3H.
    """

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P()),
        out_specs=P(),
        check_vma=False,
    )
    def forward(params, x):
        out = pp_stacked_rnn(
            params["rnn"], x, axis, num_microbatches=num_microbatches,
            unroll=unroll, cell=cell,
        )
        last = out[:, -1, :]
        return last @ params["fc"]["weight"].T + params["fc"]["bias"]

    return jax.jit(forward)
