"""Pipeline parallelism: stacked RNN layers partitioned into stages.

The reference model is monolithic (SURVEY.md checklist: "no stage
partitioning", ``/root/reference/src/motion/model.py:4-17``).  This module
adds GPipe-style pipeline parallelism as a first-class axis: a stack of L
RNN layers is split into S contiguous stages over a ``pp`` mesh axis, the
batch is split into M microbatches, and stage ``k`` processes microbatch
``m`` at tick ``t = k + m`` - ``M + S - 1`` ticks total, with activations
hopping stage-to-stage via ``lax.ppermute`` (CollectivePermute over ICI).
Bubble fraction (S-1)/(M+S-1) shrinks as M grows, the classic GPipe
trade-off.  Backward works by differentiating straight through the SPMD
program (ppermute transposes to the reverse hop), giving exact gradients -
the schedule's reverse pass is XLA's transpose of the forward scan.

An RNN pipelines over *depth*, not time: each stage runs its layers over a
microbatch's full sequence, so stage state is just the (B_m, T, width)
activation block.  Layer 0's input width (``in``) differs from every other
layer's (``H``); to keep the stage loop homogeneous for traced layer
indexing, inputs and all ``w_ih`` matrices are zero-padded to
``W = max(in, H)`` - mathematically identical (the padded columns multiply
zeros) and XLA folds the constant-zero columns away.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from pytorch_distributed_rnn_tpu.utils.compat import shard_map

from pytorch_distributed_rnn_tpu.ops.rnn import gru_step, lstm_step
from pytorch_distributed_rnn_tpu.parallel.collectives import broadcast_from


def _pad_last(x, width: int):
    pad = width - x.shape[-1]
    if pad == 0:
        return x
    cfg = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
    return jnp.pad(x, cfg)


def _stack_padded(layers, width: int, cell: str = "lstm"):
    """Stack per-layer params into (L, ...) arrays, w_ih column-padded to
    ``width`` so traced layer indexing sees homogeneous shapes.  For the
    LSTM both biases fold into the input projection; torch GRU semantics
    put ``b_hh`` inside the n-gate's ``r *`` product, so it stays a
    separate per-layer array and joins inside ``gru_step``."""
    stacked = {
        "w_ih": jnp.stack([_pad_last(p["w_ih"], width) for p in layers]),
        "w_hh_t": jnp.stack([p["w_hh"].T for p in layers]),
    }
    if cell == "gru":
        stacked["b"] = jnp.stack([p["b_ih"] for p in layers])
        stacked["b_hh"] = jnp.stack([p["b_hh"] for p in layers])
    else:
        stacked["b"] = jnp.stack([p["b_ih"] + p["b_hh"] for p in layers])
    return stacked


def _run_layer(stacked, l, acts, *, unroll: int = 1, cell: str = "lstm"):
    """Run layer ``l`` (traced index) over acts (B_m, T, W) -> (B_m, T, H)."""
    w_ih = lax.dynamic_index_in_dim(stacked["w_ih"], l, keepdims=False)
    w_hh_t = lax.dynamic_index_in_dim(stacked["w_hh_t"], l, keepdims=False)
    b = lax.dynamic_index_in_dim(stacked["b"], l, keepdims=False)
    x_proj = jnp.einsum("bti,gi->btg", acts, w_ih) + b
    batch, hidden = acts.shape[0], w_hh_t.shape[0]
    xs = jnp.swapaxes(x_proj, 0, 1)
    if cell == "gru":
        b_hh = lax.dynamic_index_in_dim(stacked["b_hh"], l, keepdims=False)
        h0 = jnp.zeros((batch, hidden), jnp.float32)
        _, out = lax.scan(
            lambda h, xp: gru_step(w_hh_t, b_hh, h, xp),
            h0, xs, unroll=unroll,
        )
    else:
        carry0 = (  # f32 per the lstm_step mixed-precision contract
            jnp.zeros((batch, hidden), jnp.float32),
            jnp.zeros((batch, hidden), jnp.float32),
        )
        _, out = lax.scan(
            lambda c, xp: lstm_step(w_hh_t, c, xp),
            carry0, xs, unroll=unroll,
        )
    return jnp.swapaxes(out, 0, 1)


def _gpipe_schedule(axis: str, x_micro, run_stage, *, hop, out_tail,
                    dtype):
    """The one GPipe tick loop shared by every pipelined family.

    Stage ``k`` processes microbatch ``m`` at tick ``t = k + m``:
    stage 0 reads microbatch ``m`` from ``x_micro`` (M, B_m, T, W_in),
    every other stage consumes what arrived from the previous stage;
    ``run_stage(stage_idx, acts)`` runs the stage's layers; the last
    stage captures its microbatch's output; ``hop(acts)`` shapes the
    activation for the stage-to-stage ``ppermute`` (identity when every
    stage speaks the same width, a pad when layer 0's input width
    differs).  Returns the (M, B_m, T, *out_tail) outputs replicated
    from the last stage.
    """
    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    M = x_micro.shape[0]

    def tick(state, tk):
        buf, outs = state
        m = tk - idx
        active = (m >= 0) & (m < M)
        m_safe = jnp.clip(m, 0, M - 1)
        inp = jnp.where(
            idx == 0,
            lax.dynamic_index_in_dim(x_micro, m_safe, keepdims=False),
            buf,
        )
        acts = run_stage(idx, inp)
        outs = jnp.where(
            (active & (idx == n - 1))
            & (jnp.arange(M)[:, None, None, None] == m_safe),
            acts[None], outs,
        )
        buf = lax.ppermute(hop(acts), axis, perm)
        return (buf, outs), None

    buf0 = jnp.zeros(x_micro.shape[1:], dtype)
    outs0 = jnp.zeros(x_micro.shape[:3] + out_tail, dtype)
    (_, outs), _ = lax.scan(tick, (buf0, outs0), jnp.arange(M + n - 1))
    # outputs live on the last stage; replicate them everywhere
    return broadcast_from(outs, axis, n - 1)


def pp_stacked_rnn(layers, x, axis: str, *, num_microbatches: int,
                   unroll: int = 1, cell: str = "lstm",
                   compute_dtype=None, remat: bool = False):
    """GPipe-scheduled stacked RNN (LSTM or GRU), for use inside
    ``shard_map`` over the ``pp`` axis (params and ``x`` (B, T, in)
    replicated per stage).

    ``L`` layers split into ``axis_size`` contiguous stages (L must divide
    evenly); the batch splits into ``num_microbatches``.  Returns the full
    (B, T, H) last-layer outputs, identical to
    :func:`~pytorch_distributed_rnn_tpu.ops.rnn.stacked_rnn`.
    ``compute_dtype`` moves the stage matmuls AND the stage-to-stage hop
    payloads (ppermute wire bytes) to e.g. bf16; ``lstm_step``/``gru_step``
    keep the per-step carry f32 per their mixed-precision contract.
    ``remat`` checkpoints each (stage, microbatch) tick - the classic
    GPipe activation-recompute trade.
    """
    n = lax.axis_size(axis)
    L = len(layers)
    if L % n != 0:
        raise ValueError(f"{L} layers do not split into {n} stages")
    # The gate count is derivable from the tree (4H for LSTM, 3H for
    # GRU), and a mismatched ``cell`` would split the pre-activations
    # into bogus gates with NO shape error whenever 4 | 3H - so verify
    # rather than trust the caller.
    gates = layers[0]["w_ih"].shape[0] // layers[0]["w_hh"].shape[1]
    expected = {"lstm": 4, "gru": 3}[cell]
    if gates != expected:
        raise ValueError(
            f"cell={cell!r} expects {expected}H-wide gates but the params "
            f"tree carries {gates}H - wrong cell for this tree"
        )
    per_stage = L // n
    M = num_microbatches
    batch, t, in_dim = x.shape
    if batch % M != 0:
        raise ValueError(f"batch {batch} not divisible into {M} microbatches")
    bm = batch // M
    hidden = layers[0]["w_hh"].shape[1]
    width = max(in_dim, hidden)
    dtype = x.dtype

    stacked = _stack_padded(layers, width, cell)
    x_micro = _pad_last(x, width).reshape(M, bm, t, width)
    if compute_dtype is not None:
        stacked = jax.tree.map(lambda p: p.astype(compute_dtype), stacked)
        x_micro = x_micro.astype(compute_dtype)
        dtype = compute_dtype

    def run_stage(stage, acts):
        for j in range(per_stage):
            # every layer consumes width-W input (layer output is H-wide)
            acts = _run_layer(stacked, stage * per_stage + j,
                              _pad_last(acts, width), unroll=unroll,
                              cell=cell)
        return acts

    if remat:
        run_stage = jax.checkpoint(run_stage)

    outs = _gpipe_schedule(
        axis, x_micro, run_stage,
        hop=lambda acts: _pad_last(acts, width),  # hops are W-wide
        out_tail=(hidden,), dtype=dtype,
    )
    return outs.reshape(batch, t, hidden)


# Backwards-compatible name from when the stage runner was LSTM-only.
pp_stacked_lstm = pp_stacked_rnn


def pp_transformer_blocks(blocks, h, axis: str, *, num_heads: int,
                          num_microbatches: int, compute_dtype=None,
                          remat: bool = False, tp_axis: str | None = None,
                          impl: str = "dense"):
    """GPipe-scheduled Transformer encoder blocks, for use inside
    ``shard_map`` over the ``pp`` axis (params and ``h`` (B, T, D)
    replicated per stage) - the attention family's pipeline axis.

    Same tick schedule as :func:`pp_stacked_rnn`, but simpler state:
    every block is D -> D (no layer-0 width mismatch, so no padding),
    and the hop payload is the (B_m, T, D) activation block.  ``L``
    blocks split into ``axis_size`` contiguous stages; embed/positions
    and the pooled head stay with the caller (position-wise and tiny -
    they run replicated).

    ``tp_axis`` composes Megatron head/MLP sharding INSIDE each stage
    (``parallel/combined.py:tp_sp_block`` with no sequence axis): each
    (pp stage, tp shard) cell computes its head group + MLP slice, the
    two per-block psums ride the tp axis, and the stage hop payload
    stays the full (B_m, T, D) activation.  ``impl`` picks each block's
    attention inner (``dense`` XLA or the fused ``flash`` Pallas kernel)
    - the caller resolves the model's ``auto``.
    """
    from pytorch_distributed_rnn_tpu.models.attention import apply_block

    attention_inner = None
    if impl == "flash" and tp_axis is None:
        # the tp path dispatches flash inside tp_sp_block itself
        from pytorch_distributed_rnn_tpu.ops.pallas_attention import (
            flash_attention,
        )

        attention_inner = flash_attention

    n = lax.axis_size(axis)
    L = len(blocks)
    if L % n != 0:
        raise ValueError(f"{L} blocks do not split into {n} stages")
    per_stage = L // n
    M = num_microbatches
    batch, t, d = h.shape
    if batch % M != 0:
        raise ValueError(f"batch {batch} not divisible into {M} microbatches")
    bm = batch // M

    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
    h_micro = h.reshape(M, bm, t, d)
    dtype = h.dtype
    if compute_dtype is not None:
        # bf16 stage blocks + hop payloads; layernorm stats stay f32
        # inside _layer_norm (models/attention.py)
        stacked = jax.tree.map(lambda p: p.astype(compute_dtype), stacked)
        h_micro = h_micro.astype(compute_dtype)
        dtype = compute_dtype

    if tp_axis is not None:
        from pytorch_distributed_rnn_tpu.parallel.combined import (
            tp_sp_block,
        )

    def run_stage(stage, acts):
        for j in range(per_stage):
            p = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(
                    a, stage * per_stage + j, keepdims=False),
                stacked,
            )
            if tp_axis is not None:
                acts = tp_sp_block(p, acts, num_heads, sp_axis=None,
                                   tp_axis=tp_axis, impl=impl)
            else:
                acts = apply_block(p, acts, num_heads,
                                   attention=attention_inner)
        return acts

    if remat:
        run_stage = jax.checkpoint(run_stage)

    outs = _gpipe_schedule(
        axis, h_micro, run_stage,
        hop=lambda acts: acts,  # every block is D -> D: no padding
        out_tail=(d,), dtype=dtype,
    )
    return outs.reshape(batch, t, d)


# ---------------------------------------------------------------------------
# 1F1B (PipeDream-flush) schedule
# ---------------------------------------------------------------------------


def simulate_1f1b_schedule(num_stages: int, num_microbatches: int):
    """Greedy event simulation of the non-interleaved 1F1B timetable.

    Each stage performs ONE op per tick - forward or backward of one
    microbatch - under the real dataflow constraints: a forward needs the
    upstream activation to have arrived (capacity-1 buffer, so the sender
    also waits until the receiver has consumed the previous one), a
    backward needs the downstream cotangent, and a stage may run at most
    ``num_stages - stage`` forwards ahead of its backwards (the 1F1B
    in-flight bound).  Backward is preferred when both are ready - that
    preference is what turns GPipe's fill-drain into the 1F1B rhythm.

    Returns ``(fwd_sched, bwd_sched)`` as (ticks, stages) numpy arrays of
    microbatch ids (-1 = idle slot for that op kind).
    """
    import numpy as np

    S, M = num_stages, num_microbatches
    next_f = [0] * S
    next_b = [0] * S
    f_done = [[-1] * M for _ in range(S)]
    b_done = [[-1] * M for _ in range(S)]
    # fwd_buf[s] = microbatch whose activation sits unconsumed at stage s
    fwd_buf = [-1] * S
    bwd_buf = [-1] * S
    fwd_sched, bwd_sched = [], []
    t = 0
    while any(nb < M for nb in next_b):
        if t > 4 * (M + S):  # safety: the greedy schedule must terminate
            raise RuntimeError("1f1b schedule simulation did not converge")
        frow, brow = [-1] * S, [-1] * S
        consumed_f, consumed_b, sent_f, sent_b = [], [], [], []
        for s in range(S):
            mb = next_b[s]
            bwd_ready = (
                mb < M
                and 0 <= f_done[s][mb] < t
                and (s == S - 1 or (0 <= b_done[s + 1][mb] < t
                                    and bwd_buf[s] == mb))
                and (s == 0 or bwd_buf[s - 1] == -1)  # room to send dacts
            )
            mf = next_f[s]
            fwd_ready = (
                mf < M
                and (s == 0 or (0 <= f_done[s - 1][mf] < t
                                and fwd_buf[s] == mf))
                and (s == S - 1 or fwd_buf[s + 1] == -1)  # room to send
                and next_f[s] - next_b[s] < S - s  # 1F1B in-flight bound
            )
            if bwd_ready:
                brow[s] = mb
                b_done[s][mb] = t
                next_b[s] += 1
                if s > 0:
                    sent_b.append((s - 1, mb))
                if s < S - 1:
                    consumed_b.append(s)
            elif fwd_ready:
                frow[s] = mf
                f_done[s][mf] = t
                next_f[s] += 1
                if s < S - 1:
                    sent_f.append((s + 1, mf))
                if s > 0:
                    consumed_f.append(s)
        for s in consumed_f:
            fwd_buf[s] = -1
        for s in consumed_b:
            bwd_buf[s] = -1
        for s, m in sent_f:
            assert fwd_buf[s] == -1, "activation buffer overwrite"
            fwd_buf[s] = m
        for s, m in sent_b:
            assert bwd_buf[s] == -1, "cotangent buffer overwrite"
            bwd_buf[s] = m
        fwd_sched.append(frow)
        bwd_sched.append(brow)
        t += 1
    return np.asarray(fwd_sched), np.asarray(bwd_sched)


def simulate_interleaved_1f1b_schedule(num_devices: int, num_chunks: int,
                                       num_microbatches: int):
    """Greedy event simulation of the INTERLEAVED (virtual-stage) 1F1B
    timetable (Megatron-LM's interleaved schedule, arXiv:2104.04473).

    Each of the ``S`` devices owns ``V`` model chunks placed round-robin:
    global stage ``g`` (of ``G = S*V``) lives on device ``g % S`` as its
    chunk ``g // S``.  Round-robin placement makes EVERY stage-to-stage
    hop a uniform +1 ring permute (chunk boundaries wrap device S-1 ->
    device 0), so the executing engine keeps the plain ``ppermute`` wire
    of the non-interleaved schedule.  Constraints per tick: one op
    (forward or backward of one (stage, microbatch)) per DEVICE, under
    the same dataflow rules as :func:`simulate_1f1b_schedule` -
    capacity-1 per-stage receive buffers, backward preferred (deepest
    ready chunk first, which drains the pipe), forwards ALSO deepest
    ready chunk first (pushing each microbatch toward the loss as fast
    as possible unblocks backwards sooner - measured: S=4 M=8 slot
    bubble 0.27 (V=1) -> 0.24 (V=2) -> 0.18 (V=4); shallow-first
    inverts the trend), and stage ``g`` may run at most ``G - g``
    forwards ahead of its backwards (the V=1 bound ``S - s``,
    generalized; the schedule's measured max in-flight sizes the
    engine's stash).

    Returns ``(fwd_mb, fwd_chunk, bwd_mb, bwd_chunk, max_inflight)``:
    (ticks, devices) arrays of microbatch ids / chunk ids (-1 = idle)
    plus the max forward-ahead count of any stage (stash bound).
    ``V=1`` reproduces :func:`simulate_1f1b_schedule`'s timetable.
    """
    import numpy as np

    S, V, M = num_devices, num_chunks, num_microbatches
    G = S * V
    next_f = [0] * G
    next_b = [0] * G
    f_done = [[-1] * M for _ in range(G)]
    b_done = [[-1] * M for _ in range(G)]
    fwd_buf = [-1] * G  # mb whose activation waits unconsumed at stage g
    bwd_buf = [-1] * G  # mb whose cotangent waits unconsumed at stage g
    fwd_mb, fwd_ck, bwd_mb, bwd_ck = [], [], [], []
    max_inflight = 1
    t = 0
    while any(nb < M for nb in next_b):
        if t > 8 * (V * M + G):  # safety: greedy must terminate
            raise RuntimeError(
                "interleaved 1f1b schedule simulation did not converge"
            )
        f_mb_row, f_ck_row = [-1] * S, [-1] * S
        b_mb_row, b_ck_row = [-1] * S, [-1] * S
        consumed_f, consumed_b, sent_f, sent_b = [], [], [], []
        for d in range(S):
            bwd_g = -1
            for c in reversed(range(V)):  # deepest chunk drains first
                g = c * S + d
                mb = next_b[g]
                if (
                    mb < M
                    and 0 <= f_done[g][mb] < t
                    and (g == G - 1 or (0 <= b_done[g + 1][mb] < t
                                        and bwd_buf[g] == mb))
                    and (g == 0 or bwd_buf[g - 1] == -1)  # room to send
                ):
                    bwd_g = g
                    break
            fwd_g = -1
            for c in reversed(range(V)):  # deepest ready chunk first
                g = c * S + d
                mf = next_f[g]
                if (
                    mf < M
                    and (g == 0 or (0 <= f_done[g - 1][mf] < t
                                    and fwd_buf[g] == mf))
                    and (g == G - 1 or fwd_buf[g + 1] == -1)  # room
                    and next_f[g] - next_b[g] < G - g  # in-flight bound
                ):
                    fwd_g = g
                    break
            if bwd_g >= 0:
                g, mb = bwd_g, next_b[bwd_g]
                b_mb_row[d], b_ck_row[d] = mb, g // S
                b_done[g][mb] = t
                next_b[g] += 1
                if g > 0:
                    sent_b.append((g - 1, mb))
                if g < G - 1:
                    consumed_b.append(g)
            elif fwd_g >= 0:
                g, mf = fwd_g, next_f[fwd_g]
                f_mb_row[d], f_ck_row[d] = mf, g // S
                f_done[g][mf] = t
                next_f[g] += 1
                max_inflight = max(max_inflight, next_f[g] - next_b[g])
                if g < G - 1:
                    sent_f.append((g + 1, mf))
                if g > 0:
                    consumed_f.append(g)
        for g in consumed_f:
            fwd_buf[g] = -1
        for g in consumed_b:
            bwd_buf[g] = -1
        for g, m in sent_f:
            assert fwd_buf[g] == -1, "activation buffer overwrite"
            fwd_buf[g] = m
        for g, m in sent_b:
            assert bwd_buf[g] == -1, "cotangent buffer overwrite"
            bwd_buf[g] = m
        fwd_mb.append(f_mb_row)
        fwd_ck.append(f_ck_row)
        bwd_mb.append(b_mb_row)
        bwd_ck.append(b_ck_row)
        t += 1
    return (np.asarray(fwd_mb), np.asarray(fwd_ck),
            np.asarray(bwd_mb), np.asarray(bwd_ck), max_inflight)


def pp_schedule_stats(num_stages: int, num_microbatches: int,
                      schedule: str = "gpipe", num_chunks: int = 1) -> dict:
    """Tick/bubble accounting for a pipeline schedule.

    ``gpipe``: the forward fill-drain loop (M + S - 1 ticks; its backward
    is XLA's transpose with the mirrored bubble).  ``1f1b``: ticks and
    idle slots measured from the simulated timetable (one F or B op per
    stage per tick).  ``interleaved`` (``num_chunks`` V > 1): the
    virtual-stage timetable; note a tick's op covers 1/V of a device's
    layers, so busy slots scale with V while warmup idle does not - the
    bubble FRACTION is what shrinks.  ``bubble_fraction`` = idle
    device-ticks / total device-ticks.
    """
    S, M, V = num_stages, num_microbatches, num_chunks
    if schedule != "interleaved" and V != 1:
        raise ValueError(
            f"num_chunks {V} only applies to schedule='interleaved'"
        )
    if schedule == "gpipe":
        ticks = M + S - 1
        busy = S * M
    elif schedule == "1f1b":
        fwd, bwd = simulate_1f1b_schedule(S, M)
        ticks = fwd.shape[0]
        busy = int((fwd >= 0).sum() + (bwd >= 0).sum())
    elif schedule == "interleaved":
        fwd_mb, _, bwd_mb, _, _ = simulate_interleaved_1f1b_schedule(
            S, V, M)
        ticks = fwd_mb.shape[0]
        busy = int((fwd_mb >= 0).sum() + (bwd_mb >= 0).sum())
    else:
        raise ValueError(f"unknown pp schedule {schedule!r}")
    total = S * ticks
    return {
        "schedule": schedule,
        "stages": S,
        "chunks": V,
        "microbatches": M,
        "ticks": ticks,
        "busy_slots": busy,
        "idle_slots": total - busy,
        "bubble_fraction": round((total - busy) / total, 4),
    }


def _pp_interleaved_engine(axis: str, *, num_microbatches: int,
                           num_chunks: int, diff_params, stage0_input,
                           stage_apply, last_loss, bm: int, t_len: int,
                           width: int, hidden: int, dtype):
    """The generic self-differentiating 1F1B tick loop shared by the
    motion and char families - flat (``num_chunks=1``, the PipeDream-
    flush timetable) and INTERLEAVED (virtual stages) in one engine.

    Runs the combined forward+backward timetable explicitly: each tick a
    device performs (masked SPMD) its scheduled forward - stashing the
    stage INPUT, the only activation kept per in-flight microbatch -
    and/or its scheduled backward, which recomputes the stage via
    ``jax.vjp`` at the stashed input and chains the cotangent upstream.
    Activation memory is bounded by the schedule's measured in-flight
    limit instead of GPipe's all-M.

    Each device owns ``num_chunks`` model chunks placed round-robin
    (global stage ``g = chunk * S + device``), so every forward hop is
    the same +1 ring ``ppermute`` and every backward hop -1 - chunk
    boundaries wrap device S-1 -> 0 on the same wire.  Per-chunk state:
    capacity-1 receive buffers and a stash ring of in-flight microbatch
    INPUTS per chunk; the chunk id of each tick's op rides in from the
    precomputed timetable (``num_chunks=1`` reproduces the flat
    timetable exactly - pinned by ``test_v1_reproduces_flat_timetable``).

    - ``diff_params``: pytree (tuple) of everything differentiated.
    - ``stage0_input(diff_params, m) -> (bm, t_len, width)``: microbatch
      ``m``'s entry activation.  It re-evaluates INSIDE the vjp so params
      feeding the entry (the char embedding) get exact gradients.
    - ``stage_apply(diff_params, acts, chunk) -> (bm, t_len, hidden)``:
      the device's ``chunk``-th layer block (traced chunk index).
    - ``last_loss(diff_params, acts, m) -> (loss_sum, correct, w_sum)``:
      the last stage's head + loss for microbatch ``m`` (weighted sums);
      fires on the global last stage (device S-1, chunk V-1) only, as
      ``stage0_input`` fires on (device 0, chunk 0) only.

    Returns ``(loss_sum, correct_sum, w_sum, grads)`` - sums banked at
    the last stage and replicated over ``pp``; ``grads`` mirrors
    ``diff_params`` and contains THIS DEVICE's contribution only (the
    caller's ``custom_vjp`` hands it to shard_map's replicated-param
    transpose, which sums over the mesh).
    """
    import numpy as np

    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    M, V = num_microbatches, num_chunks
    G = n * V

    fwd_mb_np, fwd_ck_np, bwd_mb_np, bwd_ck_np, max_if = (
        simulate_interleaved_1f1b_schedule(n, V, M))
    TT = fwd_mb_np.shape[0]
    K = min(max_if, M)  # per-chunk stash ring size

    # receive tags: device d's +1-wire carries an activation when device
    # d-1 (ring) ran a forward whose stage has a successor; the receiving
    # chunk is (sender_g + 1) // S.  Chunk-boundary sends wrap the ring
    # (device S-1's chunk-c output lands on device 0 as chunk c+1), so
    # np.roll keeps its wrap - the global-last-stage mask already
    # excludes the one send that must not happen.
    devs = np.arange(n)[None, :]
    g_send_f = fwd_ck_np * n + devs
    f_sends = (fwd_mb_np >= 0) & (g_send_f < G - 1)
    recv_f_np = np.roll(f_sends, 1, axis=1)
    recv_f_ck_np = np.roll((g_send_f + 1) // n, 1, axis=1)
    g_send_b = bwd_ck_np * n + devs
    b_sends = (bwd_mb_np >= 0) & (g_send_b > 0)
    recv_b_np = np.roll(b_sends, -1, axis=1)
    recv_b_ck_np = np.roll(
        np.maximum(g_send_b - 1, 0) // n, -1, axis=1)

    fwd_mb = jnp.asarray(fwd_mb_np)
    fwd_ck = jnp.asarray(fwd_ck_np)
    bwd_mb = jnp.asarray(bwd_mb_np)
    bwd_ck = jnp.asarray(bwd_ck_np)
    recv_f = jnp.asarray(recv_f_np)
    recv_f_ck = jnp.asarray(recv_f_ck_np)
    recv_b = jnp.asarray(recv_b_np)
    recv_b_ck = jnp.asarray(recv_b_ck_np)

    def full(dp, a, m, c):
        is_first_g = (idx == 0) & (c == 0)
        is_last_g = (idx == n - 1) & (c == V - 1)
        inp = lax.cond(is_first_g, lambda: stage0_input(dp, m), lambda: a)
        acts = stage_apply(dp, inp, c)
        loss_m = lax.cond(
            is_last_g,
            lambda: last_loss(dp, acts, m)[0],
            lambda: jnp.float32(0.0),
        )
        return acts, loss_m

    def tick(carry, tk):
        (fwd_buf, bwd_buf, stash, grads, loss_sum, correct_sum,
         w_sum) = carry
        m_f = fwd_mb[tk, idx]
        c_f = jnp.clip(fwd_ck[tk, idx], 0, V - 1)
        m_b = bwd_mb[tk, idx]
        c_b = jnp.clip(bwd_ck[tk, idx], 0, V - 1)
        f_active = m_f >= 0
        b_active = m_b >= 0
        m_f_safe = jnp.clip(m_f, 0, M - 1)
        m_b_safe = jnp.clip(m_b, 0, M - 1)

        # ---- backward op: read the stash BEFORE the forward writes it.
        # The whole op sits under lax.cond so a tick with no scheduled
        # backward skips the vjp's recompute-forward + backward entirely
        # (~2/3 of a busy tick's compute; warmup/drain ticks are the
        # bubble).  Per-device divergent conds are legal here because
        # the branches hold NO collectives - stage_apply / stage0_input /
        # last_loss are device-local, and the ppermute hops stay outside.
        is_last_b = (idx == n - 1) & (c_b == V - 1)

        def do_bwd():
            stash_in = lax.dynamic_index_in_dim(
                lax.dynamic_index_in_dim(stash, c_b, keepdims=False),
                m_b_safe % K, keepdims=False)
            buf_b = lax.dynamic_index_in_dim(bwd_buf, c_b,
                                             keepdims=False)
            (_, _), vjp_fn = jax.vjp(
                lambda dp, a: full(dp, a, m_b_safe, c_b), diff_params,
                stash_in,
            )
            cot_acts = (jnp.where(is_last_b, 0.0, 1.0)
                        * buf_b[..., :hidden])
            cot_loss = jnp.where(is_last_b, 1.0, 0.0)
            d_params, d_acts = vjp_fn((cot_acts.astype(dtype), cot_loss))
            return (
                jax.tree.map(lambda d: d.astype(jnp.float32), d_params),
                d_acts,
            )

        def skip_bwd():
            # statically-known shape: no stash/buffer gather on idle ticks
            return (zeros_f32(diff_params),
                    jnp.zeros((bm, t_len, width), dtype))

        d_params, d_acts = lax.cond(b_active, do_bwd, skip_bwd)
        grads = jax.tree.map(jnp.add, grads, d_params)

        # ---- forward op
        is_first_f = (idx == 0) & (c_f == 0)
        is_last_f = (idx == n - 1) & (c_f == V - 1)
        inp = lax.cond(
            is_first_f,
            lambda: stage0_input(diff_params, m_f_safe),
            lambda: lax.dynamic_index_in_dim(fwd_buf, c_f,
                                             keepdims=False),
        )
        stash = jnp.where(
            f_active,
            lax.dynamic_update_slice(
                stash, inp[None, None].astype(stash.dtype),
                (c_f, m_f_safe % K, 0, 0, 0)),
            stash,
        )
        acts = stage_apply(diff_params, inp, c_f)
        loss_m, correct_m, wsum_m = lax.cond(
            is_last_f,
            lambda: last_loss(diff_params, acts, m_f_safe),
            lambda: (jnp.float32(0.0), jnp.float32(0.0),
                     jnp.float32(0.0)),
        )
        bank = (f_active & is_last_f).astype(jnp.float32)
        loss_sum = loss_sum + bank * loss_m
        correct_sum = correct_sum + bank * correct_m
        w_sum = w_sum + bank * wsum_m

        # ---- communicate (one +1 act hop, one -1 cotangent hop)
        perm_f = [(i, (i + 1) % n) for i in range(n)]
        perm_b = [(i, (i - 1) % n) for i in range(n)]
        acts_hop = lax.ppermute(_pad_last(acts, width), axis, perm_f)
        dacts_hop = lax.ppermute(d_acts, axis, perm_b)
        fwd_buf = jnp.where(
            recv_f[tk, idx],
            lax.dynamic_update_slice(
                fwd_buf, acts_hop[None].astype(fwd_buf.dtype),
                (recv_f_ck[tk, idx], 0, 0, 0)),
            fwd_buf,
        )
        bwd_buf = jnp.where(
            recv_b[tk, idx],
            lax.dynamic_update_slice(
                bwd_buf,
                dacts_hop.astype(jnp.float32)[None, ..., :width],
                (recv_b_ck[tk, idx], 0, 0, 0)),
            bwd_buf,
        )
        return (fwd_buf, bwd_buf, stash, grads, loss_sum, correct_sum,
                w_sum), None

    zeros_f32 = lambda t_: jax.tree.map(  # noqa: E731
        lambda p: jnp.zeros(p.shape, jnp.float32), t_)
    carry0 = (
        jnp.zeros((V, bm, t_len, width), dtype),
        jnp.zeros((V, bm, t_len, width), jnp.float32),
        jnp.zeros((V, K, bm, t_len, width), dtype),
        zeros_f32(diff_params),
        jnp.float32(0.0),
        jnp.float32(0.0),
        jnp.float32(0.0),
    )
    (_, _, _, grads, loss_sum, correct_sum, w_sum), _ = lax.scan(
        tick, carry0, jnp.arange(TT)
    )

    loss_sum = broadcast_from(loss_sum, axis, n - 1)
    correct_sum = broadcast_from(correct_sum, axis, n - 1)
    w_sum = broadcast_from(w_sum, axis, n - 1)
    return loss_sum, correct_sum, w_sum, grads


def _check_1f1b_shapes(layers, axis, num_microbatches, batch, cell,
                       num_chunks: int = 1):
    n = lax.axis_size(axis)
    L = len(layers)
    if num_chunks < 1:
        raise ValueError(
            f"num_chunks must be >= 1, got {num_chunks} (1 = plain 1F1B, "
            ">1 = interleaved virtual stages)"
        )
    if L % (n * num_chunks) != 0:
        raise ValueError(
            f"{L} layers do not split into {n} devices x {num_chunks} "
            "chunks"
        )
    # same guard as pp_stacked_rnn: a mismatched ``cell`` would split the
    # pre-activations into bogus gates with NO shape error whenever the
    # gate widths divide evenly
    gates = layers[0]["w_ih"].shape[0] // layers[0]["w_hh"].shape[1]
    expected = {"lstm": 4, "gru": 3}[cell]
    if gates != expected:
        raise ValueError(
            f"cell={cell!r} expects {expected}H-wide gates but the params "
            f"tree carries {gates}H - wrong cell for this tree"
        )
    if batch % num_microbatches != 0:
        raise ValueError(
            f"batch {batch} not divisible into {num_microbatches} "
            f"microbatches"
        )
    return n, L // (n * num_chunks)


def _stage_layers(stk, idx, per_stage, acts, *, width, unroll, cell):
    """This stage's slice of the layer stack - the one stage_apply body
    shared by the motion and char 1F1B wrappers."""
    for j in range(per_stage):
        acts = _run_layer(stk, idx * per_stage + j,
                          _pad_last(acts, width), unroll=unroll,
                          cell=cell)
    return acts


def pp_rnn_1f1b_value_and_grad(layers, head, x, y, axis: str, *,
                               num_microbatches: int, num_chunks: int = 1,
                               unroll: int = 1,
                               cell: str = "lstm", compute_dtype=None,
                               sample_weights=None):
    """Self-differentiating 1F1B pipeline for the motion family, for use
    inside ``shard_map`` over the ``pp`` axis (the
    :func:`_pp_interleaved_engine` timetable with the last-step classification
    head).

    Returns ``(loss_sum, correct_sum, w_sum, grads)``: the weighted NLL
    sum, correct-count and weight total (all banked at the last stage and
    replicated over ``pp`` - divide loss/grads by ``w_sum`` for mean
    semantics), and ``grads``, a params-tree cotangent for ``{"rnn":
    layers, "fc": head}`` containing THIS STAGE's contribution only.
    ``sample_weights`` (B,) marks padded rows of a partial batch (the
    weighted trainer path).
    """
    M = num_microbatches
    idx = lax.axis_index(axis)
    n_dev = lax.axis_size(axis)
    batch, t, in_dim = x.shape
    _, per_stage = _check_1f1b_shapes(layers, axis, M, batch, cell,
                                      num_chunks)
    bm = batch // M
    hidden = layers[0]["w_hh"].shape[1]
    width = max(in_dim, hidden)

    stacked = _stack_padded(layers, width, cell)
    x_micro = _pad_last(x, width).reshape(M, bm, t, width)
    y_micro = y.reshape(M, bm)
    w_micro = (jnp.ones((M, bm), jnp.float32) if sample_weights is None
               else sample_weights.reshape(M, bm).astype(jnp.float32))
    if compute_dtype is not None:
        stacked = jax.tree.map(lambda p: p.astype(compute_dtype), stacked)
        x_micro = x_micro.astype(compute_dtype)
    dtype = x_micro.dtype

    def stage0_input(dp, m):
        return lax.dynamic_index_in_dim(x_micro, m, keepdims=False)

    def stage_apply_chunk(dp, acts, c):
        # global stage c*S + idx owns layers [g*per_stage, (g+1)*per_stage)
        return _stage_layers(dp[0], c * n_dev + idx, per_stage, acts,
                             width=width, unroll=unroll, cell=cell)

    def last_loss(dp, acts, m):
        _, hd = dp
        y_m = lax.dynamic_index_in_dim(y_micro, m, keepdims=False)
        w_m = lax.dynamic_index_in_dim(w_micro, m, keepdims=False)
        logits = (acts[:, -1, :].astype(jnp.float32)
                  @ hd["weight"].T + hd["bias"])
        nll = -jax.nn.log_softmax(logits)[jnp.arange(bm), y_m]
        # f32 so both lax.cond branches in the engine agree on dtypes
        correct = jnp.sum(
            (jnp.argmax(logits, axis=1) == y_m).astype(jnp.float32)
            * (w_m > 0)
        )
        return jnp.sum(nll * w_m), correct, jnp.sum(w_m)

    loss_sum, correct_sum, w_sum, (g_stk, g_head) = (
        _pp_interleaved_engine(
            axis, num_microbatches=M, num_chunks=num_chunks,
            diff_params=(stacked, head), stage0_input=stage0_input,
            stage_apply=stage_apply_chunk, last_loss=last_loss,
            bm=bm, t_len=t, width=width, hidden=hidden, dtype=dtype,
        ))
    grads = {"rnn": _unstack_grads(g_stk, layers, cell), "fc": g_head}
    return loss_sum, correct_sum, w_sum, grads


def pp_char_1f1b_value_and_grad(layers, head, embed, tokens, axis: str, *,
                                num_microbatches: int, num_chunks: int = 1,
                                unroll: int = 1,
                                cell: str = "lstm", compute_dtype=None,
                                sample_weights=None):
    """Char-LM sibling of :func:`pp_rnn_1f1b_value_and_grad`: the same
    1F1B timetable with the per-timestep vocab head and next-token
    targets.  The embedding lookup lives INSIDE stage 0\'s vjp (the
    ``stage0_input`` hook re-evaluates it), so ``embed`` gets exact
    gradients without buffering d(activations) for every microbatch.

    ``tokens``: (B, T) int windows (T = seq_length + 1); loss semantics
    match ``_char_per_sequence_stats``: per-SEQUENCE mean over the T-1
    predicted positions, weighted by ``sample_weights``; ``correct`` sums
    per-sequence mean token accuracy.  Returns ``(loss_sum, correct_sum,
    w_sum, grads)`` with ``grads`` shaped ``{"rnn", "head", "embed"}``.
    """
    M = num_microbatches
    idx = lax.axis_index(axis)
    n_dev = lax.axis_size(axis)
    batch, t = tokens.shape
    _, per_stage = _check_1f1b_shapes(layers, axis, M, batch, cell,
                                      num_chunks)
    bm = batch // M
    hidden = layers[0]["w_hh"].shape[1]
    embed_dim = embed.shape[1]
    width = max(embed_dim, hidden)
    t_len = t - 1

    stacked = _stack_padded(layers, width, cell)
    toks_micro = tokens.reshape(M, bm, t)
    w_micro = (jnp.ones((M, bm), jnp.float32) if sample_weights is None
               else sample_weights.reshape(M, bm).astype(jnp.float32))
    if compute_dtype is not None:
        stacked = jax.tree.map(lambda p: p.astype(compute_dtype), stacked)
    dtype = jnp.dtype(compute_dtype) if compute_dtype else jnp.float32

    def stage0_input(dp, m):
        _, _, emb = dp
        toks = lax.dynamic_index_in_dim(toks_micro, m, keepdims=False)
        return _pad_last(emb[toks[:, :-1]], width).astype(dtype)

    def stage_apply_chunk(dp, acts, c):
        return _stage_layers(dp[0], c * n_dev + idx, per_stage, acts,
                             width=width, unroll=unroll, cell=cell)

    def last_loss(dp, acts, m):
        _, hd, _ = dp
        toks = lax.dynamic_index_in_dim(toks_micro, m, keepdims=False)
        w_m = lax.dynamic_index_in_dim(w_micro, m, keepdims=False)
        targets = toks[:, 1:]
        logits = (acts.astype(jnp.float32)
                  @ hd["weight"].T + hd["bias"])       # (bm, T-1, V)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(
            logp, targets[..., None], axis=-1
        )[..., 0]                                       # (bm, T-1)
        per_seq_nll = jnp.mean(nll, axis=1)
        per_seq_acc = jnp.mean(
            (jnp.argmax(logits, axis=-1) == targets).astype(jnp.float32),
            axis=1,
        )
        loss_m = jnp.sum(per_seq_nll * w_m)
        correct = jnp.sum(per_seq_acc * (w_m > 0))
        return loss_m, correct, jnp.sum(w_m)

    loss_sum, correct_sum, w_sum, (g_stk, g_head, g_emb) = (
        _pp_interleaved_engine(
            axis, num_microbatches=M, num_chunks=num_chunks,
            diff_params=(stacked, head, embed),
            stage0_input=stage0_input, stage_apply=stage_apply_chunk,
            last_loss=last_loss, bm=bm, t_len=t_len, width=width,
            hidden=hidden, dtype=dtype,
        ))
    grads = {"rnn": _unstack_grads(g_stk, layers, cell), "head": g_head,
             "embed": g_emb}
    return loss_sum, correct_sum, w_sum, grads


def _unstack_grads(g_stk, layers, cell: str):
    """Map stacked-layout grads back to the per-layer params tree:
    un-pad w_ih columns, un-transpose w_hh, split the folded LSTM bias
    (d b_ih = d b_hh = d b)."""
    out = []
    for li, layer in enumerate(layers):
        cols = layer["w_ih"].shape[1]
        g = {
            "w_ih": g_stk["w_ih"][li][:, :cols],
            "w_hh": g_stk["w_hh_t"][li].T,
        }
        if cell == "gru":
            g["b_ih"] = g_stk["b"][li]
            g["b_hh"] = g_stk["b_hh"][li]
        else:
            g["b_ih"] = g_stk["b"][li]
            g["b_hh"] = g_stk["b"][li]
        out.append(g)
    return out


def make_pp_forward(mesh, axis: str = "pp", *, num_microbatches: int = 4,
                    unroll: int = 1, cell: str = "lstm"):
    """Jitted pipeline-parallel forward for a MotionModel-shaped params
    tree: staged stacked RNN + last-timestep head (computed replicated -
    it is tiny).  ``x`` replicated in, logits replicated out; numerics
    match ``MotionModel.apply`` exactly.  ``cell`` must match the params
    tree - a GRU tree run as LSTM would split (B, 3H) pre-activations
    into four bogus gates without a shape error whenever 4 | 3H.
    """

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P()),
        out_specs=P(),
        check_vma=False,
    )
    def forward(params, x):
        out = pp_stacked_rnn(
            params["rnn"], x, axis, num_microbatches=num_microbatches,
            unroll=unroll, cell=cell,
        )
        last = out[:, -1, :]
        return last @ params["fc"]["weight"].T + params["fc"]["bias"]

    return jax.jit(forward)


# ---------------------------------------------------------------------------
# pdrnn-lint --deep trace registry (lint/trace_registry.py)


def declare_trace_entries(register):
    """Register the GPipe motion step (stage-hop ppermutes riding the
    microbatch scan)."""

    def build():
        import optax

        from pytorch_distributed_rnn_tpu.lint.trace_registry import (
            abstract_init,
            lint_mesh,
            prng_spec,
            sds,
        )
        from pytorch_distributed_rnn_tpu.models import MotionModel
        from pytorch_distributed_rnn_tpu.parallel.strategy import (
            make_mesh_grad_step,
            make_motion_mesh_loss_fn,
        )

        axes = {"dp": 2, "pp": 2}
        mesh = lint_mesh(axes)
        model = MotionModel(input_dim=9, hidden_dim=8, layer_dim=2,
                            output_dim=6, impl="scan")
        params = abstract_init(model.init, prng_spec())
        optimizer = optax.adam(1e-3)
        opt_state = abstract_init(optimizer.init, params)
        loss_fn = make_motion_mesh_loss_fn(mesh, axes, num_microbatches=2)
        step = make_mesh_grad_step(loss_fn, optimizer)
        batch = (sds((8, 16, 9), jnp.float32), sds((8,), jnp.int32))
        jitted = jax.jit(step, donate_argnums=(0, 1))
        return jitted, (params, opt_state, batch)

    register(
        name="pp.motion_gpipe_step", family="pp",
        path="pytorch_distributed_rnn_tpu/parallel/pp.py",
        build=build, mesh_axes={"dp": 2, "pp": 2}, data_axis="dp",
        donate=(0, 1),
    )
