#!/usr/bin/env python
"""Benchmarks: headline motion-LSTM throughput + stress metrics.

Prints ONE JSON line (driver contract):

    {"metric": ..., "value": N, "unit": "seq/s", "vs_baseline": N,
     "data": "synthetic ...", "extra_metrics": {...}}

- Headline: motion-LSTM training throughput (bs=1440) vs the reference
  re-run on this container class's x86 CPU (1931 seq/s, BASELINE.md
  "Re-run baseline").  Workload shape matches the reference sweep
  (``/root/reference/fabfile.py:48-66``); the DATA is synthetic
  HAR-shaped arrays (the real UCI HAR download is absent in this image) -
  identical tensor shapes/dtypes, so the compute is the same.
- ``extra_metrics`` (suite "stress", default): fused-vs-scan A/B on the
  motion model, char-RNN-50M tokens/s in bf16 and f32, and an MFU
  estimate for the bf16 run (LSTM FLOPs model, v5e bf16 peak).  Every
  stress entry is best-effort: a failure records an error string instead
  of breaking the headline contract.

The timed region matches the reference's methodology (wall-clock around
the epoch loop, ``base.py:93-96``) but excludes one-time XLA compilation:
a warm-up runs first (the reference's eager PyTorch has no compile phase,
so including ours would compare compilers, not training).
"""

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

from pytorch_distributed_rnn_tpu.utils import ensure_usable_backend

# The ambient TPU backend can hang (not raise) during init when its
# tunnel is down - both r1 and r2 driver artifacts went red on exactly
# this (VERDICT.md).  Probe it in a subprocess with a timeout; on
# hang/failure force CPU so the JSON contract line still prints.
BACKEND_INFO = ensure_usable_backend(min_devices=1, timeout=60.0)

import numpy as np

BASELINE_SEQ_PER_SEC = 1931.0  # reference local trainer, bs=1440, this host class
NUM_SEQUENCES = 6912
SEQ_LEN = 128
NUM_FEATURES = 9
BATCH_SIZE = 1440
SEED = 123456789

# TPU v5e public peak: 197 TFLOP/s bf16 per chip.  f32 MFU is reported
# against the same bf16 peak (conservative; v5e has no separate f32 MXU
# path worth quoting).
V5E_BF16_PEAK_FLOPS = 197e12


def last_real_chip_evidence(repo: Path = Path(__file__).resolve().parent):
    """The most recent banked real-chip bench line, for embedding in the
    emitted JSON whenever the capture-time backend is NOT the TPU.

    The tunnel to the one real chip is flaky; BENCH_r03 and BENCH_r04
    were both captured during outages and carried only the CPU fallback,
    silently under-reporting chip numbers that were already committed in
    mid-round ``results_bench_chip_*.json`` files.  This makes the emit
    outage-proof for *evidence*, not just for rc: the freshest banked
    chip line (picked by round number in the filename, then mtime) rides
    along with its provenance (source file, the commit that banked it,
    that commit's date)."""
    import re
    import subprocess

    ranked = []
    for path in repo.glob("results_bench_chip*.json"):
        try:
            with open(path) as f:
                row = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(row, dict) or row.get("backend") != "tpu":
            continue
        m = re.search(r"_r(\d+)", path.name)
        rank = (int(m.group(1)) if m else -1, path.stat().st_mtime)
        ranked.append((rank, path, row))
    if not ranked:
        return None
    ranked.sort(key=lambda t: t[0], reverse=True)
    _, path, row = ranked[0]
    evidence = {
        "source_file": path.name,
        "headline_seq_per_sec": row.get("value"),
        "vs_baseline": row.get("vs_baseline"),
    }
    # highlights merge across ALL banked files, newest first: a
    # family-suite line (e.g. an attention-only bank from a window that
    # died before the rnn suite ran) must not shadow the older full
    # line's LM story - per key, the freshest file carrying it wins,
    # with the source recorded whenever it is not the headline file
    highlights = {}
    for _, p, r in ranked:
        extras = r.get("extra_metrics") or {}
        for key in ("char_rnn_50m_bf16", "char_rnn_55m_wide_bf16",
                    "char_rnn_50m_bf16_b512_accum2", "moe_switch_bf16",
                    "attention_seq1024_dim512_flash_bf16",
                    "attention_seq1024_dim512_dense_bf16"):
            val = extras.get(key)
            if key not in highlights and isinstance(val, dict):
                highlights[key] = {
                    k: val[k]
                    for k in ("tokens_per_sec", "seq_per_sec",
                              "mfu_vs_v5e_bf16_peak")
                    if k in val
                }
                if p.name != path.name:
                    highlights[key]["source_file"] = p.name
    if highlights:
        evidence["highlights"] = highlights
    try:
        out = subprocess.run(
            ["git", "log", "-1", "--format=%h %cI", "--", path.name],
            cwd=repo, capture_output=True, text=True, timeout=10)
        if out.returncode == 0 and out.stdout.strip():
            rev, _, date = out.stdout.strip().partition(" ")
            evidence["git_rev"] = rev
            evidence["captured_at"] = date
    except Exception:  # noqa: BLE001 - provenance is best-effort
        pass
    return evidence


def motion_throughput(impl: str, cell: str = "lstm",
                      batch: int = BATCH_SIZE) -> float:
    """seq/s for the reference workload with the given RNN impl/cell."""
    from pytorch_distributed_rnn_tpu.data import MotionDataset
    from pytorch_distributed_rnn_tpu.data.synthetic import generate_har_arrays
    from pytorch_distributed_rnn_tpu.models import MotionModel
    from pytorch_distributed_rnn_tpu.training import Trainer

    X, y = generate_har_arrays(NUM_SEQUENCES, SEQ_LEN, NUM_FEATURES, seed=0)
    train_set = MotionDataset(X, y)
    model = MotionModel(input_dim=NUM_FEATURES, hidden_dim=32, layer_dim=2,
                        output_dim=6, impl=impl, cell=cell)
    trainer = Trainer(
        model, train_set, batch_size=batch, learning_rate=0.0025,
        seed=SEED,
    )
    trainer.train(epochs=1)  # warm-up: compile the 1-epoch program
    epochs = 3
    start = time.perf_counter()
    for _ in range(epochs):
        trainer.train(epochs=1)
    duration = time.perf_counter() - start
    return epochs * NUM_SEQUENCES / duration


def dp_sharded_ab_row(epochs: int = 2):
    """--sharded-update on/off A/B for the motion-LSTM DP trainer
    (2004.13336): same dp mesh, same data and seed, steady-state seq/s
    per flavor.  On one real chip both flavors share the HBM and the
    number is mostly the smaller update program; the wire-traffic half
    of the claim is gated separately (lint/collective_check.py)."""
    import jax

    n = jax.device_count()
    if n < 2:
        return (f"skipped: {n} device(s) - a dp mesh needs >= 2 "
                "(set PDRNN_NUM_CPU_DEVICES off-chip)")
    from pytorch_distributed_rnn_tpu.data import MotionDataset
    from pytorch_distributed_rnn_tpu.data.synthetic import generate_har_arrays
    from pytorch_distributed_rnn_tpu.models import MotionModel
    from pytorch_distributed_rnn_tpu.parallel import make_mesh
    from pytorch_distributed_rnn_tpu.training import DDPTrainer

    world = 4 if n >= 4 else 2
    X, y = generate_har_arrays(NUM_SEQUENCES, SEQ_LEN, NUM_FEATURES, seed=0)
    train_set = MotionDataset(X, y)
    row: dict = {"world": world}
    for key, sharded in (("sharded_seq_per_sec", True),
                         ("replicated_seq_per_sec", False)):
        trainer = DDPTrainer(
            MotionModel(input_dim=NUM_FEATURES, hidden_dim=32, layer_dim=2,
                        output_dim=6),
            train_set, batch_size=BATCH_SIZE, learning_rate=0.0025,
            seed=SEED, mesh=make_mesh({"dp": world}),
            sharded_update=sharded,
        )
        trainer.train(epochs=1)  # warm-up: compile
        start = time.perf_counter()
        for _ in range(epochs):
            trainer.train(epochs=1)
        row[key] = round(epochs * NUM_SEQUENCES
                         / (time.perf_counter() - start), 1)
    row["sharded_vs_replicated"] = round(
        row["sharded_seq_per_sec"] / row["replicated_seq_per_sec"], 3)
    return row


def native_bucketed_ab_row(epochs: int = 2, delay_ms: int = 2):
    """Bucketed-overlap vs monolithic collectives on the real world-4
    TCP ring (training/native_ddp.py), with per-leg transport delay
    injected through the chaos ``net:delay`` bridge (the netem analogue
    this container can actually run).  The claim under test: splitting
    the flat gradient into --bucket-mb buckets whose reduce-scatter /
    allgather stream on the comm worker hides delayed ring legs behind
    the per-bucket optimizer applies, so the blocked-wall ``comm_wait_s``
    drops vs the monolithic schedule - while the params stay bitwise
    identical (gated in tests/test_bucketed_comm.py, so this row only
    measures).  Numbers come from each flavor's rank-0 metrics sidecar
    (pdrnn-metrics summarize fields).

    The model is sized so the overlap has real work to hide: a ~12.7M
    param LSTM gives each rank a ~12.7MB gradient shard, so the default
    25MB bucket cap yields 2 buckets and the param-vector fetch plus the
    per-bucket sharded applies run WHILE later buckets' ring legs (each
    paying the injected per-message delay) are on the wire.  A tiny
    model would invert the row: bucketing sends B x the delayed
    messages, so with nothing to hide the extra ring latency, splitting
    loses - which is exactly why DDP defaults to 25MB buckets instead
    of thousands of tiny ones."""
    import tempfile

    from pytorch_distributed_rnn_tpu.data.synthetic import (
        write_synthetic_har_dataset,
    )
    from pytorch_distributed_rnn_tpu.obs.summary import summarize_file
    from pytorch_distributed_rnn_tpu.training.native_ddp import launch_world

    world = 4
    row: dict = {"world": world, "net_delay_ms": delay_ms}
    with tempfile.TemporaryDirectory(prefix="pdrnn-bucketed-ab-") as tmp:
        root = Path(tmp)
        data_dir = root / "data"
        # 128 train rows -> 96 after the validation split + WORKER_DIVISOR
        # truncation (data/processor.py); short windows keep the CPU
        # forward/backward of the 12.7M-param model affordable
        write_synthetic_har_dataset(data_dir, num_train=128, num_test=8,
                                    seq_length=8)
        for key, extra, port in (
            ("bucketed", (), 29601),  # default --bucket-mb 25 -> 2 buckets
            ("monolithic", ("--no-bucketed-comm",), 29603),
        ):
            run_dir = root / key
            run_dir.mkdir()
            metrics = run_dir / "metrics.jsonl"
            launch_world(world, [
                "--epochs", str(epochs), "--seed", str(SEED),
                "--dataset-path", str(data_dir),
                "--checkpoint-directory", str(run_dir / "models"),
                "--output-path", str(run_dir / "cache"),
                "--batch-size", "32", "--no-validation",
                "--hidden-units", "1024", "--stacked-layer", "2",
                "--metrics", str(metrics),
                "--faults", f"net:delay:{delay_ms}",
                *extra,
            ], master_port=port, cwd=run_dir, timeout=900)
            s = summarize_file(metrics)
            row[key] = {k: s.get(k) for k in (
                "step_s_mean", "comm_wait_s", "comm_wait_s_mean",
                "overlap_frac", "goodput", "comm_wait_frac",
                "fault_tax_s")}
    b, m = row["bucketed"], row["monolithic"]
    if b.get("comm_wait_s") and m.get("comm_wait_s"):
        # < 1.0 is the overlap actually paying for itself on the wire
        row["comm_wait_ratio"] = round(
            b["comm_wait_s"] / m["comm_wait_s"], 3)
    if b.get("step_s_mean") and m.get("step_s_mean"):
        row["step_s_ratio"] = round(
            b["step_s_mean"] / m["step_s_mean"], 3)
    return row


def motion_ledger_row(epochs: int = 3):
    """Efficiency-ledger excerpt (obs/ledger.py) for an instrumented
    motion-LSTM run: the headline workload re-run with a metrics sidecar,
    then priced - goodput, analytic MFU vs this backend's peak (the
    run-side peak block labels CPU estimates), comm-wait fraction and
    fault tax.  This is the banked evidence row the regression gate and
    the chaos drills compare against."""
    import tempfile

    from pytorch_distributed_rnn_tpu.data import MotionDataset
    from pytorch_distributed_rnn_tpu.data.synthetic import generate_har_arrays
    from pytorch_distributed_rnn_tpu.models import MotionModel
    from pytorch_distributed_rnn_tpu.obs.ledger import ledger_run
    from pytorch_distributed_rnn_tpu.obs.recorder import MetricsRecorder
    from pytorch_distributed_rnn_tpu.training import Trainer

    X, y = generate_har_arrays(NUM_SEQUENCES, SEQ_LEN, NUM_FEATURES, seed=0)
    train_set = MotionDataset(X, y)
    with tempfile.TemporaryDirectory(prefix="pdrnn-bench-ledger-") as tmp:
        metrics = Path(tmp) / "metrics.jsonl"
        recorder = MetricsRecorder(metrics)
        try:
            trainer = Trainer(
                MotionModel(input_dim=NUM_FEATURES, hidden_dim=32,
                            layer_dim=2, output_dim=6),
                train_set, batch_size=BATCH_SIZE, learning_rate=0.0025,
                seed=SEED, recorder=recorder,
            )
            trainer.train(epochs=epochs)
        finally:
            recorder.close()
        agg = ledger_run(metrics)["aggregate"]
    row = {k: agg.get(k) for k in (
        "goodput", "mfu_est", "fault_tax_s", "comm_wait_frac",
        "recompiles")}
    row["fractions"] = {
        k: round(v, 4) for k, v in agg["fractions"].items()}
    if agg.get("peak_estimated"):
        row["peak_estimated"] = True
    return row


def lstm_lm_flops_per_token(model) -> float:
    """Training FLOPs per token for a stacked-LSTM LM: 2*MACs for the
    input + recurrent matmuls per layer, plus the vocab head; backward
    ~2x forward (the standard 3x-forward training estimate)."""
    h = model.hidden_dim
    fwd = 0.0
    for layer in range(model.layer_dim):
        in_dim = model.embed_dim if layer == 0 else h
        fwd += 2.0 * 4 * h * (in_dim + h)
    fwd += 2.0 * h * model.vocab_size  # per-timestep head
    return 3.0 * fwd


def char50m_tokens_per_sec(precision: str, batch: int = 32,
                           seq: int = 129, steps: int = 50,
                           shape: str = "deep", unroll: int = 1,
                           accum: int = 1, impl: str = "auto"):
    """(tokens/s, mfu) for a 50M-class LM; mfu vs the v5e bf16 peak.

    ``shape="deep"`` is the BASELINE.json preset (4 x 1280); ``"wide"``
    is the MFU-ceiling probe (2 x 2048, ~55M params): same class, fewer
    sequential steps, each recurrent matmul ~2.6x larger - the MXU
    utilization lever a recurrent model actually has.  ``accum > 1``
    grad-accumulates over ``accum`` microbatches of ``batch // accum``
    per optimizer step - the workaround when the monolithic program will
    not compile (the environment's remote AOT compile helper 500s on
    batch-512 shapes; 256-shaped microbatch programs compile fine).
    ``accum=1`` degenerates to a plain fused step, so every LM row
    shares this one timing harness."""
    import jax
    import jax.numpy as jnp
    import optax

    from pytorch_distributed_rnn_tpu.models import char_rnn_50m

    if batch % accum:
        raise ValueError(f"batch {batch} not divisible by accum {accum}")
    if shape == "wide":
        from pytorch_distributed_rnn_tpu.models.char_rnn import CharRNN

        model = CharRNN(vocab_size=256, embed_dim=512, hidden_dim=2048,
                        layer_dim=2, cell="lstm", impl=impl,
                        precision=precision, unroll=unroll)
    else:
        model = char_rnn_50m(impl=impl, precision=precision,
                             unroll=unroll)
    params = model.init(jax.random.PRNGKey(0))
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(p, o, tok):
        if accum == 1:
            loss, grads = jax.value_and_grad(model.loss)(p, tok)
        else:
            def micro_grads(carry, tok_m):
                acc, loss_acc = carry
                l, g = jax.value_and_grad(model.loss)(p, tok_m)
                return (jax.tree.map(jnp.add, acc, g), loss_acc + l), None

            zeros = jax.tree.map(jnp.zeros_like, p)
            (gsum, lsum), _ = jax.lax.scan(
                micro_grads, (zeros, 0.0),
                tok.reshape(accum, batch // accum, tok.shape[1]),
            )
            grads = jax.tree.map(lambda g: g / accum, gsum)
            loss = lsum / accum
        updates, o = opt.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(0, 256, size=(batch, seq)), jnp.int32)
    params, opt_state, loss = step(params, opt_state, tok)  # compile
    float(loss)
    start = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tok)
    # End the timed region with a concrete host fetch of the final loss:
    # on the tunneled axon backend `jax.block_until_ready` can return
    # before the enqueued step chain has executed (measured: a follow-up
    # fetch after block still took 0.5s), inflating short timings by
    # >100x.  A float() round-trip cannot complete until every step it
    # depends on has.
    float(loss)
    dt = (time.perf_counter() - start) / steps
    tokens_per_sec = batch * (seq - 1) / dt
    mfu = tokens_per_sec * lstm_lm_flops_per_token(model) / V5E_BF16_PEAK_FLOPS
    return tokens_per_sec, mfu


def moe_flops_per_step(router: str, tokens: int, dim: int, hidden: int,
                       experts: int, capacity: int,
                       n_groups: int = 1) -> float:
    """Training FLOPs per step of one MoE FFN layer, counting what the
    MXU actually executes: router (2*N*D*E), the one-hot dispatch AND
    combine einsums (2*N*E*C*D each - the real cost of the dense
    TPU-friendly dispatch formulation; C ~ N*cf/E makes them scale with
    N^2, which is why dispatched MoE routes GROUPS of a few thousand
    tokens), and the expert FFN over all E*C capacity slots (padded
    slots compute zeros but still occupy the MXU).  ``router="dense"``
    has no dispatch: every expert runs every token (N*E slots).
    Backward ~2x forward (the standard 3x estimate)."""
    if router == "dense":
        slots = tokens * experts
        dispatch = 0.0
    else:
        # grouped routing (GShard): capacity is PER GROUP, slots total
        # E*C*G, and each group's dispatch one-hot only spans its own
        # tokens - so dispatch stays 2*N*E*C*D with the smaller C
        slots = experts * capacity * n_groups
        dispatch = 2 * (2.0 * tokens * experts * capacity * dim)
    fwd = (
        2.0 * tokens * dim * experts      # router
        + dispatch
        + slots * 4.0 * dim * hidden      # expert fc1 + fc2
    )
    return 3.0 * fwd


def moe_ffn_throughput(router: str, *, tokens: int = 8192, dim: int = 512,
                       hidden: int = 2048, experts: int = 8,
                       capacity_factor: float = 2.0, steps: int = 10,
                       precision: str = "bf16",
                       group_size: int | None = None):
    """Train-step throughput of ONE MoE FFN layer on the dispatched
    path: ``router`` in {"switch", "top2", "expert", "dense"} (dense =
    the exact O(E) A/B reference, ``ops/moe.py::moe_ffn_dense``).

    Returns a row dict: tokens/s, MFU vs the v5e bf16 peak (FLOPs model
    in :func:`moe_flops_per_step` - executed compute, dispatch einsums
    included), the REALIZED drop fraction (token-choice: routed
    assignments that found no capacity slot, counted via the dispatch's
    own slotting formula; expert-choice: tokens no expert picked - both
    measured from the actual routing, not the capacity formula), and
    the config."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_rnn_tpu.ops.moe import (
        _route_expert_choice,
        _route_topk,
        _slot_positions,
        cast_expert_params,
        init_moe_ffn,
        moe_capacity,
        moe_ffn,
        moe_ffn_dense,
        moe_ffn_expert_choice,
    )
    from pytorch_distributed_rnn_tpu.ops.rnn import dtype_of

    params = init_moe_ffn(jax.random.PRNGKey(0), dim, experts, hidden)
    compute_dtype = dtype_of(precision) or jnp.float32
    x = jax.random.normal(jax.random.PRNGKey(1), (tokens, dim),
                          jnp.float32)

    if group_size and (group_size >= tokens
                       or router in ("expert", "dense")):
        # mirror the op's own behavior (one global group; expert/dense
        # routers have no token-choice grouping at all) so capacity,
        # FLOPs slots, and the drop counter all describe the path that
        # actually ran
        group_size = None
    num_selected = {"switch": 1, "top2": 2, "expert": 1, "dense": 1}[router]
    if router == "expert":
        capacity = moe_capacity(tokens, experts, capacity_factor)

        def ffn(p, xt):
            return moe_ffn_expert_choice(
                p, xt, capacity_factor=capacity_factor)
    elif router == "dense":
        capacity = 0

        def ffn(p, xt):
            return moe_ffn_dense(p, xt, num_selected=num_selected)
    else:
        capacity = moe_capacity(group_size or tokens, experts,
                                capacity_factor, num_selected)

        def ffn(p, xt):
            return moe_ffn(p, xt, capacity_factor=capacity_factor,
                           num_selected=num_selected,
                           group_size=group_size)

    def loss(p, xx):
        out, aux = ffn(cast_expert_params(p, compute_dtype),
                       xx.astype(compute_dtype))
        return jnp.mean(out.astype(jnp.float32) ** 2) + 0.01 * aux

    step = jax.jit(jax.value_and_grad(loss))
    l, _ = step(params, x)  # compile
    float(l)
    start = time.perf_counter()
    for _ in range(steps):
        l, grads = step(params, x)
    float(l)  # host fetch closes the timed region (see char50m note)
    dt = (time.perf_counter() - start) / steps
    n_groups = 1 if not group_size else tokens // group_size
    flops = moe_flops_per_step(router, tokens, dim, hidden, experts,
                               capacity, n_groups)

    # realized drop fraction: route in the SAME compute dtype the timed
    # step used (bf16 near-ties can pick different experts than f32),
    # under jit, returning only a scalar - never the (N, E, C) dispatch
    # tensor (gigabytes at the TPU-sized config)
    @jax.jit
    def measure_drop(p, xx):
        pc = cast_expert_params(p, compute_dtype)
        xt = xx.astype(compute_dtype)
        if router == "expert":
            sel, _ = _route_expert_choice(pc, xt, capacity)
            covered = jnp.sum(sel, axis=(0, 1)) > 0  # (N,) any slot
            return 1.0 - jnp.mean(covered.astype(jnp.float32))
        experts_k, _, _ = _route_topk(pc, xt, num_selected)

        # choice-major flattening + the shared slotting formula = the
        # exact pos make_dispatch_topk assigns, so `pos < capacity`
        # counts precisely the assignments the real dispatch keeps;
        # grouped routing slots within each group independently
        def kept_in(ex):  # (n, k) assignments of one routing group
            pos = _slot_positions(ex.T.reshape(-1), experts)
            return jnp.sum((pos < capacity).astype(jnp.float32))

        if n_groups > 1:
            kept = jnp.sum(jax.vmap(kept_in)(
                experts_k.reshape(n_groups, group_size, num_selected)))
        else:
            kept = kept_in(experts_k)
        return 1.0 - kept / (tokens * num_selected)

    drop_frac = 0.0 if router == "dense" else float(measure_drop(params, x))

    row = {
        "tokens_per_sec": round(tokens / dt, 0),
        "mfu_vs_v5e_bf16_peak": round(flops / dt / V5E_BF16_PEAK_FLOPS, 4),
        "drop_frac": round(drop_frac, 4),
        "tokens": tokens, "dim": dim, "hidden": hidden,
        "experts": experts, "capacity_factor": capacity_factor,
    }
    if group_size:
        row["group_size"] = group_size
    return row


def recurrent_roofline_row(hidden: int, batch: int, seq: int = 128,
                           steps: int = 10):
    """Train-pass timing of ONE LSTM layer's RECURRENT scan alone -
    pre-projected inputs, no vocab head - the sequential bottleneck the
    deep-vs-wide MFU gap lives in (4 x 1280 = 45.8% vs 2 x 2048 = 51.3%,
    results_bench_chip_r4.json).  The input projection is bulk MXU work
    that amortizes perfectly and identically for both shapes; what
    differs is the per-step recurrent matmul size (2*B*H*4H FLOPs) over
    the same scan overhead, so timing the scan alone across an (H, B)
    grid separates compute-roofline time from per-step overhead: fitting
    t_step = flops/eff_peak + tau over the grid yields the tau that
    bounds deep shapes below wide ones.  Uses the REAL lstm_step (the
    scan path's cell), fwd+bwd via grad."""
    from functools import partial as _partial

    import jax
    import jax.numpy as jnp

    from pytorch_distributed_rnn_tpu.ops.rnn import lstm_step

    key = jax.random.PRNGKey(0)
    w_hh_t = (jax.random.normal(key, (hidden, 4 * hidden), jnp.float32)
              * hidden ** -0.5).astype(jnp.bfloat16)
    xp = jax.random.normal(jax.random.PRNGKey(1),
                           (seq, batch, 4 * hidden), jnp.bfloat16)
    h0 = jnp.zeros((batch, hidden), jnp.float32)
    c0 = jnp.zeros((batch, hidden), jnp.float32)

    def f(w, xp):
        _, out = jax.lax.scan(_partial(lstm_step, w), (h0, c0), xp)
        return jnp.sum(out.astype(jnp.float32))

    step = jax.jit(jax.grad(f, argnums=0))
    g = step(w_hh_t, xp)  # compile
    # concrete host fetch, not block_until_ready: on the tunneled axon
    # backend the latter can return before the enqueued chain executed
    # (see the char50m timing note), which would bleed warm-up into the
    # timed region of exactly the tau fit this row feeds
    float(jnp.sum(g.astype(jnp.float32)))
    start = time.perf_counter()
    for _ in range(steps):
        g = step(w_hh_t, xp)
    float(jnp.sum(g.astype(jnp.float32)))  # host fetch closes the region
    dt = (time.perf_counter() - start) / steps
    flops = 3.0 * seq * 2 * batch * hidden * 4 * hidden
    # sequential step count is 2*seq (fwd scan + bwd scan); the 3x in
    # the FLOPs model is the training-FLOPs convention, not a step count
    return {"ms_per_pass": round(dt * 1000, 3),
            "us_per_step": round(dt * 1e6 / (2 * seq), 2),
            "eff_tflops": round(flops / dt / 1e12, 1),
            "mfu_vs_v5e_bf16_peak": round(
                flops / dt / V5E_BF16_PEAK_FLOPS, 4),
            "hidden": hidden, "batch": batch, "seq": seq}


def lm_best_row(precision, candidates=((512, 10), (256, 20), (128, 30),
                                       (32, 50)), seq=129, shape="deep",
                unroll=1, impl="auto"):
    """Largest LM batch that compiles+runs wins (batch 512 failed in the
    r2 remote compile helper - retried every round).  A compile-class
    failure retries the SAME effective batch with grad accumulation
    (microbatches of the shapes that do compile) before stepping down -
    the bench-side twin of the trainer's auto-accum fallback, so the
    failing program class produces a number, not a skip.  Failures stay
    visible either way: skipped_batches records the error and accum > 1
    on the result marks the fallback that rescued it."""
    from pytorch_distributed_rnn_tpu.training.base import Trainer

    last = None
    skipped = {}
    for batch, steps in candidates:
        for accum in (1, 2, 4):
            if batch % accum:
                continue
            try:
                tps, mfu = char50m_tokens_per_sec(
                    precision, batch=batch, steps=steps, seq=seq,
                    shape=shape, unroll=unroll, accum=accum, impl=impl)
                result = {"tokens_per_sec": round(tps, 0),
                          "mfu_vs_v5e_bf16_peak": round(mfu, 4),
                          "batch": batch, "seq": seq - 1}
                if accum > 1:
                    result["accum"] = accum
                if skipped:
                    result["skipped_batches"] = skipped
                return result
            except Exception as exc:  # noqa: BLE001 - retry or step down
                key = (str(batch) if accum == 1
                       else f"{batch}@accum{accum}")
                skipped[key] = f"{type(exc).__name__}: {exc}"[:160]
                last = exc
                if not Trainer.is_compile_failure(exc):
                    break  # not compile-shaped: step down in batch
    raise last


def attention_flops_per_seq(dim: int, depth: int, seq_len: int,
                            input_dim: int = NUM_FEATURES,
                            output_dim: int = 6,
                            mlp_ratio: int = 4) -> float:
    """Training FLOPs per sequence for the attention classifier: per
    block 2*MACs for QKV/output projections (4 * T * D^2), the two
    attention matmuls (2 * T^2 * D), and the MLP (2 * T * D * 4D each
    way); embed + head are negligible but counted.  Backward ~2x forward
    (the standard 3x estimate; flash recompute adds ~1 more forward of
    the attention core, not counted - MFU reads conservative)."""
    t, d = seq_len, dim
    per_block = (
        2.0 * 4 * t * d * d          # QKV + output projections
        + 2.0 * 2 * t * t * d        # QK^T and PV
        + 2.0 * 2 * t * d * (mlp_ratio * d)  # fc1 + fc2
    )
    fwd = depth * per_block + 2.0 * t * input_dim * d + 2.0 * d * output_dim
    return 3.0 * fwd


def attention_throughput(batch: int = 256, steps: int = 30,
                         seq_len: int = SEQ_LEN,
                         impl: str = "auto",
                         precision: str = "f32",
                         dim: int = 128, num_heads: int = 4):
    """seq/s training the attention classifier on HAR-shaped windows -
    the long-context family's single-chip baseline number (its sp/tp mesh
    composition is compile-validated by dryrun_multichip; ring-attention
    wall-clock needs a real multi-chip slice).  ``seq_len`` above the HAR
    window probes the dense-attention long-context regime one chip can
    measure (quadratic attention FLOPs start to dominate ~1k).  ``impl``
    selects the attention inner: ``dense`` XLA vs the fused ``flash``
    Pallas kernel (``auto`` = flash on TPU).  Returns ``(seq/s, mfu)``
    with MFU derived from the constructed model's own fields (the
    char50m pattern), so tuning the probe shape cannot desync them."""
    import jax
    import jax.numpy as jnp
    import optax

    from pytorch_distributed_rnn_tpu.models import AttentionClassifier
    from pytorch_distributed_rnn_tpu.ops import cross_entropy_loss

    model = AttentionClassifier(input_dim=NUM_FEATURES, dim=dim, depth=2,
                                num_heads=num_heads, output_dim=6,
                                max_len=seq_len, impl=impl,
                                precision=precision)
    params = model.init(jax.random.PRNGKey(0))
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(p, o, x, y):
        def loss_fn(p):
            return cross_entropy_loss(model.apply(p, x), y)

        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, o = opt.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, seq_len, NUM_FEATURES)
                    .astype(np.float32))
    y = jnp.asarray(rng.randint(0, 6, size=batch))
    params, opt_state, loss = step(params, opt_state, x, y)  # compile
    float(loss)
    start = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, x, y)
    float(loss)  # host fetch closes the timed region (see char50m note)
    seq_per_sec = steps * batch / (time.perf_counter() - start)
    # mlp_ratio mirrors init_block's fixed default (models/attention.py:
    # init_block) - the one block hyperparameter the model class does not
    # expose, so it cannot be tuned out of sync from here
    mfu = (seq_per_sec
           * attention_flops_per_seq(model.dim, model.depth, seq_len,
                                     input_dim=model.input_dim,
                                     output_dim=model.output_dim)
           / V5E_BF16_PEAK_FLOPS)
    return seq_per_sec, mfu


def main():
    import argparse

    parser = argparse.ArgumentParser(prog="bench.py")
    parser.add_argument("--suite",
                        choices=["quick", "stress", "attention", "moe",
                                 "rnn"],
                        default="stress",
                        help="quick: headline only; stress: every "
                        "family's standard rows (deep diagnostic ladders "
                        "excluded so the driver's plain run stays inside "
                        "its budget); attention / moe / rnn: headline + "
                        "that family's rows INCLUDING its deep ladders "
                        "(the watcher's fast paths for scarce tunnel "
                        "windows)")
    parser.add_argument("--append-rows", default=None, metavar="PATH",
                        help="also append each extra row as one JSON line "
                        "to PATH the moment it completes - a killed run "
                        "(wedged tunnel, watcher timeout) keeps every "
                        "finished measurement instead of losing the "
                        "end-of-run JSON emit")
    args = parser.parse_args()

    import jax

    on_tpu = jax.default_backend() == "tpu"
    if BACKEND_INFO["fallback"]:
        print(
            "bench.py: ambient backend unavailable (probe hung/failed); "
            "falling back to CPU",
            file=sys.stderr,
        )
    headline = motion_throughput("auto")

    extras: dict = {}
    rnn_rows = args.suite in ("stress", "rnn")
    attention_rows = args.suite in ("stress", "attention")
    moe_rows = args.suite in ("stress", "moe")
    if rnn_rows or attention_rows or moe_rows:
        def attempt(name, fn, deep=False):
            # suite filter lives HERE so the row lists below stay one
            # flat sequence: rows are classed by name prefix (attention_
            # / moe_); everything else belongs to the stress suite.
            # ``deep`` marks diagnostic ladders that run ONLY in their
            # dedicated family suite (the watcher's fast paths), never
            # in stress: the driver runs plain `python bench.py` at
            # round end, and on a live chip the ladders would stack
            # ~20 extra compiles onto a run that must finish inside the
            # driver's budget - the r5 watcher banks them instead.
            if name.startswith("attention_"):
                wanted = attention_rows
            elif name.startswith("moe_"):
                wanted = moe_rows
            else:
                wanted = rnn_rows
            if deep and args.suite == "stress":
                wanted = False
            if not wanted:
                return
            try:
                extras[name] = fn()
            except Exception as exc:  # noqa: BLE001 - headline must survive
                extras[name] = f"error: {type(exc).__name__}: {exc}"[:200]
            if args.append_rows:
                with open(args.append_rows, "a") as f:
                    f.write(json.dumps({"row": name,
                                        "result": extras[name]}) + "\n")

        # fused-vs-scan A/B.  The headline "auto" run already measured one
        # impl (fused on TPU, scan elsewhere - resolve_rnn_impl): reuse
        # that number and measure only the other side.  The fused kernel
        # is a TPU kernel (interpret mode off-TPU would benchmark the
        # interpreter), so its side only runs on the real chip.
        from pytorch_distributed_rnn_tpu.ops.rnn import resolve_rnn_impl

        auto_impl = resolve_rnn_impl("auto", "lstm", hidden=32)
        if rnn_rows:
            extras[f"motion_{auto_impl}_seq_per_sec"] = round(headline, 1)
        if auto_impl != "scan":
            attempt(
                "motion_scan_seq_per_sec",
                lambda: round(motion_throughput("scan"), 1),
            )
        elif on_tpu:
            attempt(
                "motion_fused_seq_per_sec",
                lambda: round(motion_throughput("fused"), 1),
            )
        elif rnn_rows:
            extras["motion_fused_seq_per_sec"] = (
                "skipped: no TPU (fused kernel would run interpreted)"
            )

        _lm = lm_best_row

        # GRU flavor of the reference workload (BASELINE.json config 4's
        # single-chip component; its multi-host half needs a real slice)
        attempt(
            "motion_gru_seq_per_sec",
            lambda: round(motion_throughput("auto", cell="gru"), 1),
        )

        # Steady-state batch-scaling curve - what ONE chip can honestly
        # measure (the committed results_tpu_chip.json CLI rows include
        # per-run compile/setup; these exclude it, reference sweep grid
        # {480,960,1440} + one doubling up).  1440 reuses the headline.
        def _batch_curve():
            # seq/s counts the 6912 real sequences; the trainer pads the
            # final partial batch with zero-weight rows, so each point
            # also records what fraction of its executed compute is
            # padding (6912 divides none of the grid evenly - 20% padding
            # at 2880 would otherwise read as a batch-scaling effect).
            curve = {}
            for bs in (480, 960, 1440, 2880):
                executed = -(-NUM_SEQUENCES // bs) * bs
                point = {"padded_compute_frac": round(
                    (executed - NUM_SEQUENCES) / executed, 3)}
                try:
                    point["seq_per_sec"] = (
                        round(headline, 1) if bs == BATCH_SIZE
                        else round(motion_throughput("auto", batch=bs), 1))
                except Exception as exc:  # noqa: BLE001 - keep other points
                    point["error"] = f"{type(exc).__name__}: {exc}"[:160]
                curve[str(bs)] = point
            return curve

        attempt("motion_batch_curve_seq_per_sec", _batch_curve)

        # the efficiency-ledger evidence row (ISSUE 15): the headline
        # workload instrumented and priced - goodput, analytic MFU,
        # fault tax, comm-wait fraction off its own sidecar
        attempt("motion_efficiency_ledger", motion_ledger_row)

        # sharded-vs-replicated weight update on the dp mesh
        # (2004.13336); off-chip the row self-skips below 2 devices
        attempt("motion_dp_sharded_update_ab", dp_sharded_ab_row)

        # bucketed-overlap vs monolithic collectives on the real TCP
        # ring under injected per-leg delay (ISSUE 14); spawns its own
        # 4-process world, so it never contends with the dp-mesh rows
        attempt("motion_native_bucketed_ab", native_bucketed_ab_row)

        # the MoE family's throughput evidence: all three routers on the
        # dispatched path + the dense-exact A/B.  Runs on every backend
        # (the EP axis must not stay perf-unmeasured just because the
        # tunnel is down) with CPU-sized shapes off-TPU; MFU is only
        # meaningful against the v5e peak on the real chip.
        moe_kw = (dict(tokens=8192, hidden=2048, steps=10) if on_tpu
                  else dict(tokens=2048, hidden=512, steps=3))
        attempt("moe_switch_bf16",
                lambda: moe_ffn_throughput("switch", **moe_kw))
        attempt("moe_top2_bf16",
                lambda: moe_ffn_throughput("top2", **moe_kw))
        attempt("moe_expert_choice_bf16",
                lambda: moe_ffn_throughput("expert", **moe_kw))
        attempt("moe_dense_ab_bf16",
                lambda: moe_ffn_throughput("dense", **moe_kw))

        # group-size ladder (GShard grouped routing): the one-hot
        # dispatch einsums cost 2*N*E*C*D with C per ROUTING GROUP, so
        # smaller groups trade drop locality for linear-in-N dispatch -
        # the ladder measures the throughput/drop trade directly
        def _moe_group_ladder():
            ladder = {}
            sizes = ((2048, 1024, 512) if on_tpu else (512, 256))
            for gs in sizes:
                try:
                    ladder[f"group{gs}"] = moe_ffn_throughput(
                        "switch", group_size=gs, **moe_kw)
                except Exception as exc:  # noqa: BLE001 - keep rungs
                    ladder[f"group{gs}"] = (
                        f"error: {type(exc).__name__}: {exc}"[:160])
            return ladder

        attempt("moe_switch_bf16_group_ladder", _moe_group_ladder,
                deep=True)

        if on_tpu:
            attempt("char_rnn_50m_bf16", lambda: _lm("bf16"))
            attempt("char_rnn_50m_f32", lambda: _lm("f32"))
            # longer windows amortize the recurrence's per-step overhead
            # (the MFU ceiling chase, VERDICT r2 weak #7): same token
            # throughput math, 2x/4x the sequential depth per batch row
            attempt(
                "char_rnn_50m_bf16_seq256",
                lambda: _lm("bf16", candidates=((256, 10), (128, 15),
                                                (32, 25)), seq=257),
            )
            attempt(
                "char_rnn_50m_bf16_seq512",
                lambda: _lm("bf16", candidates=((128, 8), (64, 12),
                                                (16, 20)), seq=513),
            )
            # the MFU-ceiling probe: same 50M class, 2 x 2048 instead of
            # 4 x 1280 - each recurrent matmul ~2.6x larger, half the
            # sequential depth (VERDICT r2 weak #7)
            attempt(
                "char_rnn_55m_wide_bf16",
                lambda: _lm("bf16", shape="wide"),
            )

            # scan-unroll ladder at one fixed config (batch 256, so the
            # u=1 rung is the same-config baseline): unroll>1 gives XLA
            # more ILP per loop iteration (fewer loop-carried barriers)
            # at the cost of program size; each rung records its own
            # result or error so one rung's compile failure (the
            # documented cost of large unroll) cannot discard the others
            def _unroll_ladder():
                ladder = {}
                for u in (1, 2, 4, 8):
                    try:
                        ladder[f"unroll{u}"] = _lm(
                            "bf16", candidates=((256, 15),), unroll=u)
                    except Exception as exc:  # noqa: BLE001 - keep rungs
                        ladder[f"unroll{u}"] = (
                            f"error: {type(exc).__name__}: {exc}"[:160])
                return ladder

            attempt("char_rnn_50m_bf16_unroll", _unroll_ladder, deep=True)

            # the deep-vs-wide MFU gap diagnostic: the recurrent scan
            # alone over an (H, B) grid; fit t_step = flops/eff + tau
            # offline to pin how much of the 45.8%-vs-51.3% gap is
            # per-step overhead vs roofline (each cell records its own
            # result or error so one failing shape keeps the others)
            def _roofline_grid():
                grid = {}
                for hidden, batch in ((1280, 256), (2048, 256),
                                      (1280, 512), (2048, 512)):
                    cell_key = f"h{hidden}_b{batch}"
                    try:
                        grid[cell_key] = recurrent_roofline_row(
                            hidden, batch)
                    except Exception as exc:  # noqa: BLE001 - keep cells
                        grid[cell_key] = (
                            f"error: {type(exc).__name__}: {exc}"[:160])
                return grid

            attempt("char_rnn_recurrent_roofline", _roofline_grid,
                    deep=True)

            # deep-shape MFU levers (VERDICT r4 item 6): the fused
            # Pallas kernel forced at H=1280 (auto declines it there -
            # this measures whether that policy is right), and batch
            # 1024 (bigger per-step recurrent matmuls; the auto-accum
            # ladder finds the largest microbatch that compiles)
            attempt("char_rnn_50m_bf16_fused",
                    lambda: _lm("bf16", candidates=((256, 10), (128, 15)),
                                impl="fused"), deep=True)
            attempt("char_rnn_50m_bf16_b1024",
                    lambda: _lm("bf16", candidates=((1024, 6),)),
                    deep=True)

            # effective batch 512 despite the environment's remote AOT
            # compile helper dying on the monolithic batch-512 program:
            # 2 microbatches of 256 (the shapes that DO compile),
            # grad-accumulated into one optimizer step
            def _accum_row():
                tps, mfu = char50m_tokens_per_sec(
                    "bf16", batch=512, steps=10, accum=2)
                return {"tokens_per_sec": round(tps, 0),
                        "mfu_vs_v5e_bf16_peak": round(mfu, 4),
                        "batch": 512, "accum": 2, "seq": 128}

            attempt("char_rnn_50m_bf16_b512_accum2", _accum_row)
            # dense vs fused flash kernel at the HAR window and at 8x it:
            # the flash/dense ratio is the attention family's kernel win
            # (quadratic dense attention starts to dominate ~1k)
            def _attn_row(seq_len, **kw):
                seq_s, mfu = attention_throughput(seq_len=seq_len, **kw)
                return {"seq_per_sec": round(seq_s, 1),
                        "mfu_vs_v5e_bf16_peak": round(mfu, 4)}

            attempt("attention_seq128_dense",
                    lambda: _attn_row(SEQ_LEN, impl="dense"))
            attempt("attention_seq128_flash",
                    lambda: _attn_row(SEQ_LEN, impl="flash"))
            attempt("attention_seq1024_dense",
                    lambda: _attn_row(1024, batch=64, steps=15,
                                      impl="dense"))
            attempt("attention_seq1024_flash",
                    lambda: _attn_row(1024, batch=64, steps=15,
                                      impl="flash"))
            attempt("attention_seq1024_flash_bf16",
                    lambda: _attn_row(1024, batch=64, steps=15,
                                      impl="flash", precision="bf16"))
            # the r4 window showed flash == dense == ~4.6% MFU at the
            # probe's dim=128/heads=4: head_dim 32 fills 1/4 of the MXU's
            # 128-wide contraction in BOTH impls, so the kernel never
            # differentiates.  These rows probe the kernel-relevant shape
            # (head_dim 128) where the QK^T/PV matmuls tile the MXU
            # fully, and the T=4096 point where dense's O(T^2) score
            # materialization stops fitting at all (its row records the
            # OOM/compile error as evidence; flash's O(T) VMEM state is
            # what makes the long-context point reachable on one chip).
            attempt("attention_seq1024_dim512_dense_bf16",
                    lambda: _attn_row(1024, batch=16, steps=10,
                                      impl="dense", precision="bf16",
                                      dim=512, num_heads=4))
            attempt("attention_seq1024_dim512_flash_bf16",
                    lambda: _attn_row(1024, batch=16, steps=10,
                                      impl="flash", precision="bf16",
                                      dim=512, num_heads=4))
            attempt("attention_seq4096_dim512_flash_bf16",
                    lambda: _attn_row(4096, batch=8, steps=5,
                                      impl="flash", precision="bf16",
                                      dim=512, num_heads=4))
            # pure-kernel block-size ladder: flash fwd+bwd at the
            # MXU-relevant shape (head_dim 128, T=1024) across block_q/
            # block_k tilings - the Pallas tuning lever the model-level
            # rows cannot separate from everything around the kernel
            def _flash_block_ladder():
                import jax
                import jax.numpy as jnp

                from pytorch_distributed_rnn_tpu.ops.pallas_attention import (  # noqa: E501
                    flash_attention,
                )

                rng = np.random.RandomState(0)
                q, k, v = (
                    jnp.asarray(
                        rng.randn(8, 8, 1024, 128).astype(np.float32)
                    ).astype(jnp.bfloat16)
                    for _ in range(3)
                )
                ladder = {}
                for bq, bk in ((256, 256), (256, 512), (512, 256),
                               (512, 512), (128, 1024)):
                    try:
                        def f(q, k, v, _bq=bq, _bk=bk):
                            return jnp.sum(
                                flash_attention(
                                    q, k, v, block_q=_bq, block_k=_bk
                                ).astype(jnp.float32))

                        step = jax.jit(jax.grad(f, argnums=(0, 1, 2)))
                        jax.block_until_ready(step(q, k, v))  # compile
                        iters = 10
                        start = time.perf_counter()
                        for _ in range(iters):
                            out = step(q, k, v)
                        jax.block_until_ready(out)
                        ladder[f"bq{bq}_bk{bk}_ms"] = round(
                            (time.perf_counter() - start) * 1000 / iters,
                            3)
                    except Exception as exc:  # noqa: BLE001 - keep rungs
                        ladder[f"bq{bq}_bk{bk}_ms"] = (
                            f"error: {type(exc).__name__}: {exc}"[:120])
                return ladder

            attempt("attention_flash_block_ladder", _flash_block_ladder,
                    deep=True)

            # pure-kernel dense-vs-flash A/B at the MXU-relevant shape:
            # the model-level rows dilute the attention core to ~25% of
            # block FLOPs at dim 512 (proj+MLP dominate), so "flash vs
            # dense" is sharpest timed on the cores alone - same
            # (B, H, T, D), same grad, only the attention fn differs
            def _attn_kernel_ab(seq_len=1024, d=128):
                import jax
                import jax.numpy as jnp

                from pytorch_distributed_rnn_tpu.ops.attention import (
                    mha_attention,
                )
                from pytorch_distributed_rnn_tpu.ops.pallas_attention import (  # noqa: E501
                    flash_attention,
                )

                rng = np.random.RandomState(0)
                q, k, v = (
                    jnp.asarray(
                        rng.randn(8, 8, seq_len, d).astype(np.float32)
                    ).astype(jnp.bfloat16)
                    for _ in range(3)
                )
                # fwd+bwd FLOPs of the two core matmuls (QK^T and PV),
                # 2 matmuls x 2*B*H*T^2*D, x3 for training
                flops = 3.0 * 2 * 2 * 8 * 8 * seq_len * seq_len * d
                out = {}
                for name, fn in (("dense", mha_attention),
                                 ("flash", flash_attention)):
                    # per-impl isolation (the row-family convention):
                    # a flash compile/OOM failure must not discard the
                    # dense timing already measured
                    try:
                        def f(q, k, v, _fn=fn):
                            return jnp.sum(
                                _fn(q, k, v).astype(jnp.float32))

                        step = jax.jit(jax.grad(f, argnums=(0, 1, 2)))
                        g = step(q, k, v)  # compile
                        float(jnp.sum(g[0].astype(jnp.float32)))
                        iters = 10
                        start = time.perf_counter()
                        for _ in range(iters):
                            g = step(q, k, v)
                        float(jnp.sum(g[0].astype(jnp.float32)))
                        dt = (time.perf_counter() - start) / iters
                        out[name] = {
                            "ms": round(dt * 1000, 3),
                            "core_mfu_vs_v5e_bf16_peak": round(
                                flops / dt / V5E_BF16_PEAK_FLOPS, 4),
                        }
                    except Exception as exc:  # noqa: BLE001 - keep other
                        out[name] = (
                            f"error: {type(exc).__name__}: {exc}"[:160])
                if all(isinstance(out.get(n), dict)
                       for n in ("dense", "flash")):
                    out["flash_speedup"] = round(
                        out["dense"]["ms"] / out["flash"]["ms"], 3)
                return out

            attempt("attention_kernel_ab_seq1024_d128",
                    lambda: _attn_kernel_ab(1024, 128), deep=True)
            attempt("attention_kernel_ab_seq2048_d128",
                    lambda: _attn_kernel_ab(2048, 128), deep=True)
            # LAST on purpose: the deliberately-failure-prone row (dense
            # O(T^2) scores at T=4096 may OOM or hang the remote compile
            # helper); everything measured before it is already on disk
            # via --append-rows if this one wedges the process
            attempt("attention_seq4096_dim512_dense_bf16",
                    lambda: _attn_row(4096, batch=8, steps=5,
                                      impl="dense", precision="bf16",
                                      dim=512, num_heads=4))
        else:
            # skip notes only for families the selected suite would
            # actually have measured on a TPU
            if rnn_rows:
                extras["char_rnn_50m"] = "skipped: no TPU"
            if attention_rows:
                extras["attention"] = "skipped: no TPU"

    payload = {
        "metric": "motion-LSTM train throughput (bs=1440, 1 chip)",
        "value": round(headline, 1),
        "unit": "seq/s",
        "vs_baseline": round(headline / BASELINE_SEQ_PER_SEC, 3),
        "data": "synthetic (random HAR-shaped arrays / random "
                "tokens; real UCI HAR absent in this image)",
        "backend": jax.default_backend(),
        "backend_note": (
            "ambient backend unavailable; fell back to cpu"
            if BACKEND_INFO["fallback"] else "ambient"
        ),
        "extra_metrics": extras,
    }
    if not on_tpu:
        # the capture-time backend is a fallback: carry the freshest
        # banked chip evidence so the driver artifact still tells the
        # chip story whatever the tunnel does today
        evidence = last_real_chip_evidence()
        if evidence is not None:
            payload["last_real_chip"] = evidence
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
