#!/usr/bin/env python
"""Headline benchmark: motion-LSTM training throughput (seq/sec).

Reproduces the reference's benchmark workload (BASELINE.md: UCI HAR motion
LSTM 2x32 + FC, 6912 train sequences of 128 steps x 9 features, 1 epoch,
Adam lr 0.0025, seed 123456789, no validation - sweep definition
``/root/reference/fabfile.py:48-66``) on whatever accelerator is attached,
and prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "seq/s", "vs_baseline": N}

``vs_baseline`` is measured against the reference re-run on this container
class's x86 CPU: 1931 seq/s at batch 1440 (BASELINE.md "Re-run baseline").

The timed region matches the reference's methodology (wall-clock around the
epoch loop, ``base.py:93-96``) but excludes one-time XLA compilation: a
warm-up epoch runs first (the reference's eager PyTorch has no compile
phase, so including ours would compare compilers, not training).
"""

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

from pytorch_distributed_rnn_tpu.utils import apply_platform_overrides

apply_platform_overrides()

import numpy as np

BASELINE_SEQ_PER_SEC = 1931.0  # reference local trainer, bs=1440, this host class
NUM_SEQUENCES = 6912
SEQ_LEN = 128
NUM_FEATURES = 9
BATCH_SIZE = 1440
SEED = 123456789


def main():
    from pytorch_distributed_rnn_tpu.data import MotionDataset
    from pytorch_distributed_rnn_tpu.data.synthetic import generate_har_arrays
    from pytorch_distributed_rnn_tpu.models import MotionModel
    from pytorch_distributed_rnn_tpu.training import Trainer

    X, y = generate_har_arrays(NUM_SEQUENCES, SEQ_LEN, NUM_FEATURES, seed=0)
    train_set = MotionDataset(X, y)

    model = MotionModel(input_dim=NUM_FEATURES, hidden_dim=32, layer_dim=2,
                        output_dim=6)
    trainer = Trainer(
        model, train_set, batch_size=BATCH_SIZE, learning_rate=0.0025, seed=SEED
    )

    trainer.train(epochs=1)  # warm-up: compile the 1-epoch program

    # reference methodology is 1-epoch wall-clock (base.py:93-96); repeat
    # 1-epoch runs so every timed run reuses the compiled epoch program
    epochs = 3
    start = time.perf_counter()
    for _ in range(epochs):
        trainer.train(epochs=1)
    duration = time.perf_counter() - start

    seq_per_sec = epochs * NUM_SEQUENCES / duration
    print(
        json.dumps(
            {
                "metric": "motion-LSTM train throughput (bs=1440, 1 chip)",
                "value": round(seq_per_sec, 1),
                "unit": "seq/s",
                "vs_baseline": round(seq_per_sec / BASELINE_SEQ_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
