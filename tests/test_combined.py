"""Composed dp x sp x tp training step: loss and gradients match the
single-device model; a real multi-step training run converges."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorch_distributed_rnn_tpu.models import AttentionClassifier
from pytorch_distributed_rnn_tpu.ops import cross_entropy_loss
from pytorch_distributed_rnn_tpu.parallel import make_mesh
from pytorch_distributed_rnn_tpu.parallel.combined import (
    make_3d_loss_fn,
    make_3d_train_step,
)

B, T, IN = 8, 32, 9


@pytest.fixture(scope="module")
def setup():
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    model = AttentionClassifier(input_dim=IN, dim=32, depth=2, num_heads=4,
                                output_dim=6, max_len=T)
    params = model.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (B, T, IN))
    y = jax.random.randint(jax.random.PRNGKey(2), (B,), 0, 6)
    return mesh, model, params, x, y


def test_3d_loss_matches_single_device(setup):
    mesh, model, params, x, y = setup
    loss_3d = jax.jit(make_3d_loss_fn(model, mesh))(params, x, y)
    loss_ref = cross_entropy_loss(model.apply(params, x), y)
    np.testing.assert_allclose(loss_3d, loss_ref, rtol=1e-5, atol=1e-6)


def test_3d_grads_match_single_device(setup):
    mesh, model, params, x, y = setup
    loss_fn = make_3d_loss_fn(model, mesh)
    g_3d = jax.jit(jax.grad(loss_fn))(params, x, y)

    def ref_loss(p):
        return cross_entropy_loss(model.apply(p, x), y)

    g_ref = jax.grad(ref_loss)(params)
    flat_3d, tree_3d = jax.tree.flatten(g_3d)
    flat_ref, tree_ref = jax.tree.flatten(g_ref)
    assert tree_3d == tree_ref
    for ga, gr in zip(flat_3d, flat_ref):
        np.testing.assert_allclose(ga, gr, rtol=5e-4, atol=1e-5)


def test_3d_training_converges(setup):
    mesh, model, params, x, y = setup
    opt = optax.adam(1e-3)
    step = make_3d_train_step(model, opt, mesh, donate=False)
    opt_state = opt.init(params)

    losses = []
    for _ in range(25):
        params, opt_state, loss = step(params, opt_state, (x, y))
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_3d_tp_indivisible_heads_raises(setup):
    mesh, _, params, x, y = setup
    # 3 heads divide dim (valid model) but do not shard over tp=2
    bad = AttentionClassifier(input_dim=IN, dim=30, depth=2, num_heads=3,
                              output_dim=6, max_len=T)
    with pytest.raises(ValueError, match="do not shard over tp"):
        jax.jit(make_3d_loss_fn(bad, mesh))(bad.init(jax.random.PRNGKey(3)),
                                            x, y)


class TestSpTpRnn:
    """The composed sp x tp RNN (gate-sharded cell inside the sp relay,
    r4 - VERDICT r3 item 6): parity vs the unsharded stack, both cells,
    plus the char-LM loss fn on the full dp x sp x tp mesh."""

    B, T, IN, H = 4, 16, 5, 8

    @pytest.mark.parametrize("cell", ["lstm", "gru"])
    def test_matches_unsharded_stack(self, cell):
        from functools import partial

        from pytorch_distributed_rnn_tpu.utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from pytorch_distributed_rnn_tpu.ops.rnn import (
            init_stacked_rnn,
            stacked_rnn,
        )
        from pytorch_distributed_rnn_tpu.parallel import make_mesh
        from pytorch_distributed_rnn_tpu.parallel.combined import (
            sp_tp_stacked_rnn,
        )

        mesh = make_mesh({"sp": 2, "tp": 2})
        params = init_stacked_rnn(jax.random.PRNGKey(0), self.IN, self.H,
                                  2, cell=cell)
        x = jax.random.normal(jax.random.PRNGKey(1),
                              (self.B, self.T, self.IN))

        @partial(shard_map, mesh=mesh, in_specs=(P(), P(None, "sp")),
                 out_specs=P(None, "sp", "tp"), check_vma=False)
        def run(p, x_loc):
            out_local, _ = sp_tp_stacked_rnn(p, x_loc, "sp", "tp",
                                             cell=cell)
            return out_local

        out = jax.jit(run)(params, x)
        ref, _ = stacked_rnn(params, x, cell, impl="scan")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("cell", ["lstm", "gru"])
    def test_grads_match_unsharded(self, cell):
        from functools import partial

        from jax import lax, shard_map
        from jax.sharding import PartitionSpec as P

        from pytorch_distributed_rnn_tpu.ops.rnn import (
            init_stacked_rnn,
            stacked_rnn,
        )
        from pytorch_distributed_rnn_tpu.parallel import make_mesh
        from pytorch_distributed_rnn_tpu.parallel.combined import (
            sp_tp_stacked_rnn,
        )

        mesh = make_mesh({"sp": 2, "tp": 2})
        params = init_stacked_rnn(jax.random.PRNGKey(2), self.IN, self.H,
                                  2, cell=cell)
        x = jax.random.normal(jax.random.PRNGKey(3),
                              (self.B, self.T, self.IN))

        def loss_sp(p):
            @partial(shard_map, mesh=mesh, in_specs=(P(), P(None, "sp")),
                     out_specs=P(), check_vma=False)
            def f(p, x_loc):
                out_local, _ = sp_tp_stacked_rnn(p, x_loc, "sp", "tp",
                                                 cell=cell)
                return lax.psum(
                    jnp.sum(out_local.astype(jnp.float32) ** 2),
                    ("sp", "tp"),
                )

            return f(p, x)

        g = jax.jit(jax.grad(loss_sp))(params)
        gr = jax.grad(
            lambda p: jnp.sum(stacked_rnn(p, x, cell, impl="scan")[0] ** 2)
        )(params)
        for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(g),
            jax.tree_util.tree_leaves_with_path(gr),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5,
                err_msg=jax.tree_util.keystr(pa),
            )

    def test_char_loss_fn_dp_sp_tp_matches_dp_only(self):
        from pytorch_distributed_rnn_tpu.models import CharRNN
        from pytorch_distributed_rnn_tpu.parallel import make_mesh
        from pytorch_distributed_rnn_tpu.parallel.strategy import (
            make_char_mesh_loss_fn,
        )

        lm = CharRNN(vocab_size=32, embed_dim=8, hidden_dim=8,
                     layer_dim=2, impl="scan")
        params = lm.init(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 32)
        y = jnp.zeros(8, jnp.int32)
        axes = {"dp": 2, "sp": 2, "tp": 2}
        loss_fn = make_char_mesh_loss_fn(make_mesh(axes), axes)
        (loss, _), grads = jax.jit(
            jax.value_and_grad(loss_fn, has_aux=True)
        )(params, toks, y)
        axes1 = {"dp": 8}
        loss_fn1 = make_char_mesh_loss_fn(make_mesh(axes1), axes1)
        (l1, _), g1 = jax.jit(
            jax.value_and_grad(loss_fn1, has_aux=True)
        )(params, toks, y)
        assert float(loss) == pytest.approx(float(l1), abs=1e-5)
        for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(grads),
            jax.tree_util.tree_leaves_with_path(g1),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5,
                err_msg=jax.tree_util.keystr(pa),
            )

    def test_bf16_remat_compose(self):
        """The composed pair takes the same levers as its parents: bf16
        output tracks the unsharded bf16 stack; remat is exact."""
        from functools import partial

        from pytorch_distributed_rnn_tpu.utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from pytorch_distributed_rnn_tpu.ops.rnn import (
            init_stacked_rnn,
            stacked_rnn,
        )
        from pytorch_distributed_rnn_tpu.parallel import make_mesh
        from pytorch_distributed_rnn_tpu.parallel.combined import (
            sp_tp_stacked_rnn,
        )

        mesh = make_mesh({"sp": 2, "tp": 2})
        params = init_stacked_rnn(jax.random.PRNGKey(4), self.IN, self.H, 2)
        x = jax.random.normal(jax.random.PRNGKey(5),
                              (self.B, self.T, self.IN))

        @partial(shard_map, mesh=mesh, in_specs=(P(), P(None, "sp")),
                 out_specs=P(None, "sp", "tp"), check_vma=False)
        def run(p, x_loc):
            out_local, _ = sp_tp_stacked_rnn(
                p, x_loc, "sp", "tp", compute_dtype=jnp.bfloat16,
                remat=True,
            )
            return out_local.astype(jnp.float32)

        out = jax.jit(run)(params, x)
        ref, _ = stacked_rnn(params, x, "lstm", impl="scan",
                             compute_dtype=jnp.bfloat16)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref, np.float32),
            rtol=3e-2, atol=3e-2,
        )
