"""Composed dp x sp x tp training step: loss and gradients match the
single-device model; a real multi-step training run converges."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorch_distributed_rnn_tpu.models import AttentionClassifier
from pytorch_distributed_rnn_tpu.ops import cross_entropy_loss
from pytorch_distributed_rnn_tpu.parallel import make_mesh
from pytorch_distributed_rnn_tpu.parallel.combined import (
    make_3d_loss_fn,
    make_3d_train_step,
)

B, T, IN = 8, 32, 9


@pytest.fixture(scope="module")
def setup():
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    model = AttentionClassifier(input_dim=IN, dim=32, depth=2, num_heads=4,
                                output_dim=6, max_len=T)
    params = model.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (B, T, IN))
    y = jax.random.randint(jax.random.PRNGKey(2), (B,), 0, 6)
    return mesh, model, params, x, y


def test_3d_loss_matches_single_device(setup):
    mesh, model, params, x, y = setup
    loss_3d = jax.jit(make_3d_loss_fn(model, mesh))(params, x, y)
    loss_ref = cross_entropy_loss(model.apply(params, x), y)
    np.testing.assert_allclose(loss_3d, loss_ref, rtol=1e-5, atol=1e-6)


def test_3d_grads_match_single_device(setup):
    mesh, model, params, x, y = setup
    loss_fn = make_3d_loss_fn(model, mesh)
    g_3d = jax.jit(jax.grad(loss_fn))(params, x, y)

    def ref_loss(p):
        return cross_entropy_loss(model.apply(p, x), y)

    g_ref = jax.grad(ref_loss)(params)
    flat_3d, tree_3d = jax.tree.flatten(g_3d)
    flat_ref, tree_ref = jax.tree.flatten(g_ref)
    assert tree_3d == tree_ref
    for ga, gr in zip(flat_3d, flat_ref):
        np.testing.assert_allclose(ga, gr, rtol=5e-4, atol=1e-5)


def test_3d_training_converges(setup):
    mesh, model, params, x, y = setup
    opt = optax.adam(1e-3)
    step = make_3d_train_step(model, opt, mesh, donate=False)
    opt_state = opt.init(params)

    losses = []
    for _ in range(25):
        params, opt_state, loss = step(params, opt_state, (x, y))
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_3d_tp_indivisible_heads_raises(setup):
    mesh, _, params, x, y = setup
    # 3 heads divide dim (valid model) but do not shard over tp=2
    bad = AttentionClassifier(input_dim=IN, dim=30, depth=2, num_heads=3,
                              output_dim=6, max_len=T)
    with pytest.raises(ValueError, match="do not shard over tp"):
        jax.jit(make_3d_loss_fn(bad, mesh))(bad.init(jax.random.PRNGKey(3)),
                                            x, y)
