"""TraceContext unit contract: minting, forking, wire round trips,
hostile wire input, deterministic head sampling, and the minted-counter
hook the zero-overhead pins read.  Pure python - no jax, no sockets."""

import json

from pytorch_distributed_rnn_tpu.obs.tracectx import (
    TraceContext,
    should_sample,
)


class TestMintAndChild:
    def test_mint_is_a_root_with_distinct_ids(self):
        a = TraceContext.mint()
        b = TraceContext.mint()
        assert a.trace_id != b.trace_id
        assert a.parent_id is None
        assert len(a.trace_id) == 16 and len(a.span_id) == 8

    def test_mint_drops_none_baggage(self):
        ctx = TraceContext.mint(qos="high", deadline=None)
        assert ctx.baggage == {"qos": "high"}

    def test_child_keeps_trace_forks_span_inherits_baggage(self):
        root = TraceContext.mint(qos="low")
        child = root.child()
        assert child.trace_id == root.trace_id
        assert child.span_id != root.span_id
        assert child.parent_id == root.span_id
        assert child.baggage == {"qos": "low"}
        # grandchild chains causality one more hop
        grand = child.child()
        assert grand.parent_id == child.span_id
        assert grand.trace_id == root.trace_id

    def test_minted_counter_moves_once_per_construction(self):
        before = TraceContext.minted
        ctx = TraceContext.mint()
        ctx.child()
        assert TraceContext.minted == before + 2


class TestWire:
    def test_round_trip_preserves_identity_and_baggage(self):
        root = TraceContext.mint(qos="high")
        child = root.child()
        wire = json.loads(json.dumps(child.to_wire()))  # a real hop
        back = TraceContext.from_wire(wire)
        assert back is not None
        assert back.trace_id == child.trace_id
        assert back.span_id == child.span_id
        assert back.parent_id == child.parent_id
        assert back.baggage == {"qos": "high"}

    def test_root_wire_has_no_parent_key(self):
        wire = TraceContext.mint().to_wire()
        assert "parent" not in wire
        assert set(wire) == {"id", "span"}

    def test_malformed_wire_is_none_never_a_raise(self):
        for hostile in (
            None,
            "abc",
            17,
            [],
            {},
            {"id": "t"},  # no span
            {"span": "s"},  # no trace id
            {"id": "", "span": "s"},  # empty trace id
            {"id": "t", "span": ""},  # empty span id
            {"id": 7, "span": "s"},  # non-string ids
            {"id": "t", "span": "s", "parent": 9},  # non-string parent
        ):
            assert TraceContext.from_wire(hostile) is None, hostile

    def test_non_json_scalar_baggage_is_filtered(self):
        back = TraceContext.from_wire({
            "id": "t", "span": "s", "qos": "high",
            "evil": {"nested": 1}, "list": [1, 2],
        })
        assert back is not None
        assert back.baggage == {"qos": "high"}


class TestShouldSample:
    def test_rate_bounds(self):
        assert not any(should_sample(i, 0.0) for i in range(1, 50))
        assert all(should_sample(i, 1.0) for i in range(1, 50))
        assert not any(should_sample(i, -1.0) for i in range(1, 50))
        assert all(should_sample(i, 2.0) for i in range(1, 50))

    def test_fractional_rate_is_evenly_spaced_and_exact(self):
        picks = [i for i in range(1, 101) if should_sample(i, 0.25)]
        assert len(picks) == 25
        # evenly spread: one pick per window of 4
        gaps = [b - a for a, b in zip(picks, picks[1:])]
        assert set(gaps) == {4}

    def test_deterministic_no_rng(self):
        a = [should_sample(i, 0.1) for i in range(1, 200)]
        b = [should_sample(i, 0.1) for i in range(1, 200)]
        assert a == b
        # of the first n seqs, floor(n * rate) are sampled
        assert sum(a) == 19
