"""Socket-level serving tests: a REAL trained checkpoint behind the TCP
JSONL server, concurrent mixed traffic matching single-request reference
decodes, streaming, overload shedding, and the chaos SLO drill
(subprocess server + load generator + degradation window)."""

import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorch_distributed_rnn_tpu.data.synthetic import generate_char_tokens
from pytorch_distributed_rnn_tpu.models import CharRNN
from pytorch_distributed_rnn_tpu.obs.recorder import (
    NULL_RECORDER,
    MetricsRecorder,
)
from pytorch_distributed_rnn_tpu.obs.summary import summarize_file
from pytorch_distributed_rnn_tpu.serving.adapters import adapter_for
from pytorch_distributed_rnn_tpu.serving.buckets import BucketSpec
from pytorch_distributed_rnn_tpu.serving.engine import ServingEngine
from pytorch_distributed_rnn_tpu.serving.protocol import ServingClient
from pytorch_distributed_rnn_tpu.serving.server import ServingServer
from pytorch_distributed_rnn_tpu.training.checkpoint import (
    CheckpointCorruptError,
    load_model_params,
    save_checkpoint,
)

MODEL = CharRNN(vocab_size=256, embed_dim=24, hidden_dim=24, layer_dim=2,
                impl="scan")


@pytest.fixture(scope="module")
def trained_checkpoint(tmp_path_factory):
    """A real checkpoint: the char LM actually trained a few steps on
    the synthetic motif stream, written through the crash-safe
    checkpoint path the trainers use."""
    params = MODEL.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        generate_char_tokens(32, 33, vocab_size=256, seed=0))
    opt = optax.adam(5e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(p, s):
        loss, grads = jax.value_and_grad(MODEL.loss)(p, tokens)
        updates, s = opt.update(grads, s, p)
        return optax.apply_updates(p, updates), s, loss

    loss = None
    for _ in range(25):
        params, opt_state, loss = step(params, opt_state)
    ckpt_dir = tmp_path_factory.mktemp("serve-ckpt")
    path = save_checkpoint(ckpt_dir, 0, params, opt_state, float(loss))
    return path, params


def make_server(params, metrics_path=None, **engine_kwargs):
    recorder = (
        MetricsRecorder(metrics_path, sample_every=4, heartbeat_every_s=0.0)
        if metrics_path is not None else None
    )
    defaults = dict(num_slots=6, bucket_spec=BucketSpec((8, 16)),
                    max_new_tokens=16, max_queue=64)
    defaults.update(engine_kwargs)
    engine = ServingEngine(
        adapter_for(MODEL), params,
        recorder=recorder if recorder is not None else NULL_RECORDER,
        **defaults,
    )
    engine.warmup()
    server = ServingServer(engine, model_name="char", recorder=recorder)
    return server


# ---------------------------------------------------------------------------
# checkpoint -> serving params


def test_load_model_params_round_trips_without_opt_state(
        trained_checkpoint, tmp_path):
    path, params = trained_checkpoint
    template = MODEL.init(jax.random.PRNGKey(42))
    loaded, meta = load_model_params(path, template)
    assert meta["epoch"] == 1
    for a, b in zip(jax.tree.leaves(loaded), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # a truncated file is rejected, not half-loaded
    clipped = tmp_path / "clipped.ckpt"
    clipped.write_bytes(path.read_bytes()[:-20])
    with pytest.raises(CheckpointCorruptError):
        load_model_params(clipped, template)


# ---------------------------------------------------------------------------
# the end-to-end drill (acceptance): real checkpoint, >= 50 concurrent
# mixed-length requests, responses match reference decodes, telemetry
# summarizes


def test_e2e_50_concurrent_requests_match_reference(
        trained_checkpoint, tmp_path):
    path, _ = trained_checkpoint
    params, _meta = load_model_params(
        path, MODEL.init(jax.random.PRNGKey(7)))
    # load_model_params returns host arrays (the checkpoint-module
    # convention: placement is the caller's choice); the eager
    # reference decodes below need device arrays
    params = jax.tree.map(jnp.asarray, params)
    metrics = tmp_path / "serve-metrics.jsonl"
    rng = np.random.RandomState(0)
    specs = []
    for i in range(50):
        specs.append({
            "prompt": rng.randint(0, 256, size=rng.randint(1, 13)).tolist(),
            "max_new_tokens": int([4, 8][i % 2]),
            "temperature": [0.0, 0.9][i % 2],
            "seed": 5000 + i,
        })
    replies = [None] * len(specs)

    with make_server(params, metrics_path=metrics) as server:
        def fire(i):
            with ServingClient(server.host, server.port) as client:
                replies[i] = client.generate(request_id=str(i), **specs[i])

        threads = [
            threading.Thread(target=fire, args=(i,), daemon=True)
            for i in range(len(specs))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
        stats = server.engine.stats()

    assert all(r is not None for r in replies), "requests timed out"
    for i, (spec, reply) in enumerate(zip(specs, replies)):
        assert reply["event"] == "done", (i, reply)
        ref = MODEL.generate(
            params, jnp.asarray([spec["prompt"]], jnp.int32),
            spec["max_new_tokens"], key=jax.random.PRNGKey(spec["seed"]),
            temperature=spec["temperature"],
        )
        expected = np.asarray(ref)[0, len(spec["prompt"]):].tolist()
        assert reply["tokens"] == expected, (
            f"request {i} diverged from its reference decode"
        )
        assert reply["latency_ms"] >= 0
        assert reply["ttft_ms"] is not None

    assert stats["requests"] == 50
    assert stats["requests_shed"] == 0

    # obs sidecar: p50/p95 latency + queue depth via pdrnn-metrics
    # summarize, with zero serving-specific analysis code
    summary = summarize_file(metrics)
    assert summary["requests"] == 50
    assert summary["latency_s_p50"] > 0
    assert summary["latency_s_p95"] >= summary["latency_s_p50"]
    assert summary["queue_depth_max"] >= 0
    assert summary["tokens_per_s"] > 0


# ---------------------------------------------------------------------------
# protocol behaviors


def test_streaming_tokens_arrive_in_order(trained_checkpoint):
    _, params = trained_checkpoint
    with make_server(params) as server:
        streamed = []
        with ServingClient(server.host, server.port) as client:
            reply = client.generate(
                prompt=[1, 2, 3], max_new_tokens=6, temperature=0.0,
                stream=True,
                on_token=lambda idx, tok: streamed.append((idx, tok)),
            )
        assert reply["event"] == "done"
        assert [idx for idx, _ in streamed] == list(range(6))
        assert [tok for _, tok in streamed] == reply["tokens"]


def test_text_prompt_round_trip(trained_checkpoint):
    _, params = trained_checkpoint
    with make_server(params) as server:
        with ServingClient(server.host, server.port) as client:
            reply = client.generate(text="hello", max_new_tokens=4,
                                    temperature=0.0)
        assert reply["event"] == "done"
        assert len(reply["tokens"]) == 4
        assert isinstance(reply["text"], str) and len(reply["text"]) == 4


def test_ping_stats_and_bad_requests(trained_checkpoint):
    _, params = trained_checkpoint
    with make_server(params) as server:
        with ServingClient(server.host, server.port) as client:
            pong = client.ping()
            assert pong["vocab_size"] == 256
            assert pong["slots"] == 6
            assert pong["prompt_buckets"] == [8, 16]

            reply = client.request({"op": "nope"})
            assert reply["event"] == "error"
            assert "unknown op" in reply["error"]

            reply = client.generate(prompt=[999], max_new_tokens=2)
            assert reply["event"] == "error"
            assert "prompt ids" in reply["error"]

            reply = client.generate(prompt=list(range(20)),
                                    max_new_tokens=2)
            assert reply["event"] == "error"
            assert "bucket" in reply["error"]

            # a bigint seed must be rejected at submit time, not crash
            # the engine thread at PRNGKey time (remote DoS otherwise)
            reply = client.generate(prompt=[1], max_new_tokens=2,
                                    seed=2 ** 64)
            assert reply["event"] == "error"
            assert "seed" in reply["error"]
            # the engine is still alive and serving
            reply = client.generate(prompt=[1], max_new_tokens=2)
            assert reply["event"] == "done"

            client.sock.sendall(b"not json\n")
            bad = client._recv()
            assert bad["event"] == "error"

            stats = client.stats()
            assert stats["event"] == "stats"
            assert "tokens_out" in stats


def test_overload_sheds_with_explicit_error(trained_checkpoint):
    """A pipelined burst far past slots + queue depth is answered with
    explicit shed errors - tail-drop admission, never a hang or crash -
    while the admitted requests complete normally."""
    import socket

    from pytorch_distributed_rnn_tpu.serving.protocol import (
        decode_line,
        encode_line,
    )

    _, params = trained_checkpoint
    with make_server(params, num_slots=1, max_queue=2) as server:
        sock = socket.create_connection((server.host, server.port),
                                        timeout=60.0)
        rfile = sock.makefile("r", encoding="utf-8")
        burst = 12
        for i in range(burst):
            sock.sendall(encode_line({
                "op": "generate", "id": str(i), "prompt": [1, 2],
                "max_new_tokens": 16, "temperature": 0.0,
            }))
        done = shed = 0
        while done + shed < burst:
            reply = decode_line(rfile.readline())
            if reply["event"] == "done":
                done += 1
            else:
                assert reply.get("shed") is True, reply
                shed += 1
        sock.close()
    assert shed > 0, "burst past slots+queue must shed"
    assert done >= 1  # admitted requests still complete


# ---------------------------------------------------------------------------
# the chaos SLO drill (subprocess server under a stall fault)


@pytest.mark.chaos
def test_slo_drill_under_stall_fault(trained_checkpoint, tmp_path):
    """The ISSUE's SLO drill: a subprocess `pdrnn-serve` with a stall
    fault injected stays UP, sheds/queues load through the stall, shows
    the degradation window in the report, recovers after it, and shuts
    down cleanly on SIGTERM."""
    path, params = trained_checkpoint
    from pytorch_distributed_rnn_tpu.serving.drill import run_drill
    from pytorch_distributed_rnn_tpu.serving.loadgen import LoadConfig

    metrics = tmp_path / "drill-metrics.jsonl"
    report, exit_code = run_drill(
        [
            "--checkpoint", str(path), "--model", "char",
            "--vocab-size", "256", "--hidden-units", "24",
            "--stacked-layer", "2", "--slots", "4",
            "--prompt-buckets", "8,16", "--max-new-tokens", "16",
            "--max-queue", "8", "--faults", "step:40:stall:1.5",
            "--metrics", str(metrics),
        ],
        LoadConfig(requests=60, rate=25.0, prompt_len_max=14,
                   new_tokens_min=4, new_tokens_max=10, temperature=0.8,
                   seed=3, slo_p95_ms=400.0, timeout_s=120.0),
    )
    # the server survived the fault and exited cleanly on SIGTERM
    assert exit_code == 0
    assert report["server_exit"] == 0
    # traffic was served; overload was shed, not crashed
    assert report["done"] > 0
    assert report["errors"] == 0, report["error_samples"]
    assert report["done"] + report["shed"] == 60
    # the drill report shows the degradation window...
    assert report["degraded_seconds"], (
        "stall fault produced no degradation window"
    )
    window = report["degradation_window_s"]
    assert window is not None
    # ...and recovery: the run does not END degraded (requests complete
    # after the stall at healthy latency)
    last_second = report["timeline"][-1]["second"]
    assert window[1] <= last_second
    # the chaos fault landed in the server's telemetry sidecar
    text = metrics.read_text()
    assert '"kind": "fault"' in text
    assert summarize_file(metrics)["requests"] == report["done"]


# ---------------------------------------------------------------------------
# request-id minting (the old default made every default-arg request
# the SAME request "0") + distributed tracing through the engine


def test_default_request_ids_are_unique_within_and_across_clients(
        trained_checkpoint):
    _, params = trained_checkpoint
    with make_server(params) as server:
        with ServingClient(server.host, server.port) as a, \
                ServingClient(server.host, server.port) as b:
            replies = [
                a.generate(prompt=[1], max_new_tokens=2),
                a.generate(prompt=[1], max_new_tokens=2),
                b.generate(prompt=[1], max_new_tokens=2),
            ]
    assert all(r["event"] == "done" for r in replies)
    ids = [r["id"] for r in replies]
    assert len(set(ids)) == 3, f"request ids collided: {ids}"
    assert "0" not in ids  # the old colliding default
    # explicit ids still pass through verbatim
    with make_server(params) as server:
        with ServingClient(server.host, server.port) as client:
            reply = client.generate(prompt=[1], max_new_tokens=2,
                                    request_id="mine")
    assert reply["id"] == "mine"


def test_traced_request_assembles_into_engine_lifecycle_tree(
        trained_checkpoint, tmp_path):
    """A client-minted context rides the wire, the engine emits
    queue_wait/prefill/decode spans under it, and the sidecar ALONE
    re-assembles into a validator-clean tree rooted at the client's
    (unrecorded) edge span."""
    from pytorch_distributed_rnn_tpu.obs.trace import (
        assemble_traces,
        validate_trace_tree,
    )
    from pytorch_distributed_rnn_tpu.obs.tracectx import TraceContext

    _, params = trained_checkpoint
    metrics = tmp_path / "traced.jsonl"
    ctx = TraceContext.mint(qos="high")
    with make_server(params, metrics_path=metrics) as server:
        with ServingClient(server.host, server.port) as client:
            reply = client.generate(prompt=[1, 2, 3], max_new_tokens=4,
                                    request_id="tr1", trace=ctx,
                                    stream=True)
    assert reply["event"] == "done"
    trees = assemble_traces([metrics], request=ctx.trace_id)
    assert len(trees) == 1
    tree = trees[0]
    validate_trace_tree(tree)
    assert tree.request == "tr1"
    # the engine phases are all siblings under the client's edge span,
    # which no sidecar recorded - the assembler synthesizes it
    assert tree.root.name == "request"
    assert tree.root.span_id == ctx.span_id
    names = {n.name for n in tree.root.walk()}
    assert {"queue_wait", "prefill", "decode", "stream_emit"} <= names
    fractions = tree.critical_path()
    assert sum(fractions.values()) == 1.0


def test_tracing_off_is_pinned_zero_overhead(trained_checkpoint,
                                             tmp_path):
    """The zero-overhead contract, pinned three ways: an untraced
    request constructs no TraceContext anywhere in the process, its
    wire request is byte-identical to the pre-tracing protocol, and a
    TRACED request leaves the engine's step jaxpr cache untouched (the
    context never reaches jit)."""
    from pytorch_distributed_rnn_tpu.serving.protocol import (
        build_generate_request,
        encode_line,
    )
    from pytorch_distributed_rnn_tpu.obs.tracectx import TraceContext

    # wire pin: trace=None adds NO key - the exact pre-tracing bytes
    req = build_generate_request([1, 2], request_id="w",
                                 max_new_tokens=2)
    assert set(req) == {"op", "id", "max_new_tokens", "temperature",
                        "stream", "prompt"}
    untraced_bytes = encode_line(req)
    traced = build_generate_request([1, 2], request_id="w",
                                    max_new_tokens=2,
                                    trace=TraceContext.mint())
    assert set(traced) - set(req) == {"trace"}

    _, params = trained_checkpoint
    metrics = tmp_path / "zero.jsonl"
    with make_server(params, metrics_path=metrics) as server:
        engine = server.engine
        caches = lambda: (engine._prefill._cache_size(),
                          engine._join._cache_size(),
                          engine._step._cache_size())
        warm = caches()
        before = TraceContext.minted
        with ServingClient(server.host, server.port) as client:
            reply = client.generate(prompt=[5, 6], max_new_tokens=3)
            assert reply["event"] == "done"
            # no context allocated server-side for an untraced request
            assert TraceContext.minted == before
            assert caches() == warm
            # a traced request reuses the SAME compiled programs
            reply = client.generate(prompt=[5, 6], max_new_tokens=3,
                                    trace=TraceContext.mint())
            assert reply["event"] == "done"
            assert caches() == warm
    # the untraced request's bytes were pinned above; double-check the
    # constant stayed stable across the server round trip
    assert encode_line(build_generate_request(
        [1, 2], request_id="w", max_new_tokens=2)) == untraced_bytes
